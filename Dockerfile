# Container image for the TPU-native framework (ref reference Dockerfile:
# the reference bundles Spark + PIO; here the runtime is Python + JAX).
# For TPU hosts, swap the base image for one with libtpu and run with the
# TPU device plugin; on CPU this image serves the event/query/admin planes
# and runs tests.
FROM python:3.12-slim

RUN apt-get update \
 && apt-get install -y --no-install-recommends g++ curl \
 && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/pio
COPY pyproject.toml README.md ./
COPY predictionio_tpu ./predictionio_tpu
COPY native ./native
COPY conf ./conf
COPY pio ./pio

RUN pip install --no-cache-dir . flax optax

ENV PIO_FS_BASEDIR=/var/lib/pio
VOLUME /var/lib/pio

# event server 7070, engine server 8000, admin 7071, dashboard 9000
EXPOSE 7070 8000 7071 9000
ENTRYPOINT ["./pio"]
CMD ["eventserver", "--ip", "0.0.0.0"]
