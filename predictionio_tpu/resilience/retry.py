"""Retry with exponential backoff + jitter, bounded by a per-process budget.

Two safeguards production retry loops need and ad-hoc ``for attempt in
range(3)`` loops lack:

- **Transience classification.** Only errors that can plausibly succeed on
  replay are retried. Backends mark their error types with a ``transient``
  attribute (connection failures, 5xx) — everything else (4xx, schema
  errors, ``DeadlineExceeded``) fails fast.
- **A retry budget.** Under a full outage every request retrying N times
  multiplies offered load by N exactly when the backend can least afford
  it. The token-bucket budget earns fractional tokens from first attempts
  and spends one per retry, so steady-state retries are capped at
  ``ratio`` of traffic and a dying backend sees load *drop*, not triple.

``sleep``/``rng`` are injectable so tests assert exact backoff sequences
without real sleeping.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Any, Callable

from predictionio_tpu.resilience.deadline import Deadline

# error types that are transient by nature, no marking needed
_TRANSIENT_TYPES = (ConnectionError, InterruptedError)

# HTTP statuses worth replaying: server-side trouble a fresh attempt
# (possibly against a recovered node) can clear. Shared by every
# HTTP-transport backend so the classification lives in one place.
TRANSIENT_HTTP_STATUSES = (500, 502, 503, 504)


def mark_transient(exc: BaseException) -> BaseException:
    """Tag an exception as replay-safe for ``is_transient`` and return it
    (``raise mark_transient(SomeError(...)) from exc``)."""
    exc.transient = True
    return exc


def is_transient(exc: BaseException) -> bool:
    """May this error succeed on replay?

    An explicit ``transient`` attribute on the exception (or its class)
    wins in both directions; otherwise connection-level errors are
    transient and everything else is not. ``TimeoutError`` is *not*
    blanket-transient: ``DeadlineExceeded`` subclasses it and must never
    be retried (it sets ``transient = False`` explicitly; a backend whose
    timeouts are worth retrying marks its own error type).
    """
    marked = getattr(exc, "transient", None)
    if marked is not None:
        return bool(marked)
    return isinstance(exc, _TRANSIENT_TYPES)


class RetryBudget:
    """Per-process token bucket shared by every call site of one policy.

    Each first attempt deposits ``ratio`` tokens (capped at ``max_tokens``);
    each retry withdraws 1. ``min_tokens`` pre-funds the bucket so a cold
    process can still retry its first few failures.
    """

    def __init__(
        self, ratio: float = 0.1, max_tokens: float = 100.0, min_tokens: float = 10.0
    ):
        self.ratio = ratio
        self.max_tokens = max_tokens
        self._tokens = min(min_tokens, max_tokens)
        self._lock = threading.Lock()

    def record_attempt(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff with full jitter (attempt k sleeps a uniform
    draw from ``[base * mult**k * (1 - jitter), base * mult**k]``, capped
    at ``backoff_max_s``)."""

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 2.0
    jitter: float = 0.5  # fraction of the computed backoff randomized away
    retry_on: Callable[[BaseException], bool] = is_transient
    budget: RetryBudget | None = None
    sleep: Callable[[float], None] = time.sleep
    rng: Callable[[], float] = random.random  # uniform [0, 1)
    # observability: invoked once per retry DECISION (after budget spend,
    # before backoff) with the error being retried — the servers wire this
    # to a `pio_storage_retries_total`-style counter. Monitoring only; a
    # raising hook is swallowed. `retries_attempted` mirrors the same count
    # for /healthz snapshots without requiring a hook.
    on_retry: Callable[[BaseException], None] | None = None
    retries_attempted: int = 0

    def backoff_s(self, retry_index: int) -> float:
        """Sleep before the (retry_index+1)-th retry (retry_index from 0)."""
        raw = min(
            self.backoff_max_s,
            self.backoff_base_s * self.backoff_multiplier**retry_index,
        )
        return raw * (1.0 - self.jitter * self.rng())

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        deadline: Deadline | None = None,
        **kwargs: Any,
    ) -> Any:
        """Run ``fn`` with retries. The underlying error always propagates
        unchanged — on exhaustion too, so existing ``except SomeBackendError``
        clauses (and error attributes like the ES driver's ``indexed_ids``)
        keep working whether or not a policy wraps the call."""
        if self.budget is not None:
            self.budget.record_attempt()
        attempts = 0
        while True:
            if deadline is not None:
                deadline.check("retryable call")
            attempts += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as exc:
                if not self.retry_on(exc):
                    raise
                if attempts >= self.max_attempts:
                    raise  # out of attempts
                if self.budget is not None and not self.budget.try_spend():
                    raise  # budget empty: shed the retry, surface the error
                self.retries_attempted += 1
                if self.on_retry is not None:
                    try:
                        self.on_retry(exc)
                    except Exception:
                        pass  # monitoring must never break the retry loop
                pause = self.backoff_s(attempts - 1)
                if deadline is not None:
                    rem = deadline.remaining()
                    if rem is not None and pause >= rem:
                        raise  # the backoff alone would blow the deadline
                if pause > 0:
                    self.sleep(pause)
