"""Resilience policy library: deadlines, retry/backoff with a budget,
circuit breakers, and fault injection.

One shared vocabulary for every layer that talks to the outside world —
the query server's request path, the event server's storage path, and the
s3/sql/hdfs/localfs/elasticsearch backends. See ``docs/resilience.md`` for
semantics and tuning guidance.
"""

from predictionio_tpu.resilience.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    CircuitOpenError,
)
from predictionio_tpu.resilience.deadline import Deadline, DeadlineExceeded
from predictionio_tpu.resilience.fault import FaultInjector, FaultSpec, InjectedFault
from predictionio_tpu.resilience.retry import (
    TRANSIENT_HTTP_STATUSES,
    RetryBudget,
    RetryPolicy,
    is_transient,
    mark_transient,
)
from predictionio_tpu.resilience.wrappers import (
    ResiliencePolicy,
    ResilientProxy,
    wrap_dao,
)

__all__ = [
    "CLOSED",
    "HALF_OPEN",
    "OPEN",
    "CircuitBreaker",
    "CircuitOpenError",
    "Deadline",
    "DeadlineExceeded",
    "FaultInjector",
    "FaultSpec",
    "InjectedFault",
    "ResiliencePolicy",
    "ResilientProxy",
    "RetryBudget",
    "RetryPolicy",
    "TRANSIENT_HTTP_STATUSES",
    "is_transient",
    "mark_transient",
    "wrap_dao",
]
