"""Composition: retry x breaker x deadline as one policy object, plus a
DAO proxy that applies the policy to every method of a storage object.

Layering (outermost first): the retry loop drives attempts; every attempt
is gated by the breaker and individually counted by it. ``CircuitOpenError``
is non-transient, so the instant the breaker trips the retry loop stops —
an open circuit must not be retried into.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from predictionio_tpu.resilience.breaker import CircuitBreaker
from predictionio_tpu.resilience.deadline import Deadline
from predictionio_tpu.resilience.retry import RetryPolicy


@dataclasses.dataclass
class ResiliencePolicy:
    """One dependency's full policy: retries (with backoff/budget) around
    breaker-gated attempts, all inside an optional deadline."""

    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    breaker: CircuitBreaker | None = None

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        deadline: Deadline | None = None,
        **kwargs: Any,
    ) -> Any:
        if self.breaker is None:
            return self.retry.call(fn, *args, deadline=deadline, **kwargs)
        breaker = self.breaker
        # only errors the retry policy classifies as transient (dependency
        # trouble) count against the breaker: a poison request that fails
        # deterministically must not open the circuit for everyone else
        classify = self.retry.retry_on

        def attempt() -> Any:
            return breaker.call(fn, *args, counts_as_failure=classify, **kwargs)

        return self.retry.call(attempt, deadline=deadline)

    def snapshot(self) -> dict[str, Any]:
        return {
            "breaker": self.breaker.snapshot() if self.breaker else None,
            "retryBudgetTokens": (
                self.retry.budget.tokens if self.retry.budget else None
            ),
            "retriesAttempted": self.retry.retries_attempted,
        }


class ResilientProxy:
    """Every method call on the wrapped object runs through the policy.

    ``exempt`` methods (e.g. ``close``) bypass it: a shutdown call must not
    be blocked by an open breaker or retried against a dying backend.
    """

    def __init__(
        self,
        target: Any,
        policy: ResiliencePolicy,
        exempt: tuple[str, ...] = ("close",),
    ):
        object.__setattr__(self, "_target", target)
        object.__setattr__(self, "_policy", policy)
        object.__setattr__(self, "_exempt", exempt)

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._target, name)
        if not callable(attr) or name in self._exempt or name.startswith("_"):
            return attr
        policy = self._policy

        def wrapper(*args: Any, **kwargs: Any) -> Any:
            return policy.call(attr, *args, **kwargs)

        wrapper.__name__ = getattr(attr, "__name__", name)
        return wrapper

    def __setattr__(self, name: str, value: Any) -> None:
        setattr(self._target, name, value)

    def __repr__(self) -> str:
        return f"ResilientProxy({self._target!r})"


def wrap_dao(
    dao: Any,
    policy: ResiliencePolicy,
    exempt: tuple[str, ...] = ("close",),
) -> ResilientProxy:
    """Policy-wrap a storage DAO (LEvents, Models, ...). Iterator-returning
    scans get retry protection only on the *call* that builds the iterator;
    mid-stream failures surface unretried (a half-consumed scan cannot be
    safely replayed here)."""
    return ResilientProxy(dao, policy, exempt=exempt)
