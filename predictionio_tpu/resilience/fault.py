"""Fault injection: wrap any object and make its methods fail on demand.

The chaos harness's only moving part. ``FaultInjector(target)`` proxies
every attribute of ``target``; ``inject(...)`` arms faults that matching
method calls then experience — an exception (for the next N calls or at a
probability), added latency, or a hang — before (or instead of) delegating
to the real implementation. Wrap a storage DAO to simulate a flaky
database, an HTTP transport to simulate a dead collector, an algorithm to
simulate a wedged device.

Injected errors are ``InjectedFault`` (a ``ConnectionError``, transient by
nature) unless the spec supplies its own exception factory, so retry
policies classify them exactly like real connection failures.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Any, Callable


class InjectedFault(ConnectionError):
    """A fault produced by ``FaultInjector`` (transient, like the real
    connection failures it stands in for)."""

    transient = True


@dataclasses.dataclass
class FaultSpec:
    """One armed fault. ``methods=None`` matches every method call."""

    methods: tuple[str, ...] | None = None
    fail_count: int = 0  # fail this many matching calls, then disarm
    fail_rate: float = 0.0  # else fail each matching call with this prob.
    exception: Callable[[str], BaseException] = lambda m: InjectedFault(
        f"injected fault in {m}"
    )
    latency_s: float = 0.0  # sleep before every matching call (even passing)
    hang_s: float = 0.0  # sleep before *failing* calls (simulates a stall)

    def matches(self, method: str) -> bool:
        return self.methods is None or method in self.methods


class FaultInjector:
    """Transparent proxy over ``target`` with armable faults.

    Non-callable attributes pass straight through; method calls consult the
    armed specs first. Counters (``calls``, ``faults``) let tests assert
    how much real work reached the target vs. was intercepted.
    """

    def __init__(self, target: Any, rng: Callable[[], float] = random.random):
        # avoid __setattr__ recursion via object.__setattr__
        object.__setattr__(self, "_target", target)
        object.__setattr__(self, "_specs", [])
        object.__setattr__(self, "_lock", threading.Lock())
        object.__setattr__(self, "_rng", rng)
        object.__setattr__(self, "calls", 0)
        object.__setattr__(self, "faults", 0)

    # -- arming -------------------------------------------------------------
    def inject(
        self,
        methods: str | tuple[str, ...] | None = None,
        fail_count: int = 0,
        fail_rate: float = 0.0,
        exception: Callable[[str], BaseException] | None = None,
        latency_s: float = 0.0,
        hang_s: float = 0.0,
    ) -> FaultSpec:
        if isinstance(methods, str):
            methods = (methods,)
        spec = FaultSpec(
            methods=methods,
            fail_count=fail_count,
            fail_rate=fail_rate,
            latency_s=latency_s,
            hang_s=hang_s,
        )
        if exception is not None:
            spec.exception = exception
        with self._lock:
            self._specs.append(spec)
        return spec

    def clear(self) -> None:
        """Disarm everything: the wrapped object behaves normally again."""
        with self._lock:
            self._specs.clear()

    # -- proxying -----------------------------------------------------------
    def _apply_faults(self, method: str) -> None:
        """Raise/delay per the armed specs. Counting + spec decay under the
        lock; sleeping outside it."""
        to_sleep = 0.0
        to_raise: BaseException | None = None
        with self._lock:
            self.calls += 1
            for spec in self._specs:
                if not spec.matches(method):
                    continue
                to_sleep += spec.latency_s
                if to_raise is not None:
                    continue
                if spec.fail_count > 0:
                    spec.fail_count -= 1
                    to_raise = spec.exception(method)
                elif spec.fail_rate > 0 and self._rng() < spec.fail_rate:
                    to_raise = spec.exception(method)
                if to_raise is not None:
                    self.faults += 1
                    to_sleep += spec.hang_s
        if to_sleep > 0:
            time.sleep(to_sleep)
        if to_raise is not None:
            raise to_raise

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._target, name)
        if not callable(attr):
            return attr

        def wrapper(*args: Any, **kwargs: Any) -> Any:
            self._apply_faults(name)
            return attr(*args, **kwargs)

        wrapper.__name__ = getattr(attr, "__name__", name)
        return wrapper

    def __setattr__(self, name: str, value: Any) -> None:
        if name in ("calls", "faults"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._target, name, value)

    def __repr__(self) -> str:
        return f"FaultInjector({self._target!r}, specs={len(self._specs)})"
