"""Circuit breaker: stop hammering a dependency that is already down.

Classic three-state machine:

- **closed** — normal traffic; consecutive failures are counted.
- **open** — after ``failure_threshold`` consecutive failures every call is
  rejected instantly with ``CircuitOpenError`` (the caller converts this to
  a 503 with ``Retry-After``) instead of burying the backend under timed-out
  work.
- **half-open** — after ``recovery_timeout_s`` a bounded number of probe
  calls are let through; one success closes the circuit, one failure
  re-opens it for another full recovery window.

Thread-safe; the clock is injectable for tests. A breaker guards one
dependency (one storage repository, one device dispatch path) and is shared
by every call site that touches it.

State transitions are observable: pass ``listener`` (called with
``(name, old_state, new_state)`` outside the breaker lock) and the
servers turn every trip/recovery into metrics — silent resilience
decisions were the gap the telemetry layer exists to close.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitOpenError(RuntimeError):
    """Rejected without attempting the call: the circuit is open.

    Not transient — retrying in-process within milliseconds is exactly the
    hammering the breaker exists to stop. ``retry_after_s`` is the time
    until the next half-open probe window, for a ``Retry-After`` header.
    """

    transient = False

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit '{name}' is open; retry after {retry_after_s:.2f}s"
        )
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    def __init__(
        self,
        name: str = "",
        failure_threshold: int = 5,
        recovery_timeout_s: float = 5.0,
        half_open_max_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        listener: Callable[[str, str, str], None] | None = None,
    ):
        self.name = name or "breaker"
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_timeout_s = recovery_timeout_s
        self.half_open_max_probes = max(1, half_open_max_probes)
        self._clock = clock
        # (name, old_state, new_state) observer, invoked OUTSIDE the lock
        # (a listener that re-enters the breaker must not deadlock)
        self.listener = listener
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_inflight = 0
        self.trips = 0  # closed/half-open -> open transitions (monitoring)

    # -- state machine ------------------------------------------------------
    def _trip(self) -> None:
        self._state = OPEN
        self._opened_at = self._clock()
        self._probes_inflight = 0
        self.trips += 1

    def _notify(self, old_state: str, new_state: str) -> None:
        if self.listener is not None and old_state != new_state:
            try:
                self.listener(self.name, old_state, new_state)
            except Exception:
                pass  # monitoring must never break the state machine

    def allow(self) -> None:
        """Gate one call. Raises ``CircuitOpenError`` instead of allowing;
        a successful return must be paired with ``record_success`` or
        ``record_failure`` (or use ``call()`` which does the pairing)."""
        transition: tuple[str, str] | None = None
        err: CircuitOpenError | None = None
        with self._lock:
            if self._state == CLOSED:
                return
            elapsed = self._clock() - self._opened_at
            if self._state == OPEN:
                if elapsed < self.recovery_timeout_s:
                    err = CircuitOpenError(
                        self.name, self.recovery_timeout_s - elapsed
                    )
                else:
                    self._state = HALF_OPEN
                    self._probes_inflight = 0
                    transition = (OPEN, HALF_OPEN)
            if err is None:
                # half-open: admit a bounded number of concurrent probes
                if self._probes_inflight >= self.half_open_max_probes:
                    err = CircuitOpenError(self.name, self.recovery_timeout_s)
                else:
                    self._probes_inflight += 1
        if transition is not None:
            self._notify(*transition)
        if err is not None:
            raise err

    def record_success(self) -> None:
        transition: tuple[str, str] | None = None
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._probes_inflight = 0
                transition = (HALF_OPEN, CLOSED)
        if transition is not None:
            self._notify(*transition)

    def release_probe(self) -> None:
        """Un-claim a half-open probe slot whose call was never attempted
        (admission-shed, expired in queue, client gone before dispatch).
        Without this, an unrecorded probe wedges the circuit half-open —
        rejecting everything — forever. Clamped and state-gated, so a
        spurious release is harmless (worst case: one extra probe)."""
        with self._lock:
            if self._state == HALF_OPEN and self._probes_inflight > 0:
                self._probes_inflight -= 1

    def record_failure(self) -> None:
        transition: tuple[str, str] | None = None
        with self._lock:
            if self._state == HALF_OPEN:
                self._trip()  # failed probe: full recovery window again
                transition = (HALF_OPEN, OPEN)
            else:
                self._consecutive_failures += 1
                if (
                    self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold
                ):
                    self._trip()
                    transition = (CLOSED, OPEN)
        if transition is not None:
            self._notify(*transition)

    def chain_listener(self, fn: Callable[[str, str, str], None]) -> None:
        """Add a transition observer without displacing the existing one
        (the obs instruments claim ``listener`` wholesale; the rollout
        router needs trip notifications on the same breaker). Listeners
        run in chain order, each isolated from the others' exceptions."""
        previous = self.listener

        def chained(name: str, old: str, new: str) -> None:
            if previous is not None:
                try:
                    previous(name, old, new)
                except Exception:
                    pass  # monitoring must never break the state machine
            fn(name, old, new)

        self.listener = chained

    def force_open(self) -> None:
        """Administrative trip (drain a replica without killing it)."""
        transition: tuple[str, str] | None = None
        with self._lock:
            if self._state != OPEN:
                old = self._state
                self._trip()
                transition = (old, OPEN)
        if transition is not None:
            self._notify(*transition)

    def reset(self) -> None:
        transition: tuple[str, str] | None = None
        with self._lock:
            if self._state != CLOSED:
                transition = (self._state, CLOSED)
            self._state = CLOSED
            self._consecutive_failures = 0
            self._probes_inflight = 0
        if transition is not None:
            self._notify(*transition)

    # -- conveniences -------------------------------------------------------
    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        counts_as_failure: Callable[[BaseException], bool] | None = None,
        **kwargs: Any,
    ) -> Any:
        """Gate + run + record in one step. ``CircuitOpenError`` counts as
        neither success nor failure. ``counts_as_failure`` classifies which
        exceptions are *dependency* failures: a request-specific permanent
        error (bad payload the backend deterministically rejects) must not
        trip the breaker and 503 every other client — it propagates while
        recording neither outcome (and frees its half-open probe slot)."""
        self.allow()
        try:
            result = fn(*args, **kwargs)
        except BaseException as exc:
            if counts_as_failure is None or counts_as_failure(exc):
                self.record_failure()
            else:
                self.release_probe()
            raise
        self.record_success()
        return result

    @property
    def state(self) -> str:
        with self._lock:
            # surface open->half-open lazily so monitoring doesn't need a call
            if (
                self._state == OPEN
                and self._clock() - self._opened_at >= self.recovery_timeout_s
            ):
                return HALF_OPEN
            return self._state

    def snapshot(self) -> dict[str, Any]:
        """JSON-ready state for /healthz."""
        with self._lock:
            state = self._state
            if (
                state == OPEN
                and self._clock() - self._opened_at >= self.recovery_timeout_s
            ):
                state = HALF_OPEN
            return {
                "name": self.name,
                "state": state,
                "consecutiveFailures": self._consecutive_failures,
                "trips": self.trips,
            }
