"""Request deadlines threaded through call chains.

A ``Deadline`` is an absolute point on a monotonic clock, created once at
the edge (HTTP handler, CLI entry) and passed down through every layer that
can block — micro-batch admission, dispatch, storage calls, retries. Each
layer asks ``remaining()`` and sizes its own timeout to fit, so a request
spends its budget exactly once instead of stacking N independent timeouts
whose worst case is their sum.

The clock is injectable so tests advance time without sleeping.
"""

from __future__ import annotations

import time
from typing import Callable


class DeadlineExceeded(TimeoutError):
    """The operation's deadline passed before it completed.

    Marked ``transient = False``: retrying within the same request cannot
    help (the budget is spent) — the caller should shed the request and let
    the client retry with a fresh deadline.
    """

    transient = False


class Deadline:
    """Absolute deadline on a monotonic clock. ``None`` budget = unbounded."""

    __slots__ = ("_at", "_clock")

    def __init__(
        self,
        timeout_s: float | None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._clock = clock
        self._at = None if timeout_s is None else clock() + max(0.0, timeout_s)

    @classmethod
    def after(
        cls, timeout_s: float | None, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        """``timeout_s <= 0`` or ``None`` builds an unbounded deadline."""
        if timeout_s is None or timeout_s <= 0:
            return cls(None, clock)
        return cls(timeout_s, clock)

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    @property
    def bounded(self) -> bool:
        return self._at is not None

    def remaining(self) -> float | None:
        """Seconds left (clamped at 0), or None when unbounded."""
        if self._at is None:
            return None
        return max(0.0, self._at - self._clock())

    @property
    def expired(self) -> bool:
        return self._at is not None and self._clock() >= self._at

    def check(self, what: str = "operation") -> None:
        if self.expired:
            raise DeadlineExceeded(f"{what}: deadline exceeded")

    def clamp(self, timeout_s: float | None) -> float | None:
        """Fit a layer-local timeout inside this deadline: the smaller of
        the two, with None meaning unbounded on both sides."""
        rem = self.remaining()
        if rem is None:
            return timeout_s
        if timeout_s is None:
            return rem
        return min(rem, timeout_s)

    @staticmethod
    def min_of(deadlines: "list[Deadline]") -> "Deadline":
        """The tightest of a set (for a micro-batch: the batch must answer
        by its most impatient member). Unbounded members don't tighten."""
        best: Deadline | None = None
        for d in deadlines:
            if not d.bounded:
                continue
            if best is None or d._at < best._at:  # noqa: SLF001 — same class
                best = d
        return best if best is not None else Deadline.never()

    def __repr__(self) -> str:
        rem = self.remaining()
        return f"Deadline(remaining={'inf' if rem is None else f'{rem:.3f}s'})"
