"""The lifecycle driver: ring → :class:`LifecyclePolicy` → grid →
registry → cache warm, with every transition on the telemetry ring.

The controller owns the impure half the policy refuses to touch: it
reads drift records off the ring, probes the registry's rollout state
through the shared :func:`~predictionio_tpu.registry.probe
.registry_rollout_probe`, launches the eval grid on a background thread
(the grid is synchronous and minutes-long; the tick loop must keep
deciding while it runs), watches the bake through the registry state
file, and replays warm-up queries after a promote. Two small files make
it operable and crash-safe:

``lifecycle.json``
    The durable state (tmp+rename, the registry's ``_atomic_write``
    idiom). Written after every transition; read back on start so a
    SIGKILLed controller resumes its episode — a persisted TUNING state
    relaunches the grid with ``resume=True`` and the PR-14 ledger skips
    every finished cell. Also the data source for ``pio lifecycle
    status`` and ``pio top --lifecycle``.

``lifecycle-control.json``
    The operator's mailbox: ``{"paused": bool, "trigger": N}`` written
    by ``pio lifecycle pause|trigger`` and polled every tick. The
    trigger field is a counter, not a flag — the policy remembers the
    last token it consumed, so one ``trigger`` command fires exactly one
    episode even across controller restarts.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from typing import Any, Callable

from predictionio_tpu.lifecycle.policy import (
    BAKE,
    DEFER,
    FINISH,
    GRID_DONE,
    GRID_FAILED,
    GRID_NONE,
    GRID_RUNNING,
    HOLD,
    START_TUNE,
    STATE_TUNING,
    TRIGGER,
    WARM,
    LifecycleDecision,
    LifecycleInputs,
    LifecyclePolicy,
    OUTCOME_ABORTED,
    OUTCOME_PROMOTED,
    OUTCOME_ROLLED_BACK,
)
from predictionio_tpu.obs.metrics import MetricsRegistry

logger = logging.getLogger("predictionio_tpu.lifecycle")

STATE_FILE = "lifecycle.json"
CONTROL_FILE = "lifecycle-control.json"


def register_lifecycle_metrics(registry: MetricsRegistry) -> dict[str, Any]:
    """Get-or-create the ``pio_lifecycle_*`` family (idempotent — the
    same template as ``register_eval_metrics``, so the controller, the
    metrics contract test, and a bare exporter all converge on one set).
    The names here are contract-tested against docs/observability.md."""
    return {
        "ticks": registry.counter(
            "pio_lifecycle_ticks_total", "lifecycle control-loop passes"
        ),
        "errors": registry.counter(
            "pio_lifecycle_errors_total",
            "lifecycle ticks that failed (ring read, registry probe, grid "
            "launch, or state-file write) — counted and retried",
        ),
        "triggers": registry.counter(
            "pio_lifecycle_triggers_total",
            "retune episodes started, by signal",
            labelnames=("reason",),
        ),
        "transitions": registry.counter(
            "pio_lifecycle_transitions_total",
            "episode state transitions, by destination state",
            labelnames=("to",),
        ),
        "runs": registry.counter(
            "pio_lifecycle_runs_total",
            "completed lifecycle episodes, by terminal outcome "
            "(promoted / rolled-back / aborted)",
            labelnames=("outcome",),
        ),
        "deferred": registry.counter(
            "pio_lifecycle_deferred_total",
            "retunes deferred because a rollout was mid-bake (started "
            "after promote/rollback, never concurrently)",
        ),
        "warm_queries": registry.counter(
            "pio_lifecycle_warm_queries_total",
            "cache-warm queries replayed after promotes, by result",
            labelnames=("result",),
        ),
        "state": registry.gauge(
            "pio_lifecycle_state",
            "current episode state (0=idle 1=triggered 2=tuning 3=baking)",
        ),
        "paused": registry.gauge(
            "pio_lifecycle_paused",
            "1 while the operator paused automatic triggers "
            "(in-flight episodes still run to completion)",
        ),
        "last_transition_unix": registry.gauge(
            "pio_lifecycle_last_transition_unix",
            "unix time of the last episode transition (0 = never)",
        ),
    }


_STATE_GAUGE = {"idle": 0.0, "triggered": 1.0, "tuning": 2.0, "baking": 3.0}


def _atomic_write_json(path: str, data: dict[str, Any]) -> None:
    """tmp+fsync+rename — readers (CLI status, top) see old-or-new,
    never torn (the registry store's idiom)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def read_json_file(path: str) -> dict[str, Any] | None:
    """Best-effort JSON read: missing / torn / non-dict → None. Control
    and status files are poll-read; a torn read is just 'try next tick'."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def write_control(
    dir_path: str, *, paused: bool | None = None, trigger: bool = False
) -> dict[str, Any]:
    """CLI-side helper: merge a pause flip and/or a trigger bump into the
    control file (read-modify-write; the single writer is the operator)."""
    path = os.path.join(dir_path, CONTROL_FILE)
    data = read_json_file(path) or {}
    if paused is not None:
        data["paused"] = bool(paused)
    if trigger:
        data["trigger"] = int(data.get("trigger", 0)) + 1
    os.makedirs(dir_path, exist_ok=True)
    _atomic_write_json(path, data)
    return data


class LifecycleController:
    """Ticks the policy and executes its decisions.

    ``tune(resume)`` runs the retune (production wiring: the eval grid on
    nice'd cpu-fallback workers, publishing its winner as a registry
    CANDIDATE) and returns the staged version string ("" when the grid
    produced no publishable winner). It executes on a daemon thread the
    controller owns; the policy sees it as ``grid_state`` =
    running/done/failed. ``warm(version)`` replays bounded queries into
    the new stable's result cache and returns counts. Both are injected
    so the unit matrix runs the whole episode with fakes and a fake
    clock."""

    def __init__(
        self,
        policy: LifecyclePolicy,
        *,
        state_dir: str,
        engine_id: str = "",
        registry_dir: str = "",
        tune: Callable[[bool], str] | None = None,
        warm: Callable[[str], dict[str, int]] | None = None,
        rollout_probe: Callable[[], bool] | None = None,
        ring: Any | None = None,  # obs.tsring.TelemetryRing
        incidents: Any | None = None,  # obs.incidents.IncidentRecorder
        metrics: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.time,
    ):
        self.policy = policy
        self.state_dir = state_dir
        self.engine_id = engine_id
        self.registry_dir = registry_dir
        self._tune = tune
        self._warm = warm
        self._rollout_probe = rollout_probe
        self.ring = ring
        self.incidents = incidents
        self._clock = clock
        self.metrics = metrics or MetricsRegistry()
        self._m = register_lifecycle_metrics(self.metrics)
        self._store: Any = None  # lazy ArtifactStore
        # background grid thread state (written by the thread, read by
        # ticks; the GIL + single writer make the simple fields safe)
        self._grid_thread: threading.Thread | None = None
        self._grid_state = GRID_NONE
        self._grid_version = ""
        self._grid_error = ""
        os.makedirs(state_dir, exist_ok=True)
        self._restore()

    # ----------------------------------------------------------- durability
    @property
    def state_path(self) -> str:
        return os.path.join(self.state_dir, STATE_FILE)

    def _restore(self) -> None:
        """Resume after a crash: the persisted policy episode is the
        truth. A controller killed mid-TUNING relaunches the grid with
        ``resume=True`` on its first tick — the grid's ledger skips every
        finished cell, so the SIGKILL costs at most one cell of work."""
        data = read_json_file(self.state_path)
        if not data:
            return
        policy_data = data.get("policy")
        if isinstance(policy_data, dict):
            self.policy = LifecyclePolicy.from_json_dict(
                policy_data, self.policy.config
            )
        if self.policy.state == STATE_TUNING:
            logger.info(
                "lifecycle: resuming interrupted tuning episode "
                "(grid relaunches with resume=True)"
            )
            self._launch_grid(resume=True)

    def _persist(self, decision: LifecycleDecision | None = None) -> None:
        snap: dict[str, Any] = {
            "engine": self.engine_id,
            "policy": self.policy.to_json_dict(),
            "grid": {
                "state": self._grid_state,
                "stagedVersion": self._grid_version,
                "error": self._grid_error,
            },
            "paused": bool(self._control().get("paused", False)),
            "updatedAt": self._clock(),
        }
        if decision is not None:
            snap["lastDecision"] = decision.to_json_dict()
        _atomic_write_json(self.state_path, snap)

    def _control(self) -> dict[str, Any]:
        return read_json_file(os.path.join(self.state_dir, CONTROL_FILE)) or {}

    # ------------------------------------------------------------ telemetry
    def _record(self, event: str, decision: LifecycleDecision, **extra: Any) -> None:
        """Lifecycle transitions are telemetry: appended to the SAME ring
        the drift sensor writes, so `pio top`, incident bundles, and the
        next operator see the whole loop in one timeline."""
        self._m["last_transition_unix"].set(self._clock())
        if self.ring is None:
            return
        record = {
            "kind": "lifecycle",
            "event": event,
            "engine": self.engine_id,
            "state": self.policy.state,
            "decision": decision.to_json_dict(),
        }
        record.update(extra)
        self.ring.append(record)

    # ----------------------------------------------------------- grid seam
    def _launch_grid(self, resume: bool) -> None:
        if self._tune is None:
            self._grid_state = GRID_FAILED
            self._grid_error = "no tune runner wired"
            return
        self._grid_state = GRID_RUNNING
        self._grid_version = ""
        self._grid_error = ""

        def runner() -> None:
            try:
                version = self._tune(resume)
            except Exception as exc:  # the policy aborts the episode
                logger.exception("lifecycle: grid run failed")
                self._grid_error = str(exc)
                self._grid_state = GRID_FAILED
                return
            self._grid_version = str(version or "")
            self._grid_state = GRID_DONE

        self._grid_thread = threading.Thread(
            target=runner, name="lifecycle-grid", daemon=True
        )
        self._grid_thread.start()

    def _forget_grid(self) -> None:
        # an abandoned thread (timeout) keeps running but its result is
        # discarded; the ledger it wrote still speeds up the next episode
        self._grid_thread = None
        self._grid_state = GRID_NONE
        self._grid_version = ""
        self._grid_error = ""

    # ------------------------------------------------------------- registry
    def _registry_state(self) -> tuple[str, str, str]:
        """(stable, candidate, mode) for our engine — '' / 'off' without
        a registry (the policy then resolves bakes on rollout_active)."""
        if not self.registry_dir or not self.engine_id:
            return "", "", "off"
        if self._store is None:
            from predictionio_tpu.registry.store import ArtifactStore

            self._store = ArtifactStore(self.registry_dir)
        st = self._store.get_state(self.engine_id)
        return st.stable, st.candidate, st.mode

    def rollout_active(self) -> bool:
        # raises on an unreadable registry: this tick must not launch a
        # grid on unknown rollout state (run() counts the error, retries)
        if self._rollout_probe is None:
            return False
        return bool(self._rollout_probe())

    def _unstage_timed_out_bake(self) -> None:
        if self._store is None or not self.engine_id:
            return
        try:
            self._store.unstage(self.engine_id, reason="lifecycle-bake-timeout")
        except Exception:
            logger.exception("lifecycle: unstage after bake-timeout failed")

    # ----------------------------------------------------------------- tick
    def inputs(self) -> LifecycleInputs:
        control = self._control()
        stable, candidate, mode = self._registry_state()
        records: list[dict[str, Any]] = []
        if self.ring is not None:
            records = self.ring.window(self.policy.config.drift_window_s)
        return LifecycleInputs(
            records=records,
            rollout_active=self.rollout_active(),
            paused=bool(control.get("paused", False)),
            manual_token=int(control.get("trigger", 0)),
            grid_state=self._grid_state,
            grid_staged_version=self._grid_version,
            registry_stable=stable,
            registry_candidate=candidate,
            registry_mode=mode,
        )

    def tick(self) -> LifecycleDecision:
        """One control pass: assemble inputs, decide, execute, persist.
        Exceptions propagate (run() counts them); a failed execution never
        advances the episode — note_* only fires after the action lands."""
        self._m["ticks"].inc()
        now = self._clock()
        inp = self.inputs()
        self._m["paused"].set(1.0 if inp.paused else 0.0)
        decision = self.policy.decide(inp, now)
        self._apply(decision, inp, now)
        self._m["state"].set(_STATE_GAUGE.get(self.policy.state, 0.0))
        return decision

    def _apply(
        self, decision: LifecycleDecision, inp: LifecycleInputs, now: float
    ) -> None:
        if decision.action == HOLD:
            return
        if decision.action == TRIGGER:
            self.policy.note_triggered(decision.reason, inp, now)
            self._m["triggers"].inc(reason=decision.reason)
            self._m["transitions"].inc(to="triggered")
            self._record("triggered", decision)
            logger.info("lifecycle: retune triggered (%s)", decision.reason)
        elif decision.action == DEFER:
            self.policy.note_deferred()
            self._m["deferred"].inc()
            self._record("deferred", decision)
            logger.info("lifecycle: retune deferred (%s)", decision.reason)
        elif decision.action == START_TUNE:
            self._launch_grid(resume=False)
            self.policy.note_tuning(now)
            self._m["transitions"].inc(to="tuning")
            self._record("tuning", decision)
            logger.info("lifecycle: grid launched (%s)", decision.reason)
        elif decision.action == BAKE:
            version = inp.grid_staged_version
            self._forget_grid()
            self.policy.note_baking(version, now)
            self._m["transitions"].inc(to="baking")
            self._record("baking", decision, version=version)
            logger.info("lifecycle: candidate %s baking", version)
        elif decision.action == WARM:
            # promote observed: warm BEFORE closing the episode so a
            # crash mid-warm resumes as 'baking' and re-runs the warm
            # (idempotent — warming is cache fills)
            self._run_warm(decision, inp.registry_stable)
            self._finish(decision, now)
        elif decision.action == FINISH:
            if decision.reason == "bake-timeout":
                self._unstage_timed_out_bake()
            was_tuning = self.policy.state == STATE_TUNING
            self._finish(decision, now)
            if was_tuning:
                self._forget_grid()
        self._persist(decision)

    def _run_warm(self, decision: LifecycleDecision, version: str) -> None:
        if self._warm is None or self.policy.config.warm_limit <= 0:
            return
        try:
            counts = self._warm(version)
        except Exception:
            # warming is best-effort: a failed warm never rolls back a
            # good promote (the cache fills organically instead)
            logger.exception("lifecycle: cache warm failed")
            self._m["warm_queries"].inc(result="error")
            return
        for result, n in (counts or {}).items():
            self._m["warm_queries"].inc(float(n), result=result)
        logger.info("lifecycle: cache warmed for %s: %s", version, counts)

    def _finish(self, decision: LifecycleDecision, now: float) -> None:
        outcome = decision.outcome
        self.policy.note_finished(outcome, now)
        self._m["runs"].inc(outcome=outcome)
        self._m["transitions"].inc(to=outcome)
        self._record(
            "finished", decision, outcome=outcome, error=self._grid_error
        )
        logger.info(
            "lifecycle: episode finished %s (%s)", outcome, decision.reason
        )
        if outcome in (OUTCOME_ABORTED, OUTCOME_ROLLED_BACK):
            # the bundle carries the ring tail: the drift that triggered,
            # the grid's fate, and the bake verdict, in one timeline
            if self.incidents is not None:
                self.incidents.trigger(
                    f"lifecycle-{outcome}",
                    context={
                        "engine": self.engine_id,
                        "reason": decision.reason,
                        "gridError": self._grid_error,
                    },
                )

    # ----------------------------------------------------------------- run
    async def run(self) -> None:
        """Asyncio driver: tick forever at the configured cadence; a
        failing tick is counted and retried next interval ('controller
        dead' is a failure-matrix row, not a serving outage — serving
        never depends on this loop). Ticks run on an executor thread,
        never the serving event loop: a tick walks the on-disk ring and
        reads registry state files (the autoscaler's rule)."""
        interval = self.policy.config.tick_interval_s
        loop = asyncio.get_running_loop()
        while True:
            try:
                await loop.run_in_executor(None, self.tick)
            except asyncio.CancelledError:
                raise
            except Exception:
                self._m["errors"].inc()
                logger.exception("lifecycle tick failed")
            await asyncio.sleep(interval)


__all__ = [
    "CONTROL_FILE",
    "STATE_FILE",
    "LifecycleController",
    "read_json_file",
    "register_lifecycle_metrics",
    "write_control",
]
