"""Post-promote cache warm: a bounded ``pio batchpredict``-style replay.

The result cache (PR 13) is in-process, per-replica, and stable-lane
only — a fresh promote starts every replica at 0% hit rate exactly when
the new model is most interesting. The warm closes that gap the only way
an out-of-process controller can: replay real queries over the serving
HTTP surface (``POST /queries.json``) so each replica's own cache fills
through the same code path production traffic uses. Misses are the
point; errors are counted, never raised — a failed warm must not undo a
good promote (the "zero client-visible 5xx" rule: warming happens on the
stable lane AFTER the bake resolved, so a dead replica here surfaces as
a warm error count, not a client failure).

Queries come from the batchpredict ``--from-events`` source (distinct
users off the event store) capped at ``limit`` — the same bounded corpus
the nightly precompute uses, so warm cost is predictable."""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request
from typing import Any, Callable, Iterable, Iterator

logger = logging.getLogger("predictionio_tpu.lifecycle")


def replay_queries(
    serve_url: str,
    queries: Iterable[dict[str, Any]],
    *,
    limit: int = 256,
    timeout_s: float = 10.0,
) -> dict[str, int]:
    """POST up to ``limit`` queries to ``{serve_url}/queries.json`` and
    return ``{"ok": n, "error": n}``. Never raises."""
    url = serve_url.rstrip("/") + "/queries.json"
    counts = {"ok": 0, "error": 0}
    for i, query in enumerate(queries):
        if limit and i >= limit:
            break
        body = json.dumps(query).encode("utf-8")
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                resp.read()
                counts["ok"] += 1
        except (urllib.error.URLError, OSError, ValueError):
            counts["error"] += 1
    if counts["error"]:
        logger.warning(
            "lifecycle warm: %d/%d queries failed against %s",
            counts["error"],
            counts["ok"] + counts["error"],
            url,
        )
    return counts


def event_store_queries(
    storage: Any, app_id: int, *, num: int = 10, limit: int = 256
) -> Iterator[dict[str, Any]]:
    """Bounded distinct-user queries off the event store — the
    batchpredict ``--from-events`` source, reused verbatim."""
    from predictionio_tpu.workflow.batch_predict import iter_event_users

    levents = storage.get_l_events()
    for _, query in iter_event_users(levents, app_id, limit=limit, num=num):
        yield query


def build_warmer(
    serve_url: str,
    query_source: Callable[[], Iterable[dict[str, Any]]],
    *,
    limit: int = 256,
    timeout_s: float = 10.0,
) -> Callable[[str], dict[str, int]]:
    """The controller's ``warm(version)`` callable: re-materialize the
    query corpus each promote (the event store may have grown) and replay
    it. The version argument is logging-only — the gateway already routes
    the stable lane to the promoted model."""

    def warm(version: str) -> dict[str, int]:
        logger.info(
            "lifecycle warm: replaying up to %d queries for %s", limit, version
        )
        return replay_queries(
            serve_url, query_source(), limit=limit, timeout_s=timeout_s
        )

    return warm


__all__ = ["build_warmer", "event_store_queries", "replay_queries"]
