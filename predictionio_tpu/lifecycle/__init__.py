"""The model lifecycle control plane: drift → retune → bake → promote →
warm, zero human commands (docs/lifecycle.md).

Layered like the autoscaler (PR 12): :mod:`.policy` is the pure decision
engine (fake-clock testable, no I/O), :mod:`.controller` is the driver
that wires it to the telemetry ring, the eval grid, the registry, and
the incident recorder, and :mod:`.warm` replays bounded queries into the
result cache after a promote."""

from predictionio_tpu.lifecycle.controller import (
    LifecycleController,
    read_json_file,
    register_lifecycle_metrics,
    write_control,
)
from predictionio_tpu.lifecycle.policy import (
    LifecycleConfig,
    LifecycleDecision,
    LifecycleInputs,
    LifecyclePolicy,
)
from predictionio_tpu.lifecycle.tune import build_grid_tuner
from predictionio_tpu.lifecycle.warm import build_warmer, replay_queries

__all__ = [
    "LifecycleConfig",
    "LifecycleController",
    "LifecycleDecision",
    "LifecycleInputs",
    "LifecyclePolicy",
    "build_grid_tuner",
    "build_warmer",
    "read_json_file",
    "register_lifecycle_metrics",
    "replay_queries",
    "write_control",
]
