"""The controller's production ``tune`` seam: one retune episode = one
eval-grid run on background-priority cpu-fallback workers, winner
published as a registry CANDIDATE.

Each episode gets its own sub-workdir (``run-0001``, ``run-0002``, …)
under the tuner's root — minted when the episode starts, reused on a
crash resume — so the grid's ledger semantics line up with the
controller's: ``tune(resume=False)`` is a fresh grid in a fresh dir,
``tune(resume=True)`` re-enters the SAME dir and the PR-14 ledger skips
every finished cell. The current episode number lives in
``episode.json`` (tmp+rename), which is how a SIGKILLed controller finds
its way back to the half-finished grid."""

from __future__ import annotations

import logging
import os
from typing import Any, Callable

from predictionio_tpu.lifecycle.controller import (
    _atomic_write_json,
    read_json_file,
)

logger = logging.getLogger("predictionio_tpu.lifecycle")

EPISODE_FILE = "episode.json"


def build_grid_tuner(
    source: Any,
    *,
    workdir: str,
    engine_manifest: Any,
    registry_dir: str,
    storage: Any = None,
    workers: int = 2,
    nice: int = 10,
    folds: int | None = None,
    batch_size: int = 0,
    stage_mode: str = "canary",
    stage_fraction: float = 0.1,
    cwd: str = "",
    env: dict[str, str] | None = None,
    instruments: Any = None,
) -> Callable[[bool], str]:
    """A ``tune(resume) -> staged_version`` callable for
    :class:`~predictionio_tpu.lifecycle.controller.LifecycleController`.

    The grid always runs ``publish=True`` (the whole point is a staged
    candidate), always on the cpu-fallback worker class (JAX_PLATFORMS
    pinned to cpu, worker count bounded), and always ``os.nice``'d —
    the retune is a background citizen of a serving host."""
    from predictionio_tpu.tuning.runner import (
        DEFAULT_CELL_BATCH,
        WORKER_CLASS_CPU_FALLBACK,
        run_grid,
    )

    def tune(resume: bool) -> str:
        os.makedirs(workdir, exist_ok=True)
        ep_path = os.path.join(workdir, EPISODE_FILE)
        state = read_json_file(ep_path) or {"episode": 0}
        if not resume or int(state.get("episode", 0)) == 0:
            state["episode"] = int(state.get("episode", 0)) + 1
            _atomic_write_json(ep_path, state)
        episode = int(state["episode"])
        run_dir = os.path.join(workdir, f"run-{episode:04d}")
        report = run_grid(
            source,
            workdir=run_dir,
            workers=workers,
            folds=folds,
            # within the episode dir, resume iff a ledger exists — a
            # crash before the first cell landed is just a fresh start
            resume=os.path.exists(os.path.join(run_dir, "ledger.jsonl")),
            batch_size=batch_size or DEFAULT_CELL_BATCH,
            data_span={"lifecycle": {"episode": episode}},
            publish=True,
            registry_dir=registry_dir,
            engine_manifest=engine_manifest,
            storage=storage,
            stage_mode=stage_mode,
            stage_fraction=stage_fraction,
            status_path=os.path.join(run_dir, "status.json"),
            instruments=instruments,
            cwd=cwd,
            env=env,
            nice=nice,
            worker_class=WORKER_CLASS_CPU_FALLBACK,
        )
        logger.info(
            "lifecycle tune episode %d: %d cells (%d skipped), winner %s",
            episode,
            report.cells_total,
            report.cells_skipped,
            report.published_version or "<none>",
        )
        return report.published_version

    return tune


__all__ = ["build_grid_tuner"]
