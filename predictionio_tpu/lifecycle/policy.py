"""The lifecycle decision engine: drift → retune → bake → promote → warm.

ROADMAP item 4's control plane closes the loop the existing subsystems
left open: stream drift guards (PR 5) detect that the serving model went
stale, the evaluation grid (PR 14) can find a better one, the bake gates
(PR 4) can judge it, and nightly batchpredict (PR 13) can pre-warm it —
but until now a human typed every command in between. This module is the
*policy* half of the controller: a pure state machine in the autoscaler's
idiom (fleet/autoscaler.py ``ScalingPolicy``) — every method takes an
explicit ``now``, all inputs arrive as plain values (ring records,
registry state, grid status), and tests drive every branch with a fake
clock and hand-built records, no processes anywhere.

States (the episode)::

    IDLE ──trigger (drift|cadence|manual)──▶ TRIGGERED
        TRIGGERED ──rollout active──▶ (DEFERRED, stays TRIGGERED)
        TRIGGERED ──clear──▶ TUNING          (grid launched)
    TUNING ──winner staged──▶ BAKING         (bake gates own it now)
    TUNING ──failed / no winner / timeout──▶ ABORTED
    BAKING ──registry stable == winner──▶ PROMOTED  (then cache warm)
    BAKING ──rollout off, stable != winner──▶ ROLLED_BACK
    BAKING ──timeout──▶ ABORTED              (driver unstages)

PROMOTED / ROLLED_BACK / ABORTED are terminal *outcomes*: the episode
ends, the policy returns to IDLE, and the cooldown clock starts. The
mid-bake deferral is an EPISODE exactly like the autoscaler's resize
deferral: one DEFER decision when the episode starts, HOLD afterwards,
so the deferred counter counts retunes deferred, not ticks spent baking
— and a grid run is NEVER started while a rollout bakes (the
never-concurrent rule the chaos e2e asserts).

The policy is serializable (:meth:`LifecyclePolicy.to_json_dict` /
``from_json_dict``) — the driver persists it tmp+rename after every
transition so a SIGKILLed controller resumes its episode, including a
TUNING run picked back up through the grid's durable ledger.
"""

from __future__ import annotations

import dataclasses
from typing import Any

# episode states
STATE_IDLE = "idle"
STATE_TRIGGERED = "triggered"
STATE_TUNING = "tuning"
STATE_BAKING = "baking"

# terminal outcomes (recorded on the ring / metrics, never a live state)
OUTCOME_PROMOTED = "promoted"
OUTCOME_ROLLED_BACK = "rolled-back"
OUTCOME_ABORTED = "aborted"

# decision actions
HOLD = "hold"
TRIGGER = "trigger"
DEFER = "defer"
START_TUNE = "start-tune"
BAKE = "bake"
WARM = "warm"
FINISH = "finish"

# trigger reasons
REASON_DRIFT = "drift"
REASON_CADENCE = "cadence"
REASON_MANUAL = "manual"

# grid states the driver reports (LifecycleInputs.grid_state)
GRID_NONE = ""
GRID_RUNNING = "running"
GRID_DONE = "done"
GRID_FAILED = "failed"


@dataclasses.dataclass
class LifecycleConfig:
    """Controller knobs (docs/lifecycle.md)."""

    # scheduled retune cadence; 0 disables (drift/manual only)
    cadence_s: float = 0.0
    # how far back ring drift records count as a live signal
    drift_window_s: float = 600.0
    # distinct drift records inside the window needed to trigger (one
    # breach already suppressed a publish — the default acts on it)
    min_drift_records: int = 1
    # after any terminal outcome, no drift/cadence retrigger sooner than
    # this (manual triggers bypass the cooldown, never an active episode)
    cooldown_s: float = 600.0
    # a grid run older than this is abandoned (ABORTED; its ledger keeps
    # the finished cells for the next episode's resume)
    tune_timeout_s: float = 7200.0
    # a bake the server never resolves is abandoned (driver unstages)
    bake_timeout_s: float = 3600.0
    # driver tick cadence
    tick_interval_s: float = 2.0
    # bounded post-promote cache warm (queries replayed; 0 disables)
    warm_limit: int = 256


@dataclasses.dataclass(frozen=True)
class LifecycleInputs:
    """One tick's world view, assembled by the driver: ring records
    (the policy reads ``kind="drift"``), the shared rollout probe, the
    control file's pause/manual-trigger flags, the background grid's
    status, and the engine's registry rollout state."""

    records: list[dict[str, Any]] = dataclasses.field(default_factory=list)
    rollout_active: bool = False
    paused: bool = False
    # monotonically increasing manual-trigger token (0 = never); the
    # policy remembers the last token it consumed
    manual_token: int = 0
    grid_state: str = GRID_NONE
    grid_staged_version: str = ""
    registry_stable: str = ""
    registry_candidate: str = ""
    registry_mode: str = "off"


@dataclasses.dataclass(frozen=True)
class LifecycleDecision:
    """One tick's verdict. ``action`` drives the driver; ``reason`` is
    the triggering signal or outcome cause; ``outcome`` is set only on
    FINISH/WARM (what the episode resolved to)."""

    action: str
    reason: str
    outcome: str = ""

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "action": self.action,
            "reason": self.reason,
            "outcome": self.outcome,
        }


class LifecyclePolicy:
    """The pure decision engine: inputs in, :class:`LifecycleDecision`
    out. Stateful only in what the episode needs — current state, the
    staged winner being baked, the drift high-water mark, the cooldown
    anchor, and the pending mid-bake deferral — and every method takes an
    explicit ``now``. The driver MUST confirm each applied transition via
    the ``note_*`` methods; a decision that could not be executed leaves
    the episode untouched (the same contract as
    ``ScalingPolicy.note_applied``)."""

    def __init__(self, config: LifecycleConfig | None = None):
        self.config = config or LifecycleConfig()
        self.state = STATE_IDLE
        # why the current episode triggered (drift/cadence/manual)
        self.trigger_reason = ""
        # when the current state was entered (timeout anchor)
        self.since: float | None = None
        # the grid winner's registry version while BAKING
        self.staged_version = ""
        # cooldown anchor: when the last episode resolved (also the
        # cadence anchor, so a retune schedules from the last outcome)
        self.last_done_at: float | None = None
        self.last_outcome = ""
        # drift high-water mark: ring seq of the newest drift record any
        # trigger consumed — one breach never re-triggers forever
        self.drift_seq = -1
        # manual high-water mark (control-file token)
        self.manual_seq = 0
        # episodic mid-bake deferral flag (DEFER once, HOLD after)
        self.deferred = False

    # ------------------------------------------------------------- signals
    def _drift_records(
        self, records: list[dict[str, Any]], now: float
    ) -> list[dict[str, Any]]:
        cutoff = now - self.config.drift_window_s
        return [
            r
            for r in records
            if r.get("kind") == "drift"
            and float(r.get("t", 0.0)) >= cutoff
            and int(r.get("seq", 0)) > self.drift_seq
        ]

    def wants_trigger(self, inp: LifecycleInputs, now: float) -> str | None:
        """The trigger reason when a retune is due, else None. Manual
        outranks drift outranks cadence; manual bypasses the cooldown
        (an operator typed it), the automatic signals respect it."""
        if inp.manual_token > self.manual_seq:
            return REASON_MANUAL
        if inp.paused:
            return None
        cfg = self.config
        in_cooldown = (
            self.last_done_at is not None
            and now - self.last_done_at < cfg.cooldown_s
        )
        if in_cooldown:
            return None
        fresh = self._drift_records(inp.records, now)
        if len(fresh) >= max(1, cfg.min_drift_records):
            return REASON_DRIFT
        if cfg.cadence_s > 0:
            anchor = self.last_done_at
            if anchor is None:
                # first-ever cadence run anchors at the first tick that
                # observed the clock (note_started sets it)
                anchor = self.started_at
            if anchor is not None and now - anchor >= cfg.cadence_s:
                return REASON_CADENCE
        return None

    # the first tick's clock reading — the cadence anchor before any
    # episode has resolved (set by the driver via note_started)
    started_at: float | None = None

    def note_started(self, now: float) -> None:
        if self.started_at is None:
            self.started_at = now

    # ------------------------------------------------------------- deciding
    def decide(self, inp: LifecycleInputs, now: float) -> LifecycleDecision:
        """One tick. The driver executes the returned action and
        confirms it via the matching ``note_*`` method."""
        self.note_started(now)
        if self.state == STATE_IDLE:
            reason = self.wants_trigger(inp, now)
            if reason is None:
                return LifecycleDecision(HOLD, "paused" if inp.paused else "steady")
            return LifecycleDecision(TRIGGER, reason)
        if self.state == STATE_TRIGGERED:
            if inp.rollout_active:
                # never start a grid while a candidate bakes — DEFER is
                # an episode, exactly like the autoscaler's resizes
                if self.deferred:
                    return LifecycleDecision(HOLD, "mid-bake-pending")
                return LifecycleDecision(DEFER, "mid-bake")
            return LifecycleDecision(START_TUNE, self.trigger_reason)
        if self.state == STATE_TUNING:
            if inp.grid_state == GRID_DONE:
                if inp.grid_staged_version:
                    return LifecycleDecision(BAKE, "winner-staged")
                # grid finished but staged nothing: NaN winner, winner is
                # already the stable, or publish disabled
                return LifecycleDecision(FINISH, "no-candidate", OUTCOME_ABORTED)
            if inp.grid_state == GRID_FAILED:
                return LifecycleDecision(FINISH, "grid-failed", OUTCOME_ABORTED)
            if (
                self.since is not None
                and now - self.since > self.config.tune_timeout_s
            ):
                return LifecycleDecision(FINISH, "tune-timeout", OUTCOME_ABORTED)
            return LifecycleDecision(HOLD, "tuning")
        if self.state == STATE_BAKING:
            baking = (
                inp.registry_mode != "off"
                and inp.registry_candidate == self.staged_version
            )
            if baking:
                if (
                    self.since is not None
                    and now - self.since > self.config.bake_timeout_s
                ):
                    # the driver unstages: a bake no server resolves
                    # must not pin the candidate lane forever
                    return LifecycleDecision(FINISH, "bake-timeout", OUTCOME_ABORTED)
                return LifecycleDecision(HOLD, "baking")
            # the rollout resolved (or something else took the lane over)
            if inp.registry_stable == self.staged_version:
                return LifecycleDecision(WARM, "bake-promoted", OUTCOME_PROMOTED)
            return LifecycleDecision(FINISH, "bake-rejected", OUTCOME_ROLLED_BACK)
        raise AssertionError(f"unknown lifecycle state {self.state!r}")

    # ---------------------------------------------------------- transitions
    def note_triggered(self, reason: str, inp: LifecycleInputs, now: float) -> None:
        """IDLE -> TRIGGERED applied: consume the signal's high-water
        marks so the same drift records / manual token never re-fire."""
        fresh = self._drift_records(inp.records, now)
        if fresh:
            self.drift_seq = max(int(r.get("seq", 0)) for r in fresh)
        if inp.manual_token > self.manual_seq:
            self.manual_seq = inp.manual_token
        self.state = STATE_TRIGGERED
        self.trigger_reason = reason
        self.since = now
        self.deferred = False

    def note_deferred(self) -> None:
        self.deferred = True

    def note_tuning(self, now: float) -> None:
        self.state = STATE_TUNING
        self.since = now
        self.deferred = False

    def note_baking(self, version: str, now: float) -> None:
        self.state = STATE_BAKING
        self.staged_version = version
        self.since = now

    def note_finished(self, outcome: str, now: float) -> None:
        """Any terminal outcome: episode over, cooldown starts."""
        self.state = STATE_IDLE
        self.trigger_reason = ""
        self.staged_version = ""
        self.since = None
        self.deferred = False
        self.last_done_at = now
        self.last_outcome = outcome

    # -------------------------------------------------------- serialization
    def to_json_dict(self) -> dict[str, Any]:
        return {
            "state": self.state,
            "triggerReason": self.trigger_reason,
            "since": self.since,
            "stagedVersion": self.staged_version,
            "lastDoneAt": self.last_done_at,
            "lastOutcome": self.last_outcome,
            "driftSeq": self.drift_seq,
            "manualSeq": self.manual_seq,
            "deferred": self.deferred,
            "startedAt": self.started_at,
        }

    @classmethod
    def from_json_dict(
        cls, data: dict[str, Any], config: LifecycleConfig | None = None
    ) -> "LifecyclePolicy":
        policy = cls(config)
        policy.state = str(data.get("state", STATE_IDLE))
        if policy.state not in (
            STATE_IDLE,
            STATE_TRIGGERED,
            STATE_TUNING,
            STATE_BAKING,
        ):
            policy.state = STATE_IDLE
        policy.trigger_reason = str(data.get("triggerReason", ""))
        policy.since = data.get("since")
        policy.staged_version = str(data.get("stagedVersion", ""))
        policy.last_done_at = data.get("lastDoneAt")
        policy.last_outcome = str(data.get("lastOutcome", ""))
        policy.drift_seq = int(data.get("driftSeq", -1))
        policy.manual_seq = int(data.get("manualSeq", 0))
        policy.deferred = bool(data.get("deferred", False))
        policy.started_at = data.get("startedAt")
        return policy


__all__ = [
    "BAKE",
    "DEFER",
    "FINISH",
    "GRID_DONE",
    "GRID_FAILED",
    "GRID_NONE",
    "GRID_RUNNING",
    "HOLD",
    "LifecycleConfig",
    "LifecycleDecision",
    "LifecycleInputs",
    "LifecyclePolicy",
    "OUTCOME_ABORTED",
    "OUTCOME_PROMOTED",
    "OUTCOME_ROLLED_BACK",
    "REASON_CADENCE",
    "REASON_DRIFT",
    "REASON_MANUAL",
    "START_TUNE",
    "STATE_BAKING",
    "STATE_IDLE",
    "STATE_TRIGGERED",
    "STATE_TUNING",
    "TRIGGER",
    "WARM",
]
