"""DASE controller API — what engine templates import.

Reference parity: ``core/.../controller/`` package object; the names here
mirror the reference's public controller surface.
"""

from predictionio_tpu.controller.base import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Doer,
    IdentityPreparator,
    SanityCheck,
)
from predictionio_tpu.controller.algorithm import (
    JaxAlgorithm,
    LocalAlgorithm,
    PersistentModel,
    PersistentModelManifest,
    model_to_host,
)
from predictionio_tpu.controller.engine import (
    Engine,
    EngineFactory,
    EngineParams,
    TrainOptions,
)
from predictionio_tpu.controller.params import (
    EmptyParams,
    Params,
    ParamsError,
    params_from_dict,
    params_from_json,
)
from predictionio_tpu.controller.serving import AverageServing, FirstServing

__all__ = [
    "AverageServing",
    "BaseAlgorithm",
    "BaseDataSource",
    "BasePreparator",
    "BaseServing",
    "Doer",
    "EmptyParams",
    "Engine",
    "EngineFactory",
    "EngineParams",
    "FirstServing",
    "IdentityPreparator",
    "JaxAlgorithm",
    "LocalAlgorithm",
    "Params",
    "ParamsError",
    "PersistentModel",
    "PersistentModelManifest",
    "SanityCheck",
    "TrainOptions",
    "model_to_host",
    "params_from_dict",
    "params_from_json",
]
