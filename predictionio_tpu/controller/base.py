"""DASE base classes and the component-instantiation Doer.

Reference parity: ``core/.../core/BaseDataSource.scala``,
``BasePreparator.scala``, ``BaseAlgorithm.scala``, ``BaseServing.scala``,
``AbstractDoer.scala`` (reflective ctor(Params) instantiation),
``controller/SanityCheck.scala``.

Type parameters follow the reference's ``Engine[TD, EI, PD, Q, P, A]``:
  TD = training data, EI = evaluation info, PD = prepared data,
  Q = query, P = predicted result, A = actual result.

The reference's L/P duality (local objects vs RDDs) collapses here: training
data is whatever the DataSource returns (typically a ``ColumnarEvents`` block
or jax arrays); distribution is expressed by sharding inside the algorithm,
not by the type system.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Generic, Sequence, TypeVar

from predictionio_tpu.controller.params import EmptyParams, Params
from predictionio_tpu.workflow.context import WorkflowContext

TD = TypeVar("TD")
EI = TypeVar("EI")
PD = TypeVar("PD")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")
M = TypeVar("M")  # model


class SanityCheck:
    """Optional mixin for TD/PD/model types: ``sanity_check`` is invoked
    after each stage unless --skip-sanity-check (ref Engine.scala:650-706)."""

    def sanity_check(self) -> None:
        raise NotImplementedError


class Doer:
    """Instantiate a DASE component class with its Params
    (ref AbstractDoer.scala:69 — ctor(params) with fallback to no-arg)."""

    @staticmethod
    def apply(cls: type, params: Params | None = None) -> Any:
        params = params if params is not None else EmptyParams()
        try:
            sig = inspect.signature(cls.__init__)
            takes_params = len(sig.parameters) > 1  # beyond self
        except (TypeError, ValueError):
            takes_params = False
        if takes_params:
            return cls(params)
        return cls()


class BaseDataSource(Generic[TD, EI, Q, A]):
    """Reads training and evaluation data (ref BaseDataSource.scala:55)."""

    params: Params

    def __init__(self, params: Params | None = None):
        self.params = params if params is not None else EmptyParams()

    def read_training(self, ctx: WorkflowContext) -> TD:
        raise NotImplementedError

    def read_eval(self, ctx: WorkflowContext) -> Sequence[tuple[TD, EI, Sequence[tuple[Q, A]]]]:
        """k folds of (trainingData, evalInfo, [(query, actual)])."""
        raise NotImplementedError


class BasePreparator(Generic[TD, PD]):
    """ref BasePreparator.scala:45."""

    params: Params

    def __init__(self, params: Params | None = None):
        self.params = params if params is not None else EmptyParams()

    def prepare(self, ctx: WorkflowContext, training_data: TD) -> PD:
        raise NotImplementedError


class IdentityPreparator(BasePreparator[TD, TD]):
    """Pass-through preparator (ref IdentityPreparator.scala:91)."""

    def prepare(self, ctx: WorkflowContext, training_data: TD) -> TD:
        return training_data


class BaseAlgorithm(Generic[PD, M, Q, P]):
    """ref BaseAlgorithm.scala:58-126. Subclasses are the three flavors in
    ``controller/algorithm.py``; this class defines the train/predict
    contract plus model-persistence hooks."""

    params: Params

    def __init__(self, params: Params | None = None):
        self.params = params if params is not None else EmptyParams()

    def train(self, ctx: WorkflowContext, prepared_data: PD) -> Any:
        raise NotImplementedError

    def predict(self, model: Any, query: Q) -> P:
        raise NotImplementedError

    def batch_predict(self, model: Any, queries: Sequence[tuple[int, Q]]) -> list[tuple[int, P]]:
        """Default: map predict over indexed queries (ref P2LAlgorithm
        default batchPredict :69-71). Jax algorithms override with a
        vectorized path."""
        return [(i, self.predict(model, q)) for i, q in queries]

    def predict_batch(self, model: Any, queries: Sequence[Q]) -> list[P]:
        """Serving-side micro-batch hook: predict a batch of *live* queries
        in one device call. The query server's dispatcher coalesces
        concurrent /queries.json requests into one call here — the TPU answer
        to the reference's per-request actor dispatch (and its literal
        ``TODO: Parallelize``, CreateServer.scala:488-491). Default maps
        ``predict``; device-backed algorithms override with one batched
        kernel so N concurrent requests cost one device round-trip."""
        return [self.predict(model, q) for q in queries]

    def predict_batch_dispatch(
        self, model: Any, queries: Sequence[Q]
    ) -> Callable[[], list[P]] | None:
        """Pipelined serving hook: *dispatch* the batch's device work without
        blocking and return a zero-arg finalize callable that fetches and
        decodes the results. The query server dispatches batch n+1 while
        batch n's results are still crossing the transport, so sustained
        throughput approaches the pure device-batched rate and per-request
        latency approaches one transport round-trip. Return None (the
        default) to use the synchronous ``predict_batch`` path."""
        return None

    def warmup_serving(self, model: Any, max_batch: int) -> None:
        """Deploy-time warm-up: pre-compile the device programs the serving
        path will hit (e.g. every power-of-two batch bucket up to
        ``max_batch``) so the first burst of traffic doesn't pay XLA
        compiles. Called by the query server at start and after /reload.
        Default: nothing to warm."""

    # -- persistence hooks (ref makePersistentModel, BaseAlgorithm.scala:95)
    def make_persistent_model(self, ctx: WorkflowContext, model: Any) -> Any:
        """Return the object to persist for this model. Default: the model
        itself (everything here is a picklable pytree; the reference's
        'unit sentinel, retrain on deploy' mode is intentionally dropped —
        see SURVEY.md section 7 hard part (c))."""
        return model

    def prepare_model(self, ctx: WorkflowContext, persisted: Any) -> Any:
        """Rehydrate the persisted object at deploy time (inverse of
        make_persistent_model)."""
        return persisted


class BaseServing(Generic[Q, P]):
    """ref BaseServing.scala:54."""

    params: Params

    def __init__(self, params: Params | None = None):
        self.params = params if params is not None else EmptyParams()

    def supplement(self, query: Q) -> Q:
        return query

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        raise NotImplementedError
