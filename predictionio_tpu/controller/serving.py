"""Serving flavors (ref ``core/.../controller/LServing.scala:55``,
``LFirstServing.scala:42``, ``LAverageServing.scala:44``)."""

from __future__ import annotations

from typing import Sequence

from predictionio_tpu.controller.base import P, Q, BaseServing


class FirstServing(BaseServing[Q, P]):
    """Serve the first algorithm's prediction (ref LFirstServing)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class AverageServing(BaseServing[Q, P]):
    """Average numeric predictions across algorithms (ref LAverageServing)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return sum(predictions) / len(predictions)  # type: ignore[return-value]
