"""SelfCleaningDataSource — time-windowed event retention + compaction.

Reference parity: ``core/.../core/SelfCleaningDataSource.scala:42-324`` — a
mixin for data sources: keep only events inside an ``EventWindow`` duration,
deduplicate identical events, compress each entity's ``$set``/``$unset``
chain to one equivalent ``$set``, and optionally write the cleaned stream
back to the store (``cleanPersistedPEvents``).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import logging
from typing import Iterable

from predictionio_tpu.data.aggregator import aggregate_properties
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event, now_utc
from predictionio_tpu.data.store.event_store import resolve_app
from predictionio_tpu.workflow.context import WorkflowContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class EventWindow:
    """ref controller/EventWindow: duration like "30 days", and flags."""

    duration: _dt.timedelta | None = None
    remove_duplicates: bool = False
    compress_properties: bool = False

    @staticmethod
    def parse_duration(s: str) -> _dt.timedelta:
        value, _, unit = s.strip().partition(" ")
        n = float(value)
        unit = unit.rstrip("s")
        scale = {
            "second": 1,
            "minute": 60,
            "hour": 3600,
            "day": 86400,
            "week": 7 * 86400,
        }.get(unit)
        if scale is None:
            raise ValueError(f"cannot parse duration {s!r}")
        return _dt.timedelta(seconds=n * scale)


def _dedup_key(e: Event) -> tuple:
    return (
        e.event,
        e.entity_type,
        e.entity_id,
        e.target_entity_type,
        e.target_entity_id,
        e.properties.to_json(),
        e.event_time,
    )


def clean_events(events: Iterable[Event], window: EventWindow) -> list[Event]:
    """Pure cleaning pass: window filter -> dedup -> $set-chain compression."""
    events = list(events)
    if window.duration is not None:
        cutoff = now_utc() - window.duration
        events = [e for e in events if e.event_time >= cutoff]
    if window.remove_duplicates:
        seen: set[tuple] = set()
        deduped = []
        for e in events:
            key = _dedup_key(e)
            if key not in seen:
                seen.add(key)
                deduped.append(e)
        events = deduped
    if window.compress_properties:
        special = [e for e in events if e.event in ("$set", "$unset", "$delete")]
        other = [e for e in events if e.event not in ("$set", "$unset", "$delete")]
        compressed: list[Event] = []
        by_type: dict[str, list[Event]] = {}
        for e in special:
            by_type.setdefault(e.entity_type, []).append(e)
        for entity_type, es in by_type.items():
            for entity_id, pm in aggregate_properties(es).items():
                compressed.append(
                    Event(
                        event="$set",
                        entity_type=entity_type,
                        entity_id=entity_id,
                        properties=DataMap(pm.fields),
                        event_time=pm.last_updated,
                    )
                )
        events = sorted(other + compressed, key=lambda e: e.event_time)
    return events


class SelfCleaningDataSource:
    """Mixin for DataSources. Subclasses define ``app_name`` (or params with
    one) and ``event_window``; call ``cleaned_events(ctx)`` instead of a raw
    find, or ``clean_persisted_events(ctx)`` to compact the store in place
    (ref cleanPersistedPEvents)."""

    event_window: EventWindow = EventWindow()

    def _app_name(self, ctx: WorkflowContext) -> str:
        params = getattr(self, "params", None)
        return getattr(params, "app_name", "") or ctx.app_name  # type: ignore[return-value]

    def cleaned_events(self, ctx: WorkflowContext) -> list[Event]:
        app_name = self._app_name(ctx)
        events = ctx.p_event_store().find(app_name, ctx.channel_name)
        return clean_events(events, self.event_window)

    def clean_persisted_events(self, ctx: WorkflowContext) -> int:
        """Replace the stored stream with its cleaned form. Returns the
        number of events after cleaning.

        Ordering is insert-then-delete: the cleaned events (fresh ids) go in
        first, and only then are the original ids removed. A crash in between
        leaves a recoverable superset (temporary duplicates), never a wiped
        store — unlike drop-table-then-reinsert, which loses the app's whole
        history if the process dies mid-way.
        """
        app_name = self._app_name(ctx)
        storage = ctx.storage
        app_id, channel_id = resolve_app(storage, app_name, ctx.channel_name)
        levents = storage.get_l_events()
        originals = list(storage.get_p_events().find(app_id, channel_id))
        cleaned = clean_events(originals, self.event_window)
        # strip stale event ids so re-insert assigns fresh ones
        import dataclasses as _dc

        levents.insert_batch(
            [_dc.replace(e, event_id=None) for e in cleaned], app_id, channel_id
        )
        old_ids = [e.event_id for e in originals if e.event_id]
        # batch delete: one pass for file-backed stores, one txn for SQL —
        # per-id LEvents.delete would rewrite the JSONL file O(N) times
        storage.get_p_events().delete(old_ids, app_id, channel_id)
        logger.info(
            "self-cleaning: %s now holds %d events", app_name, len(cleaned)
        )
        return len(cleaned)
