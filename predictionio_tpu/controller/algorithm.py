"""Algorithm flavors.

Reference parity: ``core/.../controller/P2LAlgorithm.scala`` (distributed
train -> local model), ``PAlgorithm.scala`` (distributed model),
``LAlgorithm.scala`` (local train/model), ``PersistentModel.scala`` /
``LocalFileSystemPersistentModel.scala``.

TPU re-design: the P2L/P split existed because Spark models either fit the
driver or stay as RDDs. On TPU both collapse into ``JaxAlgorithm`` — train
runs under jit on mesh-sharded arrays; the model is a pytree that may be
sharded across HBM during training but is always checkpointed
sharding-agnostically (host numpy) and re-laid-out at deploy. ``LocalAlgorithm``
covers host-only (pure Python/NumPy) algorithms, the analog of LAlgorithm.
"""

from __future__ import annotations

from typing import Any, Generic

import jax
import numpy as np

from predictionio_tpu.controller.base import M, PD, Q, P, BaseAlgorithm
from predictionio_tpu.workflow.context import WorkflowContext


def model_to_host(model: Any) -> Any:
    """Pull every jax array in a model pytree to host numpy — the
    sharding-agnostic checkpoint form (SURVEY.md hard part (f): train on a
    v5e-16, serve on one host).

    Arrays sharded across *processes* are not fully addressable from any one
    host; those are gathered with a cross-host collective first (every
    process must call this — it happens inside make_serializable_models,
    which all processes run)."""

    def pull(x):
        if not isinstance(x, jax.Array):
            return x
        if not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            x = multihost_utils.process_allgather(x, tiled=True)
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(pull, model)


class JaxAlgorithm(BaseAlgorithm[PD, M, Q, P], Generic[PD, M, Q, P]):
    """An algorithm whose train() builds a jax pytree model on the context's
    mesh and whose predict path is a compiled function.

    Subclasses implement ``train`` and ``predict``; ``batch_predict`` may be
    overridden with a vectorized (vmap/jit) implementation — the default maps
    ``predict``.
    """

    def make_persistent_model(self, ctx: WorkflowContext, model: M) -> Any:
        return model_to_host(model)

    def prepare_model(self, ctx: WorkflowContext, persisted: Any) -> M:
        """Default re-layout: leave arrays on host; algorithms that want
        device-resident serving override and device_put with their preferred
        shardings."""
        return persisted


class LocalAlgorithm(BaseAlgorithm[PD, M, Q, P], Generic[PD, M, Q, P]):
    """Host-only algorithm (ref LAlgorithm): pure Python/NumPy train and
    predict, no device interaction. Participates in batch eval by plain
    mapping."""


class PersistentModel:
    """Models managing their own storage (ref PersistentModel.scala:115).

    A model class implementing ``save``/``load`` is persisted by calling
    ``save`` and recording a manifest; at deploy, ``load`` rebuilds it.
    """

    def save(self, instance_id: str, params: Any, base_dir: str) -> bool:
        """Persist; return False to fall back to default pytree persistence."""
        raise NotImplementedError

    @classmethod
    def load(cls, instance_id: str, params: Any, base_dir: str) -> "PersistentModel":
        raise NotImplementedError


class PersistentModelManifest:
    """Marker stored in the model repo instead of bytes
    (ref workflow/PersistentModelManifest.scala)."""

    def __init__(self, class_path: str):
        self.class_path = class_path

    def to_json_dict(self) -> dict[str, str]:
        return {"class_path": self.class_path}
