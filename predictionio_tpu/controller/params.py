"""Typed engine parameters extracted from engine.json.

Reference parity: ``Params`` marker + ``EmptyParams``
(``core/.../controller/Params.scala``), JSON -> param-case-class extraction
(``Engine.scala:355-418``, ``workflow/JsonExtractor.scala``). Here params are
Python dataclasses; extraction is typed field-by-field with clear errors and
tolerance for missing optional fields.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Mapping, Type, TypeVar

P = TypeVar("P", bound="Params")


@dataclasses.dataclass(frozen=True)
class Params:
    """Base class for all component parameter sets."""

    def to_json_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), sort_keys=True)


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    pass


class ParamsError(ValueError):
    pass


def _coerce(value: Any, annotation: Any, field_name: str) -> Any:
    origin = typing.get_origin(annotation)
    if annotation is Any or annotation is dataclasses.MISSING:
        return value
    import types as _types

    if origin is typing.Union or origin is _types.UnionType:  # Optional / unions
        args = [a for a in typing.get_args(annotation) if a is not type(None)]
        if value is None:
            return None
        for a in args:
            try:
                return _coerce(value, a, field_name)
            except (TypeError, ValueError):
                continue
        raise ParamsError(f"field {field_name}: cannot coerce {value!r} to {annotation}")
    if origin in (list, tuple, set):
        args = typing.get_args(annotation)
        inner = args[0] if args else Any
        items = [_coerce(v, inner, field_name) for v in value]
        return origin(items) if origin is not list else items
    if origin is dict:
        return dict(value)
    if dataclasses.is_dataclass(annotation) and isinstance(value, Mapping):
        return params_from_dict(annotation, value)
    if annotation is float and isinstance(value, (int, float)):
        return float(value)
    if annotation is int:
        if isinstance(value, bool):
            raise ParamsError(f"field {field_name}: bool given for int")
        if isinstance(value, int):
            return value
        if isinstance(value, float) and value.is_integer():
            return int(value)
        raise ParamsError(f"field {field_name}: expected int, got {value!r}")
    if annotation is bool and not isinstance(value, bool):
        raise ParamsError(f"field {field_name}: expected bool, got {value!r}")
    if annotation is str and not isinstance(value, str):
        raise ParamsError(f"field {field_name}: expected str, got {value!r}")
    return value


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def params_from_dict(cls: Type[P], data: Mapping[str, Any] | None) -> P:
    """Build a params dataclass from a JSON object. Unknown keys error (the
    reference silently ignores them, which hides typos — flagged instead);
    missing keys fall back to dataclass defaults or error when required.

    JSON keys may be camelCase (``numIterations`` -> ``num_iterations``) for
    wire parity with reference engine.json files; keys colliding with Python
    keywords map to the trailing-underscore field (``lambda`` -> ``lambda_``).
    """
    raw = dict(data or {})
    if not dataclasses.is_dataclass(cls):
        raise ParamsError(f"{cls} is not a dataclass")
    field_names = {f.name for f in dataclasses.fields(cls)}
    data = {}
    for key, value in raw.items():
        for candidate in (key, _snake(key), key + "_", _snake(key) + "_"):
            if candidate in field_names:
                data[candidate] = value
                break
        else:
            data[key] = value
    hints = typing.get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _coerce(data.pop(f.name), hints.get(f.name, Any), f.name)
        elif (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        ):
            raise ParamsError(f"{cls.__name__}: required field {f.name} missing")
    if data:
        raise ParamsError(
            f"{cls.__name__}: unknown fields {sorted(data)} (known: {sorted(field_names)})"
        )
    return cls(**kwargs)  # type: ignore[return-value]


def params_from_json(cls: Type[P], text: str) -> P:
    return params_from_dict(cls, json.loads(text) if text.strip() else {})
