"""Engine — the DASE composition and its train/eval dataflow.

Reference parity: ``core/.../controller/Engine.scala`` — name->class maps for
the four roles (:82-118), ``train`` with sanity checks and stop-after flags
(static :623-710), ``eval`` multi-algo join graph (:728-817), engine-params
extraction from the engine.json variant (:355-418), ``EngineFactory``
(``EngineFactory.scala:44``).

The reference's eval join (union + groupByKey over RDDs) becomes a plain
indexed merge: queries get dense indices, each algorithm batch-predicts over
the indexed list, predictions regroup by index, serving folds them. Same
dataflow, no shuffle.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, Callable, Generic, Mapping, Sequence

from predictionio_tpu.controller.base import (
    A,
    EI,
    P,
    PD,
    Q,
    TD,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Doer,
    SanityCheck,
)
from predictionio_tpu.controller.base import BaseAlgorithm
from predictionio_tpu.controller.params import Params, params_from_dict
from predictionio_tpu.obs import xray
from predictionio_tpu.workflow.context import WorkflowContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class EngineParams:
    """Named component params (ref EngineParams.scala:35-44)."""

    data_source: tuple[str, Params] = ("", None)  # type: ignore[assignment]
    preparator: tuple[str, Params] = ("", None)  # type: ignore[assignment]
    algorithms: list[tuple[str, Params]] = dataclasses.field(default_factory=list)
    serving: tuple[str, Params] = ("", None)  # type: ignore[assignment]


@dataclasses.dataclass
class TrainOptions:
    """Sanity-check / stop-after flags (ref WorkflowParams)."""

    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False


def _maybe_sanity_check(obj: Any, what: str, skip: bool) -> None:
    if skip:
        return
    if isinstance(obj, SanityCheck):
        logger.info("sanity check %s", what)
        obj.sanity_check()


class Engine(Generic[TD, EI, PD, Q, P, A]):
    def __init__(
        self,
        data_source_classes: Mapping[str, type] | type,
        preparator_classes: Mapping[str, type] | type,
        algorithm_classes: Mapping[str, type] | type,
        serving_classes: Mapping[str, type] | type,
        query_class: type | None = None,
    ):
        def as_map(x) -> dict[str, type]:
            return dict(x) if isinstance(x, Mapping) else {"": x}

        self.data_source_classes = as_map(data_source_classes)
        self.preparator_classes = as_map(preparator_classes)
        self.algorithm_classes = as_map(algorithm_classes)
        self.serving_classes = as_map(serving_classes)
        # Serving-side codec (ref BaseAlgorithm.queryClass via TypeResolver):
        # a class with from_json_dict() for decoding POST /queries.json bodies.
        self.query_class = query_class

    def decode_query(self, payload: Any) -> Any:
        if self.query_class is not None and hasattr(
            self.query_class, "from_json_dict"
        ):
            return self.query_class.from_json_dict(payload)
        return payload

    @staticmethod
    def encode_result(result: Any) -> Any:
        if hasattr(result, "to_json_dict"):
            return result.to_json_dict()
        if dataclasses.is_dataclass(result) and not isinstance(result, type):
            return dataclasses.asdict(result)
        return result

    # ----------------------------------------------------------------- build
    def _pick(self, classes: dict[str, type], name: str, role: str) -> type:
        if name in classes:
            return classes[name]
        if name == "" and len(classes) == 1:
            return next(iter(classes.values()))
        raise KeyError(f"unknown {role} {name!r}; available: {sorted(classes)}")

    def make_components(
        self, engine_params: EngineParams
    ) -> tuple[
        BaseDataSource, BasePreparator, list[BaseAlgorithm], BaseServing
    ]:
        ds_name, ds_params = engine_params.data_source
        prep_name, prep_params = engine_params.preparator
        serv_name, serv_params = engine_params.serving
        data_source = Doer.apply(
            self._pick(self.data_source_classes, ds_name, "datasource"), ds_params
        )
        preparator = Doer.apply(
            self._pick(self.preparator_classes, prep_name, "preparator"), prep_params
        )
        algo_list = engine_params.algorithms or [("", None)]
        algorithms = [
            Doer.apply(self._pick(self.algorithm_classes, name, "algorithm"), p)
            for name, p in algo_list
        ]
        serving = Doer.apply(
            self._pick(self.serving_classes, serv_name, "serving"), serv_params
        )
        return data_source, preparator, algorithms, serving

    # ----------------------------------------------------------------- train
    def train(
        self,
        ctx: WorkflowContext,
        engine_params: EngineParams,
        options: TrainOptions | None = None,
    ) -> list[Any]:
        """ref Engine.train static (Engine.scala:623-710): read -> sanity ->
        prepare -> sanity -> train each algo -> sanity. Returns one model per
        algorithm.

        Step-profiler phases (obs/xray — no-ops without an active
        profile): read+prepare account as ``host_etl``, each algorithm's
        train as ``solve`` (algorithms that iterate internally, e.g. ALS,
        carve their own ``sweep`` steps out of it — exclusive nesting
        keeps the tiling contract exact)."""
        options = options or TrainOptions()
        with xray.phase(xray.PHASE_HOST_ETL):
            data_source, preparator, algorithms, _ = self.make_components(
                engine_params
            )
            td = data_source.read_training(ctx)
            _maybe_sanity_check(td, "training data", options.skip_sanity_check)
            if options.stop_after_read:
                logger.info("stopping after read_training")
                return []
            pd = preparator.prepare(ctx, td)
            _maybe_sanity_check(pd, "prepared data", options.skip_sanity_check)
        if options.stop_after_prepare:
            logger.info("stopping after prepare")
            return []

        models: list[Any] = []
        for i, algo in enumerate(algorithms):
            logger.info("training algorithm %d: %s", i, type(algo).__name__)
            with xray.phase(xray.PHASE_SOLVE):
                model = algo.train(ctx, pd)
                _maybe_sanity_check(model, f"model {i}", options.skip_sanity_check)
            models.append(model)
        return models

    # ------------------------------------------------------- offline dispatch
    def dispatch_batch(
        self,
        algorithms: Sequence[BaseAlgorithm],
        serving: BaseServing,
        models: Sequence[Any],
        queries: Sequence[Any],
    ) -> "Callable[[], list[Any]]":
        """Offline mega-batch entry (``pio batchpredict``): dispatch one
        pre-assembled query batch's device work through every algorithm's
        pipelined path — no HTTP, no micro-batcher, no per-request
        accounting — and return a zero-arg finalize that fetches, regroups
        per query index, and serves. The offline pipeline double-buffers
        on this split: it dispatches batch N, then drains batch N-1 while
        the device computes N. Algorithms without a pipelined path
        (``predict_batch_dispatch`` returning None) run their *indexed*
        ``batch_predict`` inside finalize — the same entry ``eval`` uses,
        so an algorithm that vectorizes only that method (e.g. the
        naive-Bayes classifier) keeps its one-call batch path instead of
        degrading to per-query predicts. Covered by the
        ``serving-host-roundtrip`` lint rule: score+select must stay
        fused on device (ops/topk)."""
        supplemented = [serving.supplement(q) for q in queries]
        fins = [
            algo.predict_batch_dispatch(model, supplemented)
            for algo, model in zip(algorithms, models)
        ]

        def finalize() -> list[Any]:
            per_query: list[list[Any]] = [[] for _ in supplemented]
            for algo, model, fin in zip(algorithms, models, fins):
                if fin is not None:
                    for i, p in enumerate(fin()):
                        per_query[i].append(p)
                else:
                    for i, p in algo.batch_predict(
                        model, list(enumerate(supplemented))
                    ):
                        per_query[i].append(p)
            return [
                serving.serve(q, preds)
                for q, preds in zip(queries, per_query)
            ]

        return finalize

    def make_serializable_models(
        self, ctx: WorkflowContext, engine_params: EngineParams, models: list[Any]
    ) -> list[Any]:
        """ref Engine.makeSerializableModels (:284-302)."""
        _, _, algorithms, _ = self.make_components(engine_params)
        return [
            algo.make_persistent_model(ctx, model)
            for algo, model in zip(algorithms, models)
        ]

    def prepare_deploy(
        self, ctx: WorkflowContext, engine_params: EngineParams, persisted: list[Any]
    ) -> list[Any]:
        """ref Engine.prepareDeploy (:198-267), minus the retrain-on-deploy
        mode: every model here is persistable, so deploy only re-lays-out."""
        _, _, algorithms, _ = self.make_components(engine_params)
        return [
            algo.prepare_model(ctx, blob)
            for algo, blob in zip(algorithms, persisted)
        ]

    # ------------------------------------------------------------------ eval
    def eval(
        self, ctx: WorkflowContext, engine_params: EngineParams
    ) -> list[tuple[EI, list[tuple[Q, P, A]]]]:
        """ref Engine.eval (:728-817): per fold, train all algorithms, then
        supplement -> batch-predict per algo -> regroup by query index ->
        serve."""
        data_source, preparator, algorithms, serving = self.make_components(
            engine_params
        )
        results: list[tuple[EI, list[tuple[Q, P, A]]]] = []
        for fold_idx, (td, ei, qa_pairs) in enumerate(data_source.read_eval(ctx)):
            # materialize ONCE before anything reads it: a data source may
            # yield a generator here (nothing enforces Sequence), and the
            # old len(list(...)) log line consumed it — the fold would then
            # evaluate zero queries and the metric silently averaged nothing
            qa_list = list(qa_pairs)
            logger.info("eval fold %d: %d queries", fold_idx, len(qa_list))
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algorithms]
            supplemented = [
                (i, serving.supplement(q)) for i, (q, _) in enumerate(qa_list)
            ]
            # per-algo batch predict, regrouped by query index
            per_query: list[list[P]] = [[] for _ in qa_list]
            for algo, model in zip(algorithms, models):
                for i, p in algo.batch_predict(model, supplemented):
                    per_query[i].append(p)
            joined = [
                (qa_list[i][0], serving.serve(qa_list[i][0], preds), qa_list[i][1])
                for i, preds in enumerate(per_query)
            ]
            results.append((ei, joined))
        return results

    # ------------------------------------------------- engine.json extraction
    def engine_params_from_variant(
        self, variant: Mapping[str, Any]
    ) -> EngineParams:
        """Build EngineParams from a parsed engine.json variant
        (ref Engine.jValueToEngineParams, Engine.scala:355-418).

        Expected shape::

            {"datasource": {"params": {...}},
             "preparator": {"params": {...}},
             "algorithms": [{"name": "als", "params": {...}}, ...],
             "serving": {"params": {...}}}
        """

        def extract(cls: type, raw: dict, role: str) -> Params | None:
            params_cls = getattr(cls, "params_class", None)
            if params_cls is not None:
                return params_from_dict(params_cls, raw)
            if raw:
                # silently training with defaults while the user's
                # hyperparameters sit in engine.json is the typo-hiding
                # behavior the strict params_from_dict exists to prevent
                raise ValueError(
                    f"{role} component {cls.__name__} declares no "
                    f"params_class but the variant supplies params "
                    f"{sorted(raw)}; they would be ignored"
                )
            return None

        def one(role: str, classes: dict[str, type]) -> tuple[str, Params]:
            node = variant.get(role) or {}
            name = node.get("name", "")
            cls = self._pick(classes, name, role)
            params = extract(cls, node.get("params") or {}, role)
            return name, params  # type: ignore[return-value]

        algorithms: list[tuple[str, Params]] = []
        for node in variant.get("algorithms") or []:
            name = node.get("name", "")
            cls = self._pick(self.algorithm_classes, name, "algorithm")
            params = extract(cls, node.get("params") or {}, "algorithm")
            algorithms.append((name, params))  # type: ignore[arg-type]
        return EngineParams(
            data_source=one("datasource", self.data_source_classes),
            preparator=one("preparator", self.preparator_classes),
            algorithms=algorithms,
            serving=one("serving", self.serving_classes),
        )

    @staticmethod
    def engine_params_to_json(engine_params: EngineParams) -> dict[str, str]:
        """Flatten params for EngineInstance persistence
        (ref CreateWorkflow EngineInstance record fields)."""

        def dump(p: Params | None) -> str:
            return p.to_json() if p is not None else "{}"

        return {
            "data_source_params": dump(engine_params.data_source[1]),
            "preparator_params": dump(engine_params.preparator[1]),
            "algorithms_params": json.dumps(
                [
                    {"name": name, "params": json.loads(dump(p))}
                    for name, p in (engine_params.algorithms or [("", None)])
                ]
            ),
            "serving_params": dump(engine_params.serving[1]),
        }


class EngineFactory:
    """ref EngineFactory.scala:44 — a callable returning an Engine. Engine
    templates expose a module-level ``engine_factory()`` function or subclass
    this."""

    def apply(self) -> Engine:
        raise NotImplementedError

    def __call__(self) -> Engine:
        return self.apply()
