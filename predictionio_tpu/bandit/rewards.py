"""Reward accounting: served impressions matched to feedback events.

The serving tier records ``trace_id -> (arm, version)`` for every answered
request (bounded FIFO — an impression that never sees feedback ages out
as pure exploration cost). The reward tailer pages NEW feedback events
from the event store through the ``find_after`` contract — bounded pages,
cursor seeded at the head when the bandit engages, so historical events
never retro-credit an arm — and matches them back by the trace id the
client echoed into the event's properties (docs/bandit.md states the
matching rules)."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Sequence


class ImpressionLog:
    """Bounded trace->arm map. ``record`` is on the serving hot path:
    one lock, one dict insert, one possible FIFO eviction."""

    def __init__(self, capacity: int = 65536):
        self.capacity = max(16, int(capacity))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, tuple[str, str]]" = OrderedDict()
        self.evicted = 0

    def record(self, trace_id: str, arm: str, version: str) -> None:
        if not trace_id:
            return
        with self._lock:
            self._entries[trace_id] = (arm, version)
            self._entries.move_to_end(trace_id)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evicted += 1

    def peek(self, trace_id: str) -> tuple[str, str] | None:
        """Non-destructive lookup (status/debug): which arm answered this
        trace, without consuming its one reward credit."""
        with self._lock:
            return self._entries.get(trace_id)

    def match(self, trace_id: str) -> tuple[str, str] | None:
        """Pop the impression for a rewarded trace: one impression earns
        reward once (duplicate feedback events for the same trace are
        dropped as unmatched — at-least-once event delivery must not
        double-credit an arm)."""
        with self._lock:
            return self._entries.pop(trace_id, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class RewardTailer:
    """Bounded ``find_after`` tail over the app's feedback events.

    Matching rules (docs/bandit.md): an event credits an arm iff its
    event name is in ``event_names``, its properties carry
    ``trace_property``, and that trace id is a live impression. The reward
    value is ``properties[reward_property]`` clamped to [0, 1]
    (absent -> 1.0: a bare conversion event is full reward)."""

    def __init__(
        self,
        levents,
        app_id: int,
        channel_id: int | None = None,
        *,
        event_names: Sequence[str] = ("reward",),
        trace_property: str = "traceId",
        reward_property: str = "reward",
        page: int = 256,
        max_pages: int = 16,
    ):
        self.levents = levents
        self.app_id = app_id
        self.channel_id = channel_id
        self.event_names = frozenset(event_names)
        self.trace_property = trace_property
        self.reward_property = reward_property
        self.page = max(1, int(page))
        self.max_pages = max(1, int(max_pages))
        # only events ingested AFTER the bandit engaged count as reward
        self._cursor = levents.seq_head(app_id, channel_id)

    def poll(
        self, impressions: ImpressionLog
    ) -> tuple[list[tuple[str, str, float]], int]:
        """Drain new feedback events; returns (matched credits as
        ``(arm, version, reward)`` triples, unmatched feedback count)."""
        from predictionio_tpu.data.storage.base import event_seq_key

        credits: list[tuple[str, str, float]] = []
        unmatched = 0
        for _ in range(self.max_pages):
            batch = list(
                self.levents.find_after(
                    self.app_id, self.channel_id, self._cursor, self.page
                )
            )
            if not batch:
                break
            self._cursor = event_seq_key(batch[-1])
            for e in batch:
                if e.event not in self.event_names:
                    continue
                trace = e.properties.get_opt(self.trace_property)
                if not isinstance(trace, str) or not trace:
                    unmatched += 1
                    continue
                hit = impressions.match(trace)
                if hit is None:
                    unmatched += 1
                    continue
                raw = e.properties.get_opt(self.reward_property)
                try:
                    reward = float(raw) if raw is not None else 1.0
                except (TypeError, ValueError):
                    reward = 1.0
                arm, version = hit
                credits.append((arm, version, min(1.0, max(0.0, reward))))
            if len(batch) < self.page:
                break
        return credits, unmatched
