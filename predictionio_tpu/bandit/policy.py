"""Bandit arm state and exploration policies.

Arms are registry lanes: the stable lane and the candidate lane of one
rollout are the two arms of a Bernoulli bandit. Per-arm reward posteriors
are Beta(1 + rewards, 1 + pulls - rewards); the policy's only actuator is
the canary FRACTION of the rollout plan — assignment itself stays the
PR-4 sticky sha256 bucket, so exploration is fleet-consistent and a user
flips lanes only when the fraction crosses their bucket.

Everything here is pure and deterministic given the seeded RNG: the
serving tick drives it, tests replay it."""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

ARM_STABLE = "stable"
ARM_CANDIDATE = "candidate"

DECIDE_EXPLORE = "explore"
DECIDE_PROMOTE = "promote"
DECIDE_RETIRE = "retire"


@dataclasses.dataclass
class ArmState:
    """One lane's reward account. ``pulls`` count SERVED impressions (an
    impression that never earns feedback decays the posterior mean — CTR
    semantics); ``rewards`` is the summed clamped-[0,1] reward mass from
    matched feedback events."""

    version: str
    arm: str
    pulls: float = 0.0
    rewards: float = 0.0

    @property
    def alpha(self) -> float:
        return 1.0 + self.rewards

    @property
    def beta(self) -> float:
        return 1.0 + max(0.0, self.pulls - self.rewards)

    @property
    def mean(self) -> float:
        """Posterior mean reward rate."""
        return self.alpha / (self.alpha + self.beta)

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "version": self.version,
            "arm": self.arm,
            "pulls": self.pulls,
            "rewards": self.rewards,
        }

    @classmethod
    def from_json_dict(cls, d: dict[str, Any]) -> "ArmState":
        return cls(
            version=str(d.get("version", "")),
            arm=str(d.get("arm", "")),
            pulls=float(d.get("pulls", 0.0)),
            rewards=float(d.get("rewards", 0.0)),
        )


@dataclasses.dataclass(frozen=True)
class BanditCriteria:
    """When the posterior is allowed to decide. ``min_pulls`` gates BOTH
    arms — a decision before either arm has evidence is a coin flip with
    extra steps. Promote/retire thresholds are on P(candidate beats
    stable) estimated from the posteriors."""

    min_pulls: float = 20.0
    promote_threshold: float = 0.95
    retire_threshold: float = 0.05
    # fraction clamp: the candidate always keeps exploring a little and
    # the stable always keeps earning fresh reward evidence
    min_fraction: float = 0.05
    max_fraction: float = 0.9
    samples: int = 512  # Monte-Carlo resolution of P(candidate > stable)


def p_candidate_better(
    stable: ArmState, candidate: ArmState, rng: np.random.Generator, samples: int
) -> float:
    """Monte-Carlo P(candidate reward rate > stable's) under the two Beta
    posteriors — the quantity both policies and the decision gate share."""
    s = rng.beta(stable.alpha, stable.beta, size=samples)
    c = rng.beta(candidate.alpha, candidate.beta, size=samples)
    return float(np.mean(c > s))


class EpsilonGreedyPolicy:
    """Exploit the posterior-better arm with probability ``1 - epsilon``
    of the traffic, keep ``epsilon`` on the other — expressed as the
    candidate fraction of the sticky canary plan."""

    name = "epsilon"

    def __init__(self, epsilon: float = 0.1):
        self.epsilon = min(0.5, max(0.0, epsilon))

    def fraction(
        self,
        stable: ArmState,
        candidate: ArmState,
        criteria: BanditCriteria,
        rng: np.random.Generator,
    ) -> float:
        if candidate.pulls < criteria.min_pulls:
            # cold-start exploration: epsilon traffic until the candidate
            # has enough pulls to have an opinion about
            frac = max(self.epsilon, criteria.min_fraction)
        elif candidate.mean > stable.mean:
            frac = 1.0 - self.epsilon
        else:
            frac = self.epsilon
        return min(criteria.max_fraction, max(criteria.min_fraction, frac))


class ThompsonPolicy:
    """Probability matching: the candidate's traffic share IS the Monte-
    Carlo estimate of P(candidate beats stable) under the posteriors."""

    name = "thompson"

    def __init__(self, epsilon: float = 0.1):
        # epsilon doubles as the cold-start fraction before min_pulls
        self.epsilon = min(0.5, max(0.0, epsilon))

    def fraction(
        self,
        stable: ArmState,
        candidate: ArmState,
        criteria: BanditCriteria,
        rng: np.random.Generator,
    ) -> float:
        if candidate.pulls < criteria.min_pulls:
            frac = max(self.epsilon, criteria.min_fraction)
        else:
            frac = p_candidate_better(stable, candidate, rng, criteria.samples)
        return min(criteria.max_fraction, max(criteria.min_fraction, frac))


def make_policy(name: str, epsilon: float = 0.1):
    if name == EpsilonGreedyPolicy.name:
        return EpsilonGreedyPolicy(epsilon)
    if name == ThompsonPolicy.name:
        return ThompsonPolicy(epsilon)
    raise ValueError(
        f"unknown bandit policy {name!r} (epsilon | thompson)"
    )


@dataclasses.dataclass(frozen=True)
class BanditDecision:
    verdict: str  # explore | promote | retire
    fraction: float
    p_better: float | None
    reason: str


def decide(
    stable: ArmState,
    candidate: ArmState,
    criteria: BanditCriteria,
    fraction: float,
    rng: np.random.Generator,
) -> BanditDecision:
    """The bake-gate-as-reward-accounting verdict: with evidence on both
    arms, a candidate whose P(beats stable) clears ``promote_threshold``
    promotes; one below ``retire_threshold`` retires through the existing
    rollback state machine. Anything else keeps exploring at the policy's
    fraction."""
    if stable.pulls < criteria.min_pulls or candidate.pulls < criteria.min_pulls:
        return BanditDecision(
            DECIDE_EXPLORE,
            fraction,
            None,
            f"collecting evidence ({candidate.pulls:g}/{criteria.min_pulls:g} "
            f"candidate pulls, {stable.pulls:g}/{criteria.min_pulls:g} stable)",
        )
    p = p_candidate_better(stable, candidate, rng, criteria.samples)
    if p >= criteria.promote_threshold:
        return BanditDecision(
            DECIDE_PROMOTE,
            fraction,
            p,
            f"P(candidate better)={p:.3f} >= {criteria.promote_threshold:g}",
        )
    if p <= criteria.retire_threshold:
        return BanditDecision(
            DECIDE_RETIRE,
            fraction,
            p,
            f"P(candidate better)={p:.3f} <= {criteria.retire_threshold:g}",
        )
    return BanditDecision(
        DECIDE_EXPLORE, fraction, p, f"P(candidate better)={p:.3f}"
    )


def regret_proxy(stable: ArmState, candidate: ArmState) -> float:
    """Pulls spent on the posterior-WORSE arm — the observable stand-in
    for cumulative regret (true regret needs the unknowable true means)."""
    worse = candidate if candidate.mean < stable.mean else stable
    return float(worse.pulls)
