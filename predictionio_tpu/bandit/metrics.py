"""The ``pio_bandit_*`` metric family (docs/observability.md).

Registered eagerly on the query server's registry (AnnInstruments
discipline): the family exists at zero from process start whether or not
a bandit policy is configured, so scrapers and the docs metrics-contract
test see it immediately. Label cardinality is bounded by construction:
the only label is ``arm`` with exactly two values (stable | candidate) —
versions live in the snapshot endpoint, not label space."""

from __future__ import annotations

from predictionio_tpu.obs.metrics import MetricsRegistry


class BanditInstruments:
    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.active = r.gauge(
            "pio_bandit_active",
            "1 while a bandit policy is steering a live rollout's traffic "
            "split, else 0",
        )
        self.pulls = r.counter(
            "pio_bandit_pulls_total",
            "matched impressions credited as pulls, per arm",
            labelnames=("arm",),
        )
        self.rewards = r.counter(
            "pio_bandit_rewards_total",
            "clamped [0,1] reward mass credited from matched feedback "
            "events, per arm",
            labelnames=("arm",),
        )
        self.reward_rate = r.gauge(
            "pio_bandit_reward_rate",
            "posterior mean reward rate Beta(1+rewards, 1+pulls-rewards), "
            "per arm",
            labelnames=("arm",),
        )
        self.fraction = r.gauge(
            "pio_bandit_fraction",
            "candidate traffic fraction the policy chose for the sticky "
            "canary plan",
        )
        self.p_better = r.gauge(
            "pio_bandit_p_candidate_better",
            "Monte-Carlo P(candidate posterior beats stable) at the last "
            "tick (-1 before both arms have evidence)",
        )
        self.regret_pulls = r.gauge(
            "pio_bandit_regret_pulls",
            "regret proxy: pulls accumulated by the posterior-worse arm",
        )
        self.matched = r.counter(
            "pio_bandit_matched_rewards_total",
            "feedback events matched to a live impression by trace id",
        )
        self.unmatched = r.counter(
            "pio_bandit_unmatched_rewards_total",
            "feedback events with no matching impression (expired, "
            "duplicate, or foreign trace id)",
        )
        self.evicted = r.counter(
            "pio_bandit_impressions_evicted_total",
            "impressions aged out of the bounded trace log before any "
            "feedback arrived",
        )
        self.promoted = r.counter(
            "pio_bandit_promotions_total",
            "candidate arms promoted by the reward posterior",
        )
        self.retired = r.counter(
            "pio_bandit_retirements_total",
            "candidate arms retired (rolled back) by the reward posterior",
        )

    def sync_arms(self, arms) -> None:
        """Refresh per-arm gauges + totals from ArmState objects."""
        for arm in arms:
            self.pulls.set_total(float(arm.pulls), arm=arm.arm)
            self.rewards.set_total(float(arm.rewards), arm=arm.arm)
            self.reward_rate.set(float(arm.mean), arm=arm.arm)
