"""The bandit loop: glue between policy, reward accounting, the registry
artifact grammar, and the serving tier's rollout state machine.

The QueryServer drives it from the SAME heartbeat as the PR-4 bake gate
(``_rollout_tick``): the bake gate keeps its veto on errors/latency (a
reward-winning arm that 5xxes still rolls back), while the bandit owns
the promote decision and the live traffic split — the bake gate doubling
as reward accounting. All decisions route through the existing
promote/rollback transitions, so a losing arm retires with zero
client-visible 5xx by construction (candidate failures already re-answer
on stable)."""

from __future__ import annotations

import logging
import threading
from typing import Any

import numpy as np

from predictionio_tpu.bandit.policy import (
    ARM_CANDIDATE,
    ARM_STABLE,
    ArmState,
    BanditCriteria,
    BanditDecision,
    decide,
    make_policy,
    regret_proxy,
)
from predictionio_tpu.bandit.rewards import ImpressionLog, RewardTailer

logger = logging.getLogger(__name__)


class BanditLoop:
    """One two-arm bandit per live rollout. Inactive between rollouts."""

    def __init__(
        self,
        policy_name: str,
        *,
        epsilon: float = 0.1,
        criteria: BanditCriteria | None = None,
        instruments=None,
        store=None,  # registry ArtifactStore (posterior persistence)
        engine_id: str | None = None,
        impression_capacity: int = 65536,
        seed: int = 0,
    ):
        self.policy = make_policy(policy_name, epsilon)
        self.criteria = criteria or BanditCriteria()
        self.instruments = instruments
        self.store = store
        self.engine_id = engine_id
        self.impressions = ImpressionLog(impression_capacity)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._stable: ArmState | None = None
        self._candidate: ArmState | None = None
        self._tailer: RewardTailer | None = None
        self._dirty = False
        self._evicted_seen = 0

    # ----------------------------------------------------------- lifecycle
    @property
    def active(self) -> bool:
        return self._candidate is not None

    def begin(
        self, stable_version: str, candidate_version: str, tailer: RewardTailer
    ) -> None:
        """Arm the bandit for a freshly staged candidate. A persisted
        posterior for the SAME (stable, candidate) pair resumes — a
        serving restart mid-experiment must not forget paid-for evidence."""
        with self._lock:
            stable = ArmState(stable_version, ARM_STABLE)
            candidate = ArmState(candidate_version, ARM_CANDIDATE)
            saved = (
                self.store.load_bandit_state(self.engine_id)
                if self.store is not None and self.engine_id is not None
                else None
            )
            if saved and not saved.get("ended"):
                s = ArmState.from_json_dict(saved.get("stable", {}))
                c = ArmState.from_json_dict(saved.get("candidate", {}))
                if (
                    s.version == stable_version
                    and c.version == candidate_version
                ):
                    stable, candidate = s, c
                    logger.info(
                        "bandit resumed persisted posterior (%g/%g stable, "
                        "%g/%g candidate)",
                        s.rewards, s.pulls, c.rewards, c.pulls,
                    )
            self._stable, self._candidate = stable, candidate
            self._tailer = tailer
            self._dirty = True
        if self.instruments is not None:
            self.instruments.active.set(1.0)

    def end(self, outcome: str) -> None:
        """Rollout finished (promote | retire | rollback | unstage): count
        the terminal verdict, persist the final posterior for audit, and
        disarm."""
        with self._lock:
            state = self._snapshot_locked()
            self._stable = self._candidate = None
            self._tailer = None
            self._dirty = False
        ins = self.instruments
        if ins is not None:
            ins.active.set(0.0)
            if outcome == "promote":
                ins.promoted.inc()
            elif outcome in ("retire", "rollback"):
                ins.retired.inc()
        if self.store is not None and self.engine_id is not None and state:
            state["ended"] = outcome
            try:
                self.store.save_bandit_state(self.engine_id, state)
            except OSError:
                logger.warning("bandit state save failed", exc_info=True)

    # ------------------------------------------------------------- serving
    def record_impression(self, trace_id: str, arm: str, version: str) -> None:
        """Hot-path accounting for one answered request: the impression
        is a pull the moment it is served (unrewarded impressions decay
        the posterior mean — CTR semantics), and the trace id becomes
        matchable for later feedback."""
        with self._lock:
            target = (
                self._candidate
                if arm == ARM_CANDIDATE
                else self._stable
            )
            if target is None or target.version != version:
                return  # raced a promote/rollback; not this rollout's pull
            target.pulls += 1.0
            self._dirty = True
        self.impressions.record(trace_id, arm, version)
        if self.instruments is not None:
            self.instruments.pulls.inc(arm=arm)

    # ---------------------------------------------------------------- tick
    def tick(self) -> BanditDecision | None:
        """One heartbeat: drain new feedback, credit posteriors, choose
        the traffic fraction, and report the reward verdict. Persists the
        posterior when it changed (atomic content-addressed write)."""
        with self._lock:
            stable, candidate, tailer = self._stable, self._candidate, self._tailer
            if stable is None or candidate is None or tailer is None:
                return None
        credits, unmatched = tailer.poll(self.impressions)
        ins = self.instruments
        with self._lock:
            if self._candidate is not candidate:
                return None  # rollout flipped underneath the poll
            for arm_name, version, reward in credits:
                target = candidate if arm_name == ARM_CANDIDATE else stable
                if target.version != version:
                    unmatched += 1
                    continue
                target.rewards += reward
                self._dirty = True
                if ins is not None:
                    ins.rewards.inc(reward, arm=arm_name)
                    ins.matched.inc()
            fraction = self.policy.fraction(
                stable, candidate, self.criteria, self._rng
            )
            decision = decide(
                stable, candidate, self.criteria, fraction, self._rng
            )
            dirty, self._dirty = self._dirty, False
            state = self._snapshot_locked() if dirty else None
        if ins is not None:
            if unmatched:
                ins.unmatched.inc(unmatched)
            evicted = self.impressions.evicted
            if evicted > self._evicted_seen:
                ins.evicted.inc(evicted - self._evicted_seen)
                self._evicted_seen = evicted
            ins.sync_arms((stable, candidate))
            ins.fraction.set(decision.fraction)
            ins.p_better.set(
                decision.p_better if decision.p_better is not None else -1.0
            )
            ins.regret_pulls.set(regret_proxy(stable, candidate))
        if state is not None and self.store is not None and self.engine_id:
            try:
                self.store.save_bandit_state(self.engine_id, state)
            except OSError:
                logger.warning("bandit state save failed", exc_info=True)
        return decision

    # ------------------------------------------------------------ snapshot
    def _snapshot_locked(self) -> dict[str, Any]:
        if self._stable is None or self._candidate is None:
            return {}
        return {
            "policy": self.policy.name,
            "epsilon": self.policy.epsilon,
            "stable": self._stable.to_json_dict(),
            "candidate": self._candidate.to_json_dict(),
        }

    def snapshot(self) -> dict[str, Any]:
        """JSON-ready view for the status endpoint and ``pio top``."""
        with self._lock:
            out = self._snapshot_locked()
            out["active"] = self.active
            out["impressions_pending"] = len(self.impressions)
            return out
