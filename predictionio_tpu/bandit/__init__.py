"""Contextual-bandit exploration on the rollout machinery (docs/bandit.md).

Arms are registry lanes; assignment is the PR-4 sticky sha256 canary
bucket; reward is feedback-loop events matched to served impressions by
trace id; the bake gate doubles as reward accounting."""

from predictionio_tpu.bandit.controller import BanditLoop
from predictionio_tpu.bandit.metrics import BanditInstruments
from predictionio_tpu.bandit.policy import (
    ARM_CANDIDATE,
    ARM_STABLE,
    DECIDE_EXPLORE,
    DECIDE_PROMOTE,
    DECIDE_RETIRE,
    ArmState,
    BanditCriteria,
    BanditDecision,
    EpsilonGreedyPolicy,
    ThompsonPolicy,
    decide,
    make_policy,
    p_candidate_better,
    regret_proxy,
)
from predictionio_tpu.bandit.rewards import ImpressionLog, RewardTailer

__all__ = [
    "ARM_CANDIDATE",
    "ARM_STABLE",
    "DECIDE_EXPLORE",
    "DECIDE_PROMOTE",
    "DECIDE_RETIRE",
    "ArmState",
    "BanditCriteria",
    "BanditDecision",
    "BanditInstruments",
    "BanditLoop",
    "EpsilonGreedyPolicy",
    "ImpressionLog",
    "RewardTailer",
    "ThompsonPolicy",
    "decide",
    "make_policy",
    "p_candidate_better",
    "regret_proxy",
]
