"""Platform selection guard.

This image (like many TPU dev hosts) registers an out-of-tree PJRT plugin
whose device init talks to a network tunnel and can hang when the tunnel is
unreachable. When the user *explicitly* asked for CPU (``JAX_PLATFORMS=cpu``)
nothing should ever touch the plugin — but a sitecustomize may have imported
jax before the env var was visible, so the env alone is not enough. Dropping
the non-standard backend factories and re-pointing the live config makes an
explicit CPU run hermetic. Mirrors ``tests/conftest.py``.
"""

from __future__ import annotations

import os

_STANDARD = {"cpu", "gpu", "cuda", "rocm", "tpu", "METAL"}


def ensure_cpu_if_requested() -> None:
    """If JAX_PLATFORMS=cpu, make the CPU backend the only reachable one."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return
    try:
        import jax
        from jax._src import xla_bridge as xb

        for name in [n for n in xb._backend_factories if n not in _STANDARD]:
            xb._backend_factories.pop(name, None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - guard must never break startup
        pass
