"""Shared utilities: latency histograms, logging helpers."""
