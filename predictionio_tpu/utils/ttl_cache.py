"""Thread-safe TTL + LRU cache for serving-time storage lookups.

The reference's e-commerce template queries the live LEventStore on every
predict (seen items, unavailable-items constraint —
``train-with-rate-event/src/main/scala/ECommAlgorithm.scala:252-300``),
putting one-or-more row-store round trips on the query hot path. Serving
here caches those lookups for a short TTL so steady-state p50 pays zero
storage round trips; ``ttl_s=0`` disables caching entirely, restoring the
reference's always-live semantics.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Hashable


class TTLCache:
    """``get_or_load(key, loader)`` with per-entry TTL and LRU bound.

    The loader runs OUTSIDE the lock (it does I/O); concurrent misses on
    one key may load twice — harmless for idempotent reads, and better
    than serializing every cache user behind storage latency.
    """

    def __init__(self, ttl_s: float, maxsize: int = 4096):
        self.ttl_s = float(ttl_s)
        self.maxsize = int(maxsize)
        self._entries: OrderedDict[Hashable, tuple[float, Any]] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get_or_load(self, key: Hashable, loader: Callable[[], Any]) -> Any:
        if self.ttl_s <= 0:
            return loader()
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and now - entry[0] < self.ttl_s:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry[1]
            self.misses += 1
        value = loader()
        with self._lock:
            self._entries[key] = (time.monotonic(), value)
            self._entries.move_to_end(key)
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
        return value

    def invalidate(self, key: Hashable | None = None) -> None:
        with self._lock:
            if key is None:
                self._entries.clear()
            else:
                self._entries.pop(key, None)
