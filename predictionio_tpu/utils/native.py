"""Loader for the native C++ scan library.

Compiles ``native/pio_scan.cpp`` with g++ on first use (cached in the
PIO_FS_BASEDIR), loads it via ctypes, and exposes ``scan_jsonl_columnar``.
Everything degrades gracefully: no compiler / failed build -> ``None`` and
callers use the pure-Python path.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _source_path() -> str:
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo_root, "native", "pio_scan.cpp")


def _build_dir() -> str:
    base = os.environ.get(
        "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".pio_store")
    )
    d = os.path.join(base, "native")
    os.makedirs(d, exist_ok=True)
    return d


def _compiler_version() -> bytes:
    """`g++ --version` first line; a compiler upgrade must invalidate the
    cached .so exactly like a source edit does (ABI/codegen changes)."""
    try:
        out = subprocess.run(
            ["g++", "--version"], capture_output=True, timeout=15
        ).stdout
        return out.splitlines()[0] if out else b"unknown"
    except (subprocess.SubprocessError, OSError, IndexError):
        return b"unknown"


def get_library() -> ctypes.CDLL | None:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        src = _source_path()
        if not os.path.exists(src):
            _lib_failed = True
            return None
        # cache key = source bytes + compiler identity: a stale .so must
        # never be loaded after pio_scan.cpp OR the toolchain changes
        h = hashlib.sha256()
        with open(src, "rb") as f:
            h.update(f.read())
        h.update(b"\0")
        h.update(_compiler_version())
        digest = h.hexdigest()[:16]
        so_path = os.path.join(_build_dir(), f"pio_scan_{digest}.so")
        if not os.path.exists(so_path):
            # per-process tmp name: multi-host workers share PIO_FS_BASEDIR
            # and compile concurrently — a shared ".tmp" let one process
            # install another's half-written ELF under the digest name
            tmp = f"{so_path}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    [
                        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                        "-o", tmp, src,
                    ],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, so_path)
                logger.info("built native scan library: %s", so_path)
            except (subprocess.SubprocessError, OSError) as exc:
                logger.warning("native build failed (%s); using python path", exc)
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(so_path)
        except OSError as exc:
            logger.warning("cannot load %s: %s", so_path, exc)
            _lib_failed = True
            return None
        lib.pio_scan_file.restype = ctypes.c_void_p
        lib.pio_scan_file.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p,
        ]
        lib.pio_scan_num_rows.restype = ctypes.c_int64
        lib.pio_scan_num_rows.argtypes = [ctypes.c_void_p]
        lib.pio_scan_error.restype = ctypes.c_char_p
        lib.pio_scan_error.argtypes = [ctypes.c_void_p]
        lib.pio_scan_copy_int32.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int32)]
        lib.pio_scan_copy_f64.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double)]
        lib.pio_scan_copy_f32.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
        lib.pio_scan_vocab_size.restype = ctypes.c_int64
        lib.pio_scan_vocab_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pio_scan_vocab_get.restype = ctypes.c_char_p
        lib.pio_scan_vocab_get.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int64]
        lib.pio_scan_row_id.restype = ctypes.c_char_p
        lib.pio_scan_row_id.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pio_scan_ids_total_bytes.restype = ctypes.c_int64
        lib.pio_scan_ids_total_bytes.argtypes = [ctypes.c_void_p]
        lib.pio_scan_copy_ids.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p,
        ]
        lib.pio_scan_free.argtypes = [ctypes.c_void_p]
        lib.pio_coo_group.restype = ctypes.c_int32
        lib.pio_coo_group.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.pio_cooccur_topn.restype = ctypes.c_int32
        lib.pio_cooccur_topn.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
        return _lib


def cooccur_topn(
    users: np.ndarray, items: np.ndarray, n_items: int, top_n: int
) -> tuple[np.ndarray, np.ndarray] | None:
    """Dense-row cooccurrence count + top-N select at C++ speed. ``users``
    must be sorted ascending with DISTINCT (user, item) pairs (the shape
    ``np.unique`` over 1-D codes produces). Returns ``(items, counts)``
    matrices of shape (n_items, top_n), item slots padded with -1 — or
    None when the native library is unavailable or declines (huge vocab,
    out-of-range ids), in which case callers use the scipy path."""
    lib = get_library()
    if lib is None:
        return None
    users = np.ascontiguousarray(users, np.int32)
    items = np.ascontiguousarray(items, np.int32)
    out_items = np.empty((n_items, top_n), np.int32)
    out_counts = np.empty((n_items, top_n), np.int32)
    rc = lib.pio_cooccur_topn(
        users.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        items.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        users.shape[0],
        n_items,
        top_n,
        out_items.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return out_items, out_counts


def coo_group(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray, n_entities: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Stable group-by-entity of a COO rating list at C++ speed: returns
    ``(cols_sorted, vals_sorted, deg)`` where rows are grouped by ascending
    entity id (original order preserved within an entity) and ``deg`` is the
    per-entity rating count. Returns None when the native library is
    unavailable (callers fall back to numpy argsort)."""
    lib = get_library()
    if lib is None:
        return None
    rows = np.ascontiguousarray(rows, np.int32)
    cols = np.ascontiguousarray(cols, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    n = rows.shape[0]
    cols_out = np.empty(n, np.int32)
    vals_out = np.empty(n, np.float32)
    deg = np.zeros(n_entities, np.int32)
    rc = lib.pio_coo_group(
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        n_entities,
        cols_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals_out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        deg.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return cols_out, vals_out, deg


def scan_jsonl_columnar(
    path: str,
    event_names: list[str] | None = None,
    rating_key: str = "rating",
    entity_type: str | None = None,
    target_entity_type: str | None = None,
):
    """Native columnar scan of a JSONL event file. Returns a dict of numpy
    columns + vocab lists, or None when the native path is unavailable."""
    lib = get_library()
    if lib is None or not os.path.exists(path):
        return None
    csv = ",".join(event_names) if event_names else ""
    handle = lib.pio_scan_file(
        path.encode(),
        csv.encode(),
        rating_key.encode(),
        (entity_type or "").encode(),
        (target_entity_type or "").encode(),
    )
    try:
        err = lib.pio_scan_error(handle)
        if err:
            logger.warning("native scan error: %s", err.decode())
            return None
        n = lib.pio_scan_num_rows(handle)
        entity_ids = np.empty(n, np.int32)
        target_ids = np.empty(n, np.int32)
        event_codes = np.empty(n, np.int32)
        timestamps = np.empty(n, np.float64)
        ratings = np.empty(n, np.float32)
        if n:
            lib.pio_scan_copy_int32(
                handle, 0, entity_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            lib.pio_scan_copy_int32(
                handle, 1, target_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            lib.pio_scan_copy_int32(
                handle, 2, event_codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            lib.pio_scan_copy_f64(
                handle, timestamps.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            lib.pio_scan_copy_f32(
                handle, ratings.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))

        def vocab(which: int) -> list[str]:
            size = lib.pio_scan_vocab_size(handle, which)
            return [
                lib.pio_scan_vocab_get(handle, which, i).decode()
                for i in range(size)
            ]

        # row ids in TWO ffi calls (lengths + one concatenated buffer):
        # a pio_scan_row_id call + decode per row was a python loop that
        # rivaled the whole C++ scan at 20M rows
        event_ids: list[str] = []
        if n:
            lengths = np.empty(n, np.int32)
            buf = ctypes.create_string_buffer(
                max(1, int(lib.pio_scan_ids_total_bytes(handle)))
            )
            lib.pio_scan_copy_ids(
                handle,
                lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                buf,
            )
            raw = buf.raw
            pos = 0
            for ln in lengths.tolist():
                event_ids.append(raw[pos : pos + ln].decode())
                pos += ln

        return {
            "entity_ids": entity_ids,
            "target_ids": target_ids,
            "event_codes": event_codes,
            "timestamps": timestamps,
            "ratings": ratings,
            "entity_vocab": vocab(0),
            "target_vocab": vocab(1),
            "event_vocab": vocab(2),
            "event_ids": event_ids,
        }
    finally:
        lib.pio_scan_free(handle)
