"""Shared server-side TLS setup (ref ``common/.../SSLConfiguration.scala:33``
— one keystore config served both the event server and the engine server).

Both aiohttp servers (event server, query server) build their SSLContext
here so TLS policy changes (minimum version, cert reload) happen once.
"""

from __future__ import annotations


def server_ssl_context(certfile: str | None, keyfile: str | None):
    """SSLContext from a cert/key pair; None when TLS is off.

    Raises when exactly one of the pair is set — that misconfiguration
    would otherwise silently serve plaintext.
    """
    if bool(certfile) != bool(keyfile):
        raise ValueError(
            "TLS misconfigured: both ssl_certfile and ssl_keyfile are required"
        )
    if not certfile:
        return None
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx
