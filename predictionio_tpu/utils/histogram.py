"""Serving-latency histogram.

The reference only tracked request count + running average
(``CreateServer.scala:400-402``); BASELINE.md requires real latency
percentiles (p50 target < 10 ms), so the measurement machinery is
first-class here: exponential-bucket histogram, O(1) observe, exact-ish
percentiles.
"""

from __future__ import annotations

import math
import threading


class LatencyHistogram:
    """Exponential buckets from 10us to ~100s, factor 1.25."""

    FACTOR = 1.25
    MIN_SEC = 1e-5

    def __init__(self):
        self._lock = threading.Lock()
        n = int(math.log(1e7, self.FACTOR)) + 2  # covers up to ~1e2 s
        self._buckets = [0] * n
        self._count = 0
        self._sum = 0.0
        self._max = 0.0

    def _index(self, sec: float) -> int:
        if sec <= self.MIN_SEC:
            return 0
        i = int(math.log(sec / self.MIN_SEC, self.FACTOR)) + 1
        return min(i, len(self._buckets) - 1)

    def observe(self, sec: float) -> None:
        with self._lock:
            self._buckets[self._index(sec)] += 1
            self._count += 1
            self._sum += sec
            self._max = max(self._max, sec)

    def _bucket_upper(self, i: int) -> float:
        return self.MIN_SEC * (self.FACTOR ** i)

    def _percentile_of(
        self, buckets: list[int], count: int, mx: float, q: float
    ) -> float:
        if count == 0:
            return 0.0
        target = q * count
        acc = 0
        for i, c in enumerate(buckets):
            acc += c
            if acc >= target:
                return self._bucket_upper(i)
        return mx

    def percentile(self, q: float) -> float:
        with self._lock:
            return self._percentile_of(self._buckets, self._count, self._max, q)

    def summary(self) -> dict:
        # ONE snapshot under the lock: re-reading live state per percentile
        # could report a p99 above the reported max when observe() lands
        # between the reads
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
            buckets = list(self._buckets)
        if count == 0:
            return {"count": 0}
        return {
            "count": count,
            "mean_ms": 1000.0 * total / count,
            "p50_ms": 1000.0 * self._percentile_of(buckets, count, mx, 0.50),
            "p95_ms": 1000.0 * self._percentile_of(buckets, count, mx, 0.95),
            "p99_ms": 1000.0 * self._percentile_of(buckets, count, mx, 0.99),
            "max_ms": 1000.0 * mx,
        }
