"""predictionio_tpu — a TPU-native ML serving framework.

A ground-up rebuild of the capabilities of Apache PredictionIO (reference:
event collection REST API, pluggable event/metadata/model storage, templated
DASE engines, train -> model repository -> deploy lifecycle, metric-driven
evaluation, low-latency query serving) with the execution substrate replaced
by JAX/XLA on TPU: sharded `jax.Array` ingestion instead of Spark RDDs,
`jit`/`shard_map` over an ICI/DCN `jax.sharding.Mesh` instead of a Spark
cluster, pytree checkpoints instead of Kryo blobs, and asyncio HTTP servers
instead of Akka/Spray.

Layer map (mirrors reference SURVEY.md section 1):
  - ``predictionio_tpu.data``       event model + storage SPI + event server (ref: data/)
  - ``predictionio_tpu.controller`` DASE controller API (ref: core/ controller)
  - ``predictionio_tpu.workflow``   train/eval/deploy/batch-predict workflows (ref: core/ workflow)
  - ``predictionio_tpu.eval``       metrics + evaluator + grid search (ref: core/ evaluation)
  - ``predictionio_tpu.ops``        TPU math: ALS solvers, top-k, cooccurrence (pallas/XLA)
  - ``predictionio_tpu.parallel``   mesh construction, sharding, host->device ingest
  - ``predictionio_tpu.models``     bundled engine templates (ref: examples/)
  - ``predictionio_tpu.e2``         engine-building algorithm library (ref: e2/)
  - ``predictionio_tpu.tools``      CLI + admin/dashboard servers (ref: tools/)
"""

__version__ = "0.1.0"
