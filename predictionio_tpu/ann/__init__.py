"""On-device ANN retrieval: a clustered (IVF) MIPS index as a registry
artifact, so candidate generation stops being O(corpus) per query.

- :mod:`predictionio_tpu.ann.index` — k-means build / incremental
  refresh, padded-bucket layout, optional int8 quantization, the
  pickle-free artifact wire format.
- :mod:`predictionio_tpu.ann.search` — the two-stage jitted search
  kernels (centroid probe -> gathered-bucket scoring -> fused top-k on
  the shared ops/topk pack format).
- :mod:`predictionio_tpu.ann.lifecycle` — registry integration (build at
  train, stream refresh, serving attach) and the :class:`AnnServing`
  wrapper the engines consult.
- :mod:`predictionio_tpu.ann.metrics` — the ``pio_ann_*`` family.

docs/ann.md walks the layout, lifecycle, and the recall/latency knobs.
"""

from predictionio_tpu.ann.index import (
    AnnConfig,
    AnnIndex,
    build_index,
    default_clusters,
    default_nprobe,
    deserialize_index,
    refresh_index,
    serialize_index,
)

__all__ = [
    "AnnConfig",
    "AnnIndex",
    "build_index",
    "default_clusters",
    "default_nprobe",
    "deserialize_index",
    "refresh_index",
    "serialize_index",
]
