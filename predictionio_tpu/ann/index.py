"""Clustered MIPS index: build, refresh, and a pickle-free wire format.

The IVF layout (ALX-style: everything a dense batched matmul over
device-resident tables, no host pointer-chasing):

  - ``centroids``    [C, f] float32 — k-means cluster centers over the
    item-embedding table.
  - ``bucket_ids``   [C, cap] int32 — the item indices of each cluster,
    padded to one shared power-of-two capacity with ``-1`` (pad slots are
    masked to ``-inf`` inside the search kernel and never surface).
  - ``bucket_vecs``  [C, cap, f] — each cluster's item vectors, gathered
    into the padded layout so stage-2 scoring is ONE
    ``einsum("bf,bpcf->bpc")`` over the probed buckets. float32, or int8
    with a per-item ``bucket_scale`` [C, cap] when ``quantize_int8`` is
    on (the int8 pass keeps HBM at a quarter and the exact f32 rescore of
    the survivors restores the ranking).

Build is batched Lloyd iterations: the O(n*C) assignment runs as a jitted
chunked distance matmul on device; the centroid update is a deterministic
host scatter-add (numpy, seeded init) so the same embeddings always build
byte-identical indexes — content addressing in the registry then dedupes
identical rebuilds for free.

Serialization is a deliberate non-pickle framing (magic + json header +
raw array bytes): index artifacts live in the registry blob store next to
model blobs, and a corrupted index must surface as an integrity error,
never as a pickle of garbage (same posture as ``registry/store.py``).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np

_MAGIC = b"PIOANN01"

# capacity-planner padding model: buckets are padded to a shared pow2
# capacity; a perfectly balanced build lands near next_pow2(n/C), skew
# costs more. estimate_ann (obs/xray) prices 2x the balanced mean.
PAD_SKEW_MODEL = 2


def next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def default_clusters(n_items: int) -> int:
    """k chosen from corpus size: ~4*sqrt(n) rounded to a power of two.
    More clusters than the classic sqrt(n) because the padded gather
    volume per probe is ``bucket_capacity ~ 2n/C`` — finer clusters keep
    each probe slab small enough that the stage-2 gather stays cache- and
    HBM-friendly (measured: the same 2% candidate fraction runs ~6x
    faster at C=1024/cap=256 than at C=512/cap=512 on a 100k corpus).
    Clamped so the balanced mean bucket keeps >= ~8 items."""
    if n_items <= 0:
        return 1
    c = next_pow2(int(round(4.0 * float(n_items) ** 0.5)))
    return max(1, min(c, 8192, next_pow2(n_items) // 8 or 1))


def default_nprobe(clusters: int) -> int:
    """Probe width at build time: clusters/128 with a floor of 16. The
    floor carries small corpora (fewer clusters per data mode -> a higher
    probe fraction is needed for the same recall: measured 0.936@8 vs
    0.998@16 on an 8k corpus at C=512), while the 1/128 ratio keeps the
    candidate set ~1-4% of large corpora. The recall harness in
    tests/test_ann.py measures this across nprobe settings rather than
    trusting it."""
    return min(clusters, max(16, clusters // 128))


@dataclasses.dataclass(frozen=True)
class AnnConfig:
    """Build/refresh knobs (docs/ann.md walks the tradeoffs)."""

    clusters: int = 0  # 0 = default_clusters(corpus)
    nprobe: int = 0  # 0 = default_nprobe(clusters); serve-time default
    build_iters: int = 10  # Lloyd iterations
    seed: int = 0
    quantize_int8: bool = False
    rescore: int = 4  # int8 path: exact-rescore pool = rescore * k
    # corpus-size threshold: below it no index is built and exact serving
    # stays the default (the fused O(corpus) matmul wins at small n)
    min_items: int = 50_000
    # stream refresh: fraction of items whose nearest centroid changed
    # before the incremental rebucket is distrusted and a full k-means
    # rebuild is triggered
    refresh_drift: float = 0.25
    assign_chunk: int = 16_384  # items per jitted assignment call

    def resolved(self, n_items: int) -> "AnnConfig":
        """Fill the auto (0) fields from the corpus size."""
        clusters = self.clusters or default_clusters(n_items)
        clusters = max(1, min(clusters, max(1, n_items)))
        nprobe = self.nprobe or default_nprobe(clusters)
        return dataclasses.replace(
            self, clusters=clusters, nprobe=min(nprobe, clusters)
        )


@dataclasses.dataclass
class AnnIndex:
    """One built index + the metadata its manifest entry records."""

    centroids: np.ndarray  # [C, f] f32
    bucket_ids: np.ndarray  # [C, cap] int32, -1 padded
    bucket_vecs: np.ndarray  # [C, cap, f] f32 (or int8 when quantized)
    bucket_scale: np.ndarray | None  # [C, cap] f32, int8 mode only
    # raw nearest-centroid assignment [n] int32 (BEFORE the balanced
    # spill): the refresh drift guard compares against this, so overflow
    # spill can't masquerade as drift
    nearest_assign: np.ndarray | None
    n_items: int
    nprobe: int
    model_version: str = ""  # registry version whose vectors built this
    built_from: str = ""  # "train" | "refresh" | "rebuild"
    config: AnnConfig = dataclasses.field(default_factory=AnnConfig)

    @property
    def clusters(self) -> int:
        return int(self.centroids.shape[0])

    @property
    def dim(self) -> int:
        return int(self.centroids.shape[1])

    @property
    def bucket_cap(self) -> int:
        return int(self.bucket_ids.shape[1])

    @property
    def quantized(self) -> bool:
        return self.bucket_scale is not None

    def assignments(self) -> np.ndarray:
        """[n_items] nearest-centroid id per item — the drift baseline.
        Falls back to bucket membership for pre-spill artifacts."""
        if self.nearest_assign is not None:
            return np.asarray(self.nearest_assign, np.int32)
        out = np.full(self.n_items, -1, np.int32)
        for c in range(self.clusters):
            ids = self.bucket_ids[c]
            ids = ids[ids >= 0]
            out[ids] = c
        return out

    def hbm_bytes(self) -> int:
        """Resident device footprint (what the capacity planner prices)."""
        total = self.centroids.nbytes + self.bucket_ids.nbytes
        total += self.bucket_vecs.nbytes
        if self.bucket_scale is not None:
            total += self.bucket_scale.nbytes
        return int(total)

    def manifest_meta(self) -> dict[str, Any]:
        """The ``ann_index`` manifest entry (minus the store-owned
        sha256/bytes fields)."""
        return {
            "items": self.n_items,
            "dim": self.dim,
            "clusters": self.clusters,
            "bucketCap": self.bucket_cap,
            "nprobe": self.nprobe,
            "quantized": self.quantized,
            "hbmBytes": self.hbm_bytes(),
            "modelVersion": self.model_version,
            "builtFrom": self.built_from,
        }


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def _assign(vecs: np.ndarray, centroids, chunk: int) -> np.ndarray:
    """Nearest-centroid assignment for every row of ``vecs`` — the O(n*C)
    half of Lloyd, chunked through one jitted distance matmul per slab so
    the [chunk, C] score matrix never outgrows device memory."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def nearest(x, c):
        # argmin ||x - c||^2 == argmin (||c||^2 - 2 x.c); ||x||^2 is a
        # per-row constant that cannot move the argmin
        d = (c * c).sum(axis=1)[None, :] - 2.0 * (x @ c.T)
        return jnp.argmin(d, axis=1).astype(jnp.int32)

    from predictionio_tpu.obs import xray

    c_dev = jnp.asarray(centroids)
    out = np.empty(len(vecs), np.int32)
    for start in range(0, len(vecs), chunk):
        sl = vecs[start : start + chunk]
        out[start : start + len(sl)] = xray.device_fetch(
            nearest(jnp.asarray(sl), c_dev), "ann-assign"
        )
    return out


def kmeans(
    vecs: np.ndarray, clusters: int, iters: int, seed: int, chunk: int
) -> tuple[np.ndarray, np.ndarray]:
    """Batched Lloyd: jitted chunked assignment + deterministic host
    update. Returns (centroids [C,f] f32, assignment [n] int32). Empty
    clusters are re-seeded from the member of the fattest cluster farthest
    from its centroid — deterministic, and it splits exactly the cluster
    whose padding would otherwise dominate the bucket capacity."""
    vecs = np.ascontiguousarray(vecs, np.float32)
    n = len(vecs)
    clusters = max(1, min(clusters, n))
    rng = np.random.default_rng(seed)
    centroids = vecs[rng.choice(n, size=clusters, replace=False)].copy()
    assign = np.zeros(n, np.int32)
    for _ in range(max(1, iters)):
        assign = _assign(vecs, centroids, chunk)
        sums = np.zeros_like(centroids, dtype=np.float64)
        np.add.at(sums, assign, vecs)
        counts = np.bincount(assign, minlength=clusters)
        empty = np.flatnonzero(counts == 0)
        nonempty = counts > 0
        centroids[nonempty] = (
            sums[nonempty] / counts[nonempty, None]
        ).astype(np.float32)
        for e in empty:
            fat = int(np.argmax(counts))
            members = np.flatnonzero(assign == fat)
            d = ((vecs[members] - centroids[fat]) ** 2).sum(axis=1)
            far = members[int(np.argmax(d))]
            centroids[e] = vecs[far]
            # hand the stolen point over so the same donor isn't re-picked
            assign[far] = e
            counts[fat] -= 1
            counts[e] += 1
    assign = _assign(vecs, centroids, chunk)
    return centroids, assign


def bucket_capacity(n_items: int, clusters: int) -> int:
    """The shared padded bucket capacity: pow2 of 2x the balanced mean —
    the rule that bounds the probe-time gather volume. A skew-free build
    half-fills it; skew spills instead of inflating every bucket (the
    fattest-cluster rule blew the padded gather volume ~5x on real
    builds, which is exactly the O(corpus) creep this subsystem exists to
    kill). Mirrored by ``obs/xray.estimate_ann``."""
    mean = -(-n_items // max(1, clusters))
    return next_pow2(max(1, PAD_SKEW_MODEL * mean))


def _bucketize(
    vecs: np.ndarray,
    assign: np.ndarray,
    clusters: int,
    centroids: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Capacity-bounded scatter into the padded [C, cap] layout.

    Buckets are capped at :func:`bucket_capacity`; an overflowing cluster
    keeps its cap members CLOSEST to the centroid and spills the rest to
    their nearest cluster with space (in increasing spill-distance order
    — deterministic). Probing several clusters recovers spilled boundary
    items; the recall harness measures the cost instead of assuming it.

    Returns (bucket_ids, bucket_vecs, balanced_assign) where
    ``balanced_assign`` is the post-spill bucket membership.
    """
    n = len(assign)
    cap = bucket_capacity(n, clusters)
    balanced = assign.astype(np.int32).copy()
    counts = np.bincount(balanced, minlength=clusters)
    if int(counts.max(initial=0)) > cap:
        # distance of each item to its assigned centroid (for keep/spill)
        d_own = ((vecs - centroids[balanced]) ** 2).sum(axis=1)
        spilled: list[int] = []
        for c in np.flatnonzero(counts > cap):
            members = np.flatnonzero(balanced == c)
            order = members[np.argsort(d_own[members], kind="stable")]
            spilled.extend(order[cap:])
        counts = np.minimum(counts, cap)
        # nearest-with-space, nearest-first: deterministic greedy
        sp = np.asarray(spilled, np.int64)
        d_all = (
            (centroids * centroids).sum(axis=1)[None, :]
            - 2.0 * (vecs[sp] @ centroids.T)
        )
        pref = np.argsort(d_all, axis=1, kind="stable")
        best = d_all[np.arange(len(sp)), pref[:, 0]]
        for row in np.argsort(best, kind="stable"):
            item = int(sp[row])
            for c in pref[row]:
                if counts[c] < cap:
                    balanced[item] = c
                    counts[c] += 1
                    break
    bucket_ids = np.full((clusters, cap), -1, np.int32)
    order = np.argsort(balanced, kind="stable")
    sorted_assign = balanced[order]
    starts = np.searchsorted(sorted_assign, np.arange(clusters))
    pos = np.arange(n) - starts[sorted_assign]
    bucket_ids[sorted_assign, pos] = order
    bucket_vecs = vecs[np.maximum(bucket_ids, 0)].astype(np.float32)
    bucket_vecs[bucket_ids < 0] = 0.0
    return bucket_ids, bucket_vecs, balanced


def _quantize_int8(
    bucket_vecs: np.ndarray, bucket_ids: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Per-item symmetric int8: scale = max|x| / 127 per row. Pad rows get
    scale 0 (their dequantized vector is exactly zero)."""
    amax = np.abs(bucket_vecs).max(axis=2)
    scale = (amax / 127.0).astype(np.float32)
    scale[bucket_ids < 0] = 0.0
    safe = np.where(scale > 0, scale, 1.0)[..., None]
    q = np.clip(np.rint(bucket_vecs / safe), -127, 127).astype(np.int8)
    q[bucket_ids < 0] = 0
    return q, scale


def build_index(
    vectors: np.ndarray,
    config: AnnConfig | None = None,
    *,
    model_version: str = "",
    built_from: str = "train",
) -> AnnIndex:
    """Full build: k-means + bucketize (+ optional int8 quantize).
    Deterministic for (vectors, config): the registry's content addressing
    dedupes identical rebuilds."""
    config = (config or AnnConfig()).resolved(len(vectors))
    vecs = np.ascontiguousarray(vectors, np.float32)
    if vecs.ndim != 2 or len(vecs) == 0:
        raise ValueError(f"need a [n, f] vector table, got shape {vecs.shape}")
    centroids, assign = kmeans(
        vecs, config.clusters, config.build_iters, config.seed,
        config.assign_chunk,
    )
    return _finish(vecs, centroids, assign, config, model_version, built_from)


def _finish(
    vecs: np.ndarray,
    centroids: np.ndarray,
    assign: np.ndarray,
    config: AnnConfig,
    model_version: str,
    built_from: str,
) -> AnnIndex:
    clusters = len(centroids)
    bucket_ids, bucket_vecs, _balanced = _bucketize(
        vecs, assign, clusters, centroids
    )
    bucket_scale = None
    if config.quantize_int8:
        bucket_vecs, bucket_scale = _quantize_int8(bucket_vecs, bucket_ids)
    return AnnIndex(
        centroids=centroids.astype(np.float32),
        bucket_ids=bucket_ids,
        bucket_vecs=bucket_vecs,
        bucket_scale=bucket_scale,
        nearest_assign=assign.astype(np.int32),
        n_items=len(vecs),
        nprobe=min(config.nprobe, clusters),
        model_version=model_version,
        built_from=built_from,
        config=config,
    )


def refresh_index(
    index: AnnIndex,
    vectors: np.ndarray,
    *,
    model_version: str = "",
) -> tuple[AnnIndex, dict[str, Any]]:
    """Incremental refresh: assign the NEW vector table (updated + grown
    items) to the EXISTING centroids and rebucket — no k-means. When the
    assignment drift (fraction of surviving items whose nearest centroid
    moved) crosses ``config.refresh_drift``, or the geometry changed
    (dim), the centroids are stale and a full rebuild runs instead.

    Returns (new index, report) where report carries the drift fraction
    and which path ran — the stream layer publishes both."""
    vecs = np.ascontiguousarray(vectors, np.float32)
    cfg = index.config
    if vecs.ndim != 2 or len(vecs) == 0:
        raise ValueError(f"need a [n, f] vector table, got shape {vecs.shape}")
    if vecs.shape[1] != index.dim:
        rebuilt = build_index(
            vecs, cfg, model_version=model_version, built_from="rebuild"
        )
        return rebuilt, {"path": "rebuild", "drift": 1.0, "reason": "dim-changed"}
    assign = _assign(vecs, index.centroids, cfg.assign_chunk)
    prev = index.assignments()
    shared = min(len(prev), len(assign))
    drift = (
        float(np.mean(assign[:shared] != prev[:shared])) if shared else 1.0
    )
    if drift > cfg.refresh_drift:
        rebuilt = build_index(
            vecs, cfg, model_version=model_version, built_from="rebuild"
        )
        return rebuilt, {
            "path": "rebuild",
            "drift": round(drift, 4),
            "reason": "drift-guard",
        }
    refreshed = _finish(
        vecs, index.centroids, assign, cfg, model_version, "refresh"
    )
    return refreshed, {"path": "refresh", "drift": round(drift, 4)}


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


class AnnFormatError(RuntimeError):
    """The blob is not a well-formed ANN index artifact."""


def serialize_index(index: AnnIndex) -> bytes:
    """magic + [u32 header length] + json header + raw C-order array
    bytes, concatenated in header order. Deterministic for equal indexes."""
    arrays: dict[str, np.ndarray] = {
        "centroids": index.centroids,
        "bucket_ids": index.bucket_ids,
        "bucket_vecs": index.bucket_vecs,
    }
    if index.bucket_scale is not None:
        arrays["bucket_scale"] = index.bucket_scale
    if index.nearest_assign is not None:
        arrays["nearest_assign"] = index.nearest_assign
    header = {
        "meta": {
            "n_items": index.n_items,
            "nprobe": index.nprobe,
            "model_version": index.model_version,
            "built_from": index.built_from,
            "config": dataclasses.asdict(index.config),
        },
        "arrays": [
            {
                "name": name,
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
            }
            for name, arr in arrays.items()
        ],
    }
    head = json.dumps(header, sort_keys=True, separators=(",", ":")).encode()
    parts = [_MAGIC, len(head).to_bytes(4, "big"), head]
    for arr in arrays.values():
        parts.append(np.ascontiguousarray(arr).tobytes())
    return b"".join(parts)


def deserialize_index(blob: bytes) -> AnnIndex:
    if blob[: len(_MAGIC)] != _MAGIC:
        raise AnnFormatError("not an ANN index artifact (bad magic)")
    off = len(_MAGIC)
    head_len = int.from_bytes(blob[off : off + 4], "big")
    off += 4
    try:
        header = json.loads(blob[off : off + head_len].decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise AnnFormatError(f"corrupt index header: {exc}") from exc
    off += head_len
    arrays: dict[str, np.ndarray] = {}
    for spec in header["arrays"]:
        dtype = np.dtype(spec["dtype"])
        shape = tuple(int(s) for s in spec["shape"])
        n_bytes = dtype.itemsize * int(np.prod(shape)) if shape else dtype.itemsize
        raw = blob[off : off + n_bytes]
        if len(raw) != n_bytes:
            raise AnnFormatError(
                f"truncated index artifact at array {spec['name']!r}"
            )
        arrays[spec["name"]] = np.frombuffer(raw, dtype).reshape(shape).copy()
        off += n_bytes
    meta = header["meta"]
    known = {f.name for f in dataclasses.fields(AnnConfig)}
    config = AnnConfig(
        **{k: v for k, v in (meta.get("config") or {}).items() if k in known}
    )
    try:
        return AnnIndex(
            centroids=arrays["centroids"],
            bucket_ids=arrays["bucket_ids"],
            bucket_vecs=arrays["bucket_vecs"],
            bucket_scale=arrays.get("bucket_scale"),
            nearest_assign=arrays.get("nearest_assign"),
            n_items=int(meta["n_items"]),
            nprobe=int(meta["nprobe"]),
            model_version=str(meta.get("model_version", "")),
            built_from=str(meta.get("built_from", "")),
            config=config,
        )
    except KeyError as exc:
        raise AnnFormatError(f"index artifact missing field {exc}") from exc
