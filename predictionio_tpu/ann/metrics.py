"""The ``pio_ann_*`` metric family (docs/observability.md).

One instrument set serves both surfaces: the QueryServer registers it so
serving traffic through a pinned index is visible (probes, candidates
scored, sampled recall), and the stream pipeline registers it so index
refresh/rebuild activity rides the same scrape. Registration is eager —
the family exists (zero) from process start, so scrapers and the docs
metrics-contract test see it before the first ANN query.
"""

from __future__ import annotations

from predictionio_tpu.obs.metrics import MetricsRegistry


class AnnInstruments:
    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.queries = r.counter(
            "pio_ann_queries_total",
            "queries answered through the ANN index (candidate generation "
            "skipped the exact O(corpus) scan)",
        )
        self.fallbacks = r.counter(
            "pio_ann_fallback_total",
            "queries an ANN-capable lane answered exactly instead "
            "(k wider than the probe pool, or a filtered int8 index)",
        )
        self.probes = r.counter(
            "pio_ann_probes_total", "clusters probed, summed over queries"
        )
        self.candidates = r.counter(
            "pio_ann_candidates_total",
            "real (non-pad) candidate items scored, summed over queries",
        )
        self.candidates_frac = r.gauge(
            "pio_ann_candidates_frac",
            "candidates scored per query as a fraction of the corpus "
            "(mean over the last fetched batch)",
        )
        self.recall_sampled = r.gauge(
            "pio_ann_recall_sampled",
            "recall@k of the ANN top-k vs a shadow exact top-k on sampled "
            "batches (EWMA)",
        )
        self.recall_samples = r.counter(
            "pio_ann_recall_samples_total",
            "batches shadow-scored exactly for the recall proxy",
        )
        self.index_items = r.gauge(
            "pio_ann_index_items",
            "corpus items covered by the pinned index",
            labelnames=("version",),
        )
        self.index_clusters = r.gauge(
            "pio_ann_index_clusters",
            "clusters in the pinned index",
            labelnames=("version",),
        )
        self.refreshes = r.counter(
            "pio_ann_refreshes_total",
            "incremental index refreshes (rebucket onto existing centroids) "
            "published by the stream layer",
        )
        self.rebuilds = r.counter(
            "pio_ann_rebuilds_total",
            "full index rebuilds (drift guard or geometry change)",
        )
        # version-labeled index gauges ever set through this instrument
        # set — sync_indexes zeroes the retired ones so a reloaded lane's
        # old version stops rendering as pinned
        self._known_versions: set[str] = set()

    def set_index(self, version: str, items: float, clusters: float) -> None:
        self.index_items.set(float(items), version=version)
        self.index_clusters.set(float(clusters), version=version)
        self._known_versions = self._known_versions | {version}

    def sync_indexes(self, indexes: dict[str, tuple[float, float]]) -> None:
        """Reconcile the version-labeled gauges against the CURRENTLY
        pinned indexes (the query server calls this at scrape time from
        its live lanes): set every live series, zero every previously
        known version that is no longer pinned — `pio top` filters on
        value > 0, so a retired index disappears instead of rendering as
        pinned forever after a reload."""
        for version, (items, clusters) in indexes.items():
            self.set_index(version, items, clusters)
        for stale in self._known_versions - set(indexes):
            self.index_items.set(0.0, version=stale)
            self.index_clusters.set(0.0, version=stale)
        self._known_versions = set(indexes)
