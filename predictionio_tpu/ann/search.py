"""On-device two-stage IVF-MIPS search.

Stage 1: ``query @ centroids.T`` -> top-``nprobe`` clusters (one [B, C]
matmul — C is hundreds, not the corpus). Stage 2: gather each probed
bucket as one contiguous padded slab, score the [B, P*cap, f] candidates
with one batched matmul, mask pads/filters to ``-inf``, and end on the
shared fused top-k wire format (``ops/topk.pack_batch``: [B, 2, k] int32,
score bits in row 0). The fetch stays O(batch * k) — candidate generation
no longer touches the other ~(1 - nprobe*cap/n) of the corpus.

Kernel discipline mirrors ops/topk: one compiled program per (pow2 batch,
k, nprobe) bucket; the index tables ride resident and are never donated;
the per-batch query/mask uploads are donated. The int8 variant scores the
quantized buckets, keeps a ``rescore * k`` survivor pool, gathers those
rows from the resident exact f32 table and re-scores them exactly before
the final top-k.

Each search returns TWO device arrays — the packed top-k and a [B] int32
count of real (non-pad) candidates scored — fetched together in
:meth:`AnnSearcher.fetch`; the count feeds the ``pio_ann_candidates_*``
metrics and the <=10%-of-corpus acceptance measurement.
"""

from __future__ import annotations

import functools

import numpy as np

from predictionio_tpu.ann.index import AnnIndex

__all__ = ["AnnSearcher"]


def _kernels():
    """jit-compiled kernel set, built lazily so importing the ann package
    never drags jax in (pio top / pio models are stdlib-light).

    Stage 2 gathers each probed bucket as ONE contiguous ``cap*f`` slab
    (the tables ride flattened [C, cap*f]) and scores the reshaped
    [B, P*cap, f] candidates with one batched matmul against the query —
    big-row gathers are memcpy-shaped on every backend, where the naive
    [B, P, cap, f] element gather + einsum measured ~7x slower on CPU.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from predictionio_tpu.ops.topk import pack_batch

    def _stage1(centroids, q, nprobe: int):
        cs = q @ centroids.T  # [B, C]
        _, probe = lax.top_k(cs, nprobe)
        return probe  # [B, nprobe]

    def _flat_candidates(bucket_flat, bucket_ids, q, probe):
        b, f = q.shape
        vecs = bucket_flat[probe].reshape(b, -1, f)  # [B, P*cap, f]
        ids = bucket_ids[probe].reshape(b, -1)  # [B, P*cap]
        scores = jnp.matmul(vecs, q[:, :, None])[:, :, 0]
        return scores, ids

    def _counts(ids):
        return (ids >= 0).sum(axis=1).astype(jnp.int32)

    @functools.partial(
        jax.jit, static_argnames=("nprobe", "k"), donate_argnums=(3,)
    )
    def search(centroids, bucket_flat, bucket_ids, q, nprobe: int, k: int):
        probe = _stage1(centroids, q, nprobe)
        flat_s, flat_i = _flat_candidates(bucket_flat, bucket_ids, q, probe)
        flat_s = jnp.where(flat_i >= 0, flat_s, -jnp.inf)
        s, pos = lax.top_k(flat_s, k)
        items = jnp.take_along_axis(flat_i, pos, axis=1)
        return pack_batch(s, items), _counts(flat_i)

    @functools.partial(
        jax.jit, static_argnames=("nprobe", "k"), donate_argnums=(3, 4)
    )
    def search_excl(
        centroids, bucket_flat, bucket_ids, q, excl, nprobe: int, k: int
    ):
        """``excl`` [B, E] int32 item ids never returned (a query's own
        items) — pad with -1, which matches no candidate."""
        probe = _stage1(centroids, q, nprobe)
        flat_s, flat_i = _flat_candidates(bucket_flat, bucket_ids, q, probe)
        hit = (flat_i[:, :, None] == excl[:, None, :]).any(axis=2)
        flat_s = jnp.where((flat_i >= 0) & ~hit, flat_s, -jnp.inf)
        s, pos = lax.top_k(flat_s, k)
        items = jnp.take_along_axis(flat_i, pos, axis=1)
        return pack_batch(s, items), _counts(flat_i)

    @functools.partial(
        jax.jit, static_argnames=("nprobe", "k"), donate_argnums=(3, 4)
    )
    def search_masked(
        centroids, bucket_flat, bucket_ids, q, mask, nprobe: int, k: int
    ):
        """``mask`` [B, n] bool over the FULL corpus (the engines' existing
        candidate masks); candidate rows gather their own mask bit."""
        probe = _stage1(centroids, q, nprobe)
        flat_s, flat_i = _flat_candidates(bucket_flat, bucket_ids, q, probe)
        ok = jnp.take_along_axis(mask, jnp.maximum(flat_i, 0), axis=1)
        flat_s = jnp.where((flat_i >= 0) & ok, flat_s, -jnp.inf)
        s, pos = lax.top_k(flat_s, k)
        items = jnp.take_along_axis(flat_i, pos, axis=1)
        return pack_batch(s, items), _counts(flat_i)

    @functools.partial(
        jax.jit,
        static_argnames=("nprobe", "k", "pool"),
        donate_argnums=(5, 6),
    )
    def search_q8(
        centroids,
        bucket_q8_flat,
        bucket_scale,
        bucket_ids,
        exact_table,
        q,
        excl,
        nprobe: int,
        k: int,
        pool: int,
    ):
        """int8 score pass + exact f32 rescore of the ``pool`` survivors.
        ``exact_table`` [n, f] is the engine's resident full-precision
        table — gathered only at the survivor rows. The int8 dot rides
        the same slab-gather shape; the per-item scale multiplies the
        scalar score, not the vectors. ``excl`` [B, E] int32 (-1 padded)
        works exactly as in ``search_excl`` — exclusion compares ids, it
        never needs the full-precision vectors, so the similarproduct
        filter-less dispatch stays on the int8 path."""
        probe = _stage1(centroids, q, nprobe)
        b, f = q.shape
        vq = bucket_q8_flat[probe].reshape(b, -1, f).astype(jnp.float32)
        flat_i = bucket_ids[probe].reshape(b, -1)
        scale = bucket_scale[probe].reshape(b, -1)
        flat_s = jnp.matmul(vq, q[:, :, None])[:, :, 0] * scale
        hit = (flat_i[:, :, None] == excl[:, None, :]).any(axis=2)
        flat_s = jnp.where((flat_i >= 0) & ~hit, flat_s, -jnp.inf)
        ps, pos = lax.top_k(flat_s, pool)
        cand = jnp.take_along_axis(flat_i, pos, axis=1)  # [B, pool]
        cvec = exact_table[jnp.maximum(cand, 0)]  # [B, pool, f]
        es = jnp.matmul(cvec, q[:, :, None])[:, :, 0]
        es = jnp.where((cand >= 0) & jnp.isfinite(ps), es, -jnp.inf)
        s, p2 = lax.top_k(es, k)
        items = jnp.take_along_axis(cand, p2, axis=1)
        return pack_batch(s, items), _counts(flat_i)

    return search, search_excl, search_masked, search_q8


_KERNELS = None


class AnnSearcher:
    """Device-resident index tables + the jitted two-stage search.

    ``exact_table`` (the engine's resident [n, f] device table) is
    required for the int8 rescore path and ignored otherwise.
    """

    def __init__(self, index: AnnIndex, exact_table=None):
        import jax.numpy as jnp

        global _KERNELS
        if _KERNELS is None:
            _KERNELS = _kernels()
        self.index = index
        self._centroids = jnp.asarray(index.centroids)
        self._bucket_ids = jnp.asarray(index.bucket_ids)
        # resident flattened [C, cap*f]: stage 2 gathers one contiguous
        # slab per probed cluster (see _kernels)
        c = index.clusters
        self._bucket_flat = jnp.asarray(index.bucket_vecs.reshape(c, -1))
        self._bucket_scale = (
            jnp.asarray(index.bucket_scale)
            if index.bucket_scale is not None
            else None
        )
        self._exact_table = exact_table
        if index.bucket_scale is not None and exact_table is None:
            raise ValueError(
                "an int8-quantized index needs the engine's exact f32 table "
                "for survivor rescoring"
            )

    @property
    def n_items(self) -> int:
        return self.index.n_items

    @property
    def nprobe(self) -> int:
        return self.index.nprobe

    def candidate_pool(self, nprobe: int | None = None) -> int:
        """Upper bound of candidates one query can score (pads included)."""
        return (nprobe or self.nprobe) * self.index.bucket_cap

    def supports(self, k: int, nprobe: int | None = None) -> bool:
        """Can this index answer top-``k``? ``lax.top_k`` needs the pool at
        least k wide; callers fall back to exact scoring when it can't."""
        return 0 < k <= self.candidate_pool(nprobe)

    def search_async(self, qvecs, k: int, *, mask=None, exclude=None,
                     nprobe: int | None = None):
        """Dispatch (no fetch). ``qvecs`` [B, f] — host numpy or a device
        array (e.g. the two-tower user embedding handle, composed without
        a host round-trip). At most one of ``mask`` ([B, n] bool) /
        ``exclude`` ([B, E] int32, -1 padded) may be given. Returns the
        (packed [B,2,k], counts [B]) device-handle pair."""
        import jax.numpy as jnp

        from predictionio_tpu.ops.als import upload

        search, search_excl, search_masked, search_q8 = _KERNELS
        nprobe = min(nprobe or self.nprobe, self.index.clusters)
        # upload() COPIES host staging buffers (scratch-pool reuse must
        # not race the in-flight kernel); device handles pass through
        q = qvecs if hasattr(qvecs, "dtype") and not isinstance(
            qvecs, np.ndarray
        ) else upload(qvecs, np.float32)
        if self._bucket_scale is not None:
            if mask is not None:
                # a [B, n] mask gather is fine on ids, but masked queries
                # carry engine filters whose exact fallback is cheap and
                # already wired — keep the int8 surface to the hot path
                raise ValueError(
                    "mask filtering is unsupported on the int8 path; "
                    "route filtered queries to the exact fallback "
                    "(AnnServing.supports(filtered=True) says so)"
                )
            pool = min(
                max(k, self.index.config.rescore * k), self.candidate_pool(nprobe)
            )
            excl = (
                upload(exclude, np.int32)
                if exclude is not None
                else jnp.full((q.shape[0], 1), -1, jnp.int32)
            )
            return search_q8(
                self._centroids,
                self._bucket_flat,
                self._bucket_scale,
                self._bucket_ids,
                self._exact_table,
                q,
                excl,
                nprobe,
                k,
                pool,
            )
        if mask is not None:
            return search_masked(
                self._centroids,
                self._bucket_flat,
                self._bucket_ids,
                q,
                upload(mask),
                nprobe,
                k,
            )
        if exclude is not None:
            return search_excl(
                self._centroids,
                self._bucket_flat,
                self._bucket_ids,
                q,
                upload(exclude, np.int32),
                nprobe,
                k,
            )
        return search(
            self._centroids, self._bucket_flat, self._bucket_ids, q, nprobe, k
        )

    @staticmethod
    def fetch(handle) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The one sanctioned fetch of an ANN search: the packed [B,2,k]
        top-k plus the [B] candidate counts — O(batch*k), never
        O(batch*corpus). Returns (scores, item indices, counts)."""
        from predictionio_tpu.ops.als import ServingIndex

        packed, counts = handle
        # pio-lint: disable=serving-host-roundtrip -- k-only packed fetch + [B] counts, the ANN wire contract
        packed_np, counts_np = np.asarray(packed), np.asarray(counts)
        scores, idx = ServingIndex.unpack_batch(packed_np)
        return scores, idx, counts_np

    def warmup(self, max_batch: int, k: int) -> None:
        """Pre-compile one search program per pow2 batch bucket (same
        discipline as ops/topk.warmup_pow2_buckets) so the first burst
        after deploy/reload pays no XLA compiles on the ANN path."""
        from predictionio_tpu.ops import topk

        dim = self.index.dim
        kk = min(topk.next_pow2(k), self.candidate_pool())

        def dispatch(b: int):
            packed, _counts = self.search_async(
                np.zeros((b, dim), np.float32), kk
            )
            return packed

        topk.warmup_pow2_buckets(max_batch, dispatch)
