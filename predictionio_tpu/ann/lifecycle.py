"""ANN index lifecycle: registry artifact <-> serving attachment.

The index is a **content-addressed registry artifact with lineage**: its
blob lives in the engine's blob store next to the model blobs, and the
model version's manifest records it under ``ann_index`` (sha256 + layout
metadata). Three producers, one consumer:

  - ``pio train`` (workflow/core_workflow.py) calls
    :func:`build_for_version` after the registry publish when the trained
    model exposes an item-vector table and the corpus clears the
    ``min_items`` threshold.
  - the stream layer (stream/pipeline.py) calls
    :func:`refresh_for_publish` on every candidate publish: new/updated
    item vectors are assigned to the parent index's centroids
    (incremental rebucket); when assignment drift crosses the guard a
    full k-means rebuild runs instead. The refreshed index rides the
    CANDIDATE version — the same publish-as-candidate discipline as the
    model itself, so a bad index can never hot-swap into stable.
  - serving (workflow/create_server.py) calls :func:`attach_from_registry`
    when loading any lane from the registry; when the manifest pins an
    index, an :class:`AnnServing` lands on the model object under the
    ``ann_serving`` attribute and the engines' dispatch paths consult it.
    No index pinned -> attribute stays None -> exact scoring, unchanged.

Model support is duck-typed on the item-vector table: two-tower
(``item_embeddings``), similarproduct's :class:`SimilarModel`
(``item_factors``), and the recommendation template's ALSModel
(``item_factors`` + ``user_factors``) — the last so the fold-in ALS
stream trainer refreshes an index end to end.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any

import numpy as np

from predictionio_tpu.ann.index import (
    AnnConfig,
    AnnIndex,
    build_index,
    deserialize_index,
    refresh_index,
    serialize_index,
)
from predictionio_tpu.ann.metrics import AnnInstruments
from predictionio_tpu.ann.search import AnnSearcher

logger = logging.getLogger(__name__)

#: attribute engines consult on their model object
ATTR = "ann_serving"

_RECALL_EWMA = 0.2


def config_from_env() -> AnnConfig:
    """Build-time knobs from the environment (the train/stream paths have
    no per-engine params surface for a cross-cutting subsystem):
    ``PIO_ANN_MIN_ITEMS`` (corpus threshold, default 50000),
    ``PIO_ANN_CLUSTERS`` / ``PIO_ANN_NPROBE`` (0 = auto),
    ``PIO_ANN_INT8`` (quantized score pass). ``PIO_ANN=0`` disables the
    build entirely (checked by the callers, not here)."""
    return AnnConfig(
        clusters=int(os.environ.get("PIO_ANN_CLUSTERS", "0") or 0),
        nprobe=int(os.environ.get("PIO_ANN_NPROBE", "0") or 0),
        min_items=int(os.environ.get("PIO_ANN_MIN_ITEMS", "50000")),
        quantize_int8=os.environ.get("PIO_ANN_INT8", "0").lower()
        in ("1", "true", "yes"),
    )


def ann_enabled() -> bool:
    return os.environ.get("PIO_ANN", "1").lower() not in ("0", "false", "off")


# ---------------------------------------------------------------------------
# model-type plumbing (duck-typed)
# ---------------------------------------------------------------------------


def item_vectors_of(model: Any) -> np.ndarray | None:
    """The model's item-vector table, or None for model types ANN does not
    apply to (popularity/cooccurrence/NB...)."""
    if hasattr(model, "item_embeddings"):  # two-tower
        # pio-lint: disable=hostsync-serving-path -- one-time lane-load/refresh materialization feeding the host-side ANN build, not per-request
        return np.asarray(model.item_embeddings, np.float32)
    if hasattr(model, "item_factors"):  # SimilarModel / ALSModel
        # pio-lint: disable=hostsync-serving-path -- one-time lane-load/refresh materialization feeding the host-side ANN build, not per-request
        return np.asarray(model.item_factors, np.float32)
    return None


def _exact_device_table(model: Any):
    """The engine's resident full-precision device table (the int8 rescore
    gathers survivor rows from it)."""
    if hasattr(model, "device_items"):
        return model.device_items()
    if hasattr(model, "device_factors"):
        return model.device_factors()
    if hasattr(model, "serving_index"):
        return model.serving_index().item_factors
    return None


def find_indexable_model(models: list[Any]) -> Any | None:
    for m in models:
        if item_vectors_of(m) is not None:
            return m
    return None


# ---------------------------------------------------------------------------
# serving wrapper
# ---------------------------------------------------------------------------


class AnnServing:
    """One pinned index wired for the dispatch path: the device searcher,
    the ``pio_ann_*`` instruments, and the shadow-exact recall sampler.

    Thread contract: dispatch threads (micro-batcher, shadow, stable
    retry) share one instance; the metrics registry's own locks make the
    counter math safe, and the sampler keeps its own lock.
    """

    def __init__(
        self,
        index: AnnIndex,
        model: Any,
        instruments: AnnInstruments | None = None,
        recall_sample_every: int | None = None,
    ):
        self.index = index
        self.searcher = AnnSearcher(
            index, exact_table=_exact_device_table(model) if index.quantized else None
        )
        self.instruments = instruments
        # 0 disables the recall shadow; None = the env default
        self._sample_every = (
            recall_sample_every
            if recall_sample_every is not None
            else int(os.environ.get("PIO_ANN_RECALL_EVERY", "64"))
        )
        self._sample_lock = threading.Lock()
        self._batches = 0
        self._recall_ewma: float | None = None
        if instruments is not None:
            self.bind(instruments)

    def bind(self, instruments: AnnInstruments) -> None:
        self.instruments = instruments
        instruments.set_index(
            self.index.model_version or "?",
            self.index.n_items,
            self.index.clusters,
        )

    # ------------------------------------------------------------- dispatch
    def supports(self, k: int, *, filtered: bool = False) -> bool:
        """False routes the batch to the exact path: a k wider than the
        probe pool, or filters on an int8 index (filter gathers need
        full-precision candidate ids). Pure — dispatch paths that fall
        back call :meth:`count_fallback` so warmup probes stay silent."""
        return self.searcher.supports(k) and not (
            filtered and self.index.quantized
        )

    def count_fallback(self, rows: int = 1) -> None:
        if self.instruments is not None and rows > 0:
            self.instruments.fallbacks.inc(rows)

    def search_async(self, qvecs, k: int, *, mask=None, exclude=None):
        return self.searcher.search_async(qvecs, k, mask=mask, exclude=exclude)

    def take_recall_sample(self) -> bool:
        """True on every Nth dispatched batch: the caller then ALSO
        dispatches its exact kernel and hands both results to
        :meth:`record_recall` — a measured recall proxy on live traffic,
        not a build-time promise."""
        with self._sample_lock:
            self._batches += 1
            return self._sample_every > 0 and (
                (self._batches - 1) % self._sample_every == 0
            )

    # --------------------------------------------------------------- fetch
    def fetch(self, handle, rows: int):
        """Fetch + account one batch: returns (scores, idx) shaped like
        ``ops.topk.fetch_topk``. ``rows`` = real (non-pad) batch rows."""
        scores, idx, counts = AnnSearcher.fetch(handle)
        ins = self.instruments
        if ins is not None and rows > 0:
            ins.queries.inc(rows)
            ins.probes.inc(rows * self.searcher.nprobe)
            real = counts[:rows]
            ins.candidates.inc(float(real.sum()))
            if self.index.n_items:
                ins.candidates_frac.set(
                    float(real.mean()) / float(self.index.n_items)
                )
        return scores, idx

    def record_recall(
        self, ann_idx: np.ndarray, exact_idx: np.ndarray, rows: int
    ) -> float | None:
        """Overlap@k of the ANN vs shadow-exact indices over the batch's
        real rows -> EWMA gauge. Returns the batch's recall."""
        rows = min(rows, len(ann_idx), len(exact_idx))
        if rows <= 0:
            return None
        k = min(ann_idx.shape[1], exact_idx.shape[1])
        if k <= 0:
            return None
        hits = 0
        for r in range(rows):
            hits += len(
                set(map(int, ann_idx[r, :k])) & set(map(int, exact_idx[r, :k]))
            )
        recall = hits / float(rows * k)
        with self._sample_lock:
            if self._recall_ewma is None:
                self._recall_ewma = recall
            else:
                self._recall_ewma += _RECALL_EWMA * (recall - self._recall_ewma)
            value = self._recall_ewma
        if self.instruments is not None:
            self.instruments.recall_samples.inc()
            self.instruments.recall_sampled.set(value)
        return recall

    def warmup(self, max_batch: int, k: int = 10) -> None:
        self.searcher.warmup(max_batch, k)


# ---------------------------------------------------------------------------
# registry lifecycle
# ---------------------------------------------------------------------------


def build_for_version(
    store: Any,
    engine_id: str,
    version: str,
    models: list[Any],
    config: AnnConfig | None = None,
    *,
    force: bool = False,
) -> dict[str, Any] | None:
    """End-of-train build: when a model in ``models`` exposes an item
    table with at least ``config.min_items`` rows (or ``force``), build
    the index, write it content-addressed, and pin it on ``version``'s
    manifest. Returns the manifest's ``ann_index`` entry, or None when no
    index applies. Never raises past the registry contract — callers keep
    publish best-effort."""
    if not ann_enabled():
        return None
    config = config or config_from_env()
    model = find_indexable_model(models)
    if model is None:
        return None
    vecs = item_vectors_of(model)
    if vecs is None or len(vecs) == 0:
        return None
    if len(vecs) < config.min_items and not force:
        logger.debug(
            "ann: corpus %d below min_items %d; exact serving stays default",
            len(vecs),
            config.min_items,
        )
        return None
    index = build_index(vecs, config, model_version=version, built_from="train")
    manifest = store.attach_ann_index(
        engine_id, version, serialize_index(index), index.manifest_meta()
    )
    logger.info(
        "ann: built index for %s (%d items, %d clusters, nprobe %d)",
        version,
        index.n_items,
        index.clusters,
        index.nprobe,
    )
    return manifest.ann_index


def refresh_for_publish(
    store: Any,
    engine_id: str,
    parent_version: str,
    version: str,
    models: list[Any],
    instruments: AnnInstruments | None = None,
) -> dict[str, Any] | None:
    """Stream-layer refresh: when the PARENT (stable) version pins an
    index and the freshly published candidate's models carry item
    vectors, re-derive the candidate's index from the parent's centroids
    (incremental) or rebuild on drift, and pin it on the candidate's
    manifest. Returns the refresh report (path + drift) or None when no
    parent index exists."""
    if not ann_enabled() or not parent_version:
        return None
    loaded = load_index(store, engine_id, parent_version)
    if loaded is None:
        return None
    model = find_indexable_model(models)
    vecs = item_vectors_of(model) if model is not None else None
    if vecs is None or len(vecs) == 0:
        return None
    refreshed, report = refresh_index(loaded, vecs, model_version=version)
    store.attach_ann_index(
        engine_id, version, serialize_index(refreshed), refreshed.manifest_meta()
    )
    if instruments is not None:
        if report["path"] == "rebuild":
            instruments.rebuilds.inc()
        else:
            instruments.refreshes.inc()
    logger.info(
        "ann: %s index for candidate %s (drift %.3f)",
        report["path"],
        version,
        report.get("drift", 0.0),
    )
    return report


def load_index(store: Any, engine_id: str, version: str) -> AnnIndex | None:
    """The verified index artifact pinned on ``version``, or None."""
    loaded = store.load_ann_blob(engine_id, version)
    if loaded is None:
        return None
    blob, _meta = loaded
    return deserialize_index(blob)


def attach_from_registry(
    store: Any,
    engine_id: str,
    version: str,
    models: list[Any],
    instruments: AnnInstruments | None = None,
) -> AnnServing | None:
    """Serving-side attach: when ``version``'s manifest pins an index,
    wire an :class:`AnnServing` onto the matching model object (attribute
    ``ann_serving``). Best-effort: a broken index artifact logs and
    leaves the lane on exact scoring — the index is an accelerator, never
    a single point of failure."""
    try:
        index = load_index(store, engine_id, version)
    except Exception:
        logger.exception(
            "ann: index artifact for %s unusable; serving exact", version
        )
        return None
    if index is None:
        return None
    model = find_indexable_model(models)
    if model is None:
        return None
    vecs = item_vectors_of(model)
    if vecs is None or len(vecs) != index.n_items:
        logger.warning(
            "ann: index for %s covers %d items but the model has %d; "
            "serving exact",
            version,
            index.n_items,
            0 if vecs is None else len(vecs),
        )
        return None
    serving = AnnServing(index, model, instruments=instruments)
    setattr(model, ATTR, serving)
    return serving


def bind_instruments(models: list[Any], instruments: AnnInstruments) -> None:
    """Late-bind the server's instruments onto any attached AnnServing
    (the attach happens in the lane loader, before the server's registry
    is in scope)."""
    for m in models:
        serving = getattr(m, ATTR, None)
        if isinstance(serving, AnnServing):
            serving.bind(instruments)


def pinned_indexes(
    model_lists: list[list[Any]],
) -> dict[str, tuple[float, float]]:
    """The (version -> (items, clusters)) map of every index attached to
    the given lanes' models — what the query server feeds
    :meth:`AnnInstruments.sync_indexes` at scrape time so retired
    versions' gauge series zero out after a reload."""
    out: dict[str, tuple[float, float]] = {}
    for models in model_lists:
        for m in models or ():
            serving = getattr(m, ATTR, None)
            if isinstance(serving, AnnServing):
                out[serving.index.model_version or "?"] = (
                    float(serving.index.n_items),
                    float(serving.index.clusters),
                )
    return out
