"""Classification template (naive Bayes + random forest).

Reference parity: ``examples/scala-parallel-classification/add-algorithm/``
— reads entity *properties* (not events), trains MLlib NaiveBayes plus an
added RandomForest, Query{attr0,attr1,attr2} -> PredictedResult{label}.
"""

from predictionio_tpu.models.classification.engine import (
    ActualResult,
    DataSource,
    DataSourceParams,
    NaiveBayesAlgorithm,
    PredictedResult,
    Preparator,
    Query,
    RandomForestAlgorithm,
    Serving,
    TrainingData,
    custom_properties_engine_factory,
    engine_factory,
)

__all__ = [
    "ActualResult",
    "DataSource",
    "DataSourceParams",
    "NaiveBayesAlgorithm",
    "PredictedResult",
    "Preparator",
    "Query",
    "RandomForestAlgorithm",
    "Serving",
    "TrainingData",
    "custom_properties_engine_factory",
    "engine_factory",
]
