"""Classification engine (DASE components).

Reference parity (behavioral):
  - DataSource aggregates user entity properties requiring
    plan/attr0/attr1/attr2; label = plan, features = attrs —
    ``add-algorithm/src/main/scala/DataSource.scala:36-75``; k-fold readEval.
  - Query {attr0, attr1, attr2} -> PredictedResult {label} —
    ``Engine.scala:23-36``.
  - Algorithms "naive" (MLlib NaiveBayes with lambda smoothing) and
    "randomforest" (added algo) — ``NaiveBayesAlgorithm.scala``,
    ``RandomForestAlgorithm.scala``. TPU build: jit-batched multinomial NB
    (ops.classify) + compact numpy random forest.
  - Serving returns the first prediction (``Serving.scala``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    JaxAlgorithm,
    LocalAlgorithm,
    Params,
    SanityCheck,
)
from predictionio_tpu.e2.cross_validation import k_fold_split
from predictionio_tpu.tuning.grid import clamp_folds
from predictionio_tpu.ops.classify import (
    NaiveBayesModel,
    RandomForestModel,
    train_naive_bayes,
    train_random_forest,
)
from predictionio_tpu.workflow.context import WorkflowContext


@dataclasses.dataclass(frozen=True)
class Query:
    attr0: float
    attr1: float
    attr2: float

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "Query":
        return Query(float(d["attr0"]), float(d["attr1"]), float(d["attr2"]))

    def to_array(self) -> np.ndarray:
        return np.array([self.attr0, self.attr1, self.attr2], np.float64)


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: float

    def to_json_dict(self) -> dict[str, Any]:
        return {"label": self.label}


@dataclasses.dataclass(frozen=True)
class ActualResult:
    label: float


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    eval_k: int | None = None
    entity_type: str = "user"
    label_property: str = "plan"
    attr_properties: tuple[str, ...] = ("attr0", "attr1", "attr2")


@dataclasses.dataclass
class TrainingData(SanityCheck):
    labels: np.ndarray  # [N]
    features: np.ndarray  # [N, F]

    def sanity_check(self) -> None:
        if len(self.labels) == 0:
            raise ValueError("no labeled entities found; check app data")
        if not np.all(np.isfinite(self.features)):
            raise ValueError("non-finite feature values present")


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def _read_points(self, ctx: WorkflowContext) -> tuple[np.ndarray, np.ndarray]:
        store = ctx.p_event_store()
        props = store.aggregate_properties(
            app_name=self.params.app_name or ctx.app_name,
            entity_type=self.params.entity_type,
            channel_name=ctx.channel_name,
            required=[self.params.label_property, *self.params.attr_properties],
        )
        labels, rows = [], []
        for _, pm in props.items():
            labels.append(float(pm.get(self.params.label_property)))
            rows.append([float(pm.get(a)) for a in self.params.attr_properties])
        return (
            np.asarray(labels, np.float64),
            np.asarray(rows, np.float64).reshape(len(labels), -1),
        )

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        labels, features = self._read_points(ctx)
        return TrainingData(labels, features)

    def read_eval(self, ctx: WorkflowContext):
        if not self.params.eval_k:
            raise ValueError("DataSourceParams.evalK must not be None")
        labels, features = self._read_points(ctx)
        indices = list(range(len(labels)))
        # an evalK beyond the corpus degrades loudly to leave-one-out
        # instead of hard-failing every grid cell (k_fold_split raises on
        # the empty test folds an oversized k would produce)
        k = clamp_folds(self.params.eval_k, len(indices), what="points")
        folds = []
        for train_idx, test_idx in k_fold_split(indices, k):
            td = TrainingData(labels[train_idx], features[train_idx])
            qa = [
                (
                    self._make_query(features[i]),
                    ActualResult(float(labels[i])),
                )
                for i in test_idx
            ]
            folds.append((td, {}, qa))
        return folds

    def _make_query(self, features_row: np.ndarray):
        """Eval-query constructor; variants with a different Query shape
        override this so read_eval stays consistent with their features."""
        return Query(*[float(x) for x in features_row[:3]])


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td


@dataclasses.dataclass(frozen=True)
class NaiveBayesParams(Params):
    lambda_: float = 1.0


class NaiveBayesAlgorithm(JaxAlgorithm):
    params_class = NaiveBayesParams
    params: NaiveBayesParams

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> NaiveBayesModel:
        return train_naive_bayes(pd.labels, pd.features, self.params.lambda_)

    def predict(self, model: NaiveBayesModel, query: Query) -> PredictedResult:
        return PredictedResult(model.predict(query.to_array()))

    def batch_predict(self, model, queries):
        if not queries:
            return []
        X = np.stack([q.to_array() for _, q in queries])
        labels = model.predict_batch(X)
        return [(i, PredictedResult(float(l))) for (i, _), l in zip(queries, labels)]


@dataclasses.dataclass(frozen=True)
class RandomForestParams(Params):
    num_trees: int = 10
    max_depth: int = 4
    seed: int = 42


class RandomForestAlgorithm(LocalAlgorithm):
    params_class = RandomForestParams
    params: RandomForestParams

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> RandomForestModel:
        return train_random_forest(
            pd.labels,
            pd.features,
            num_trees=self.params.num_trees,
            max_depth=self.params.max_depth,
            seed=self.params.seed,
        )

    def predict(self, model: RandomForestModel, query: Query) -> PredictedResult:
        return PredictedResult(model.predict(query.to_array()))


class Serving(BaseServing):
    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        Preparator,
        {"naive": NaiveBayesAlgorithm, "randomforest": RandomForestAlgorithm},
        Serving,
        query_class=Query,
    )


# ---------------------------------------------------------------------------
# reading-custom-properties variant (ref examples/scala-parallel-classification/
# reading-custom-properties/src/main/scala/DataSource.scala:49-66, Engine.scala)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CustomPropertiesQuery:
    """Four named features instead of attr0-2 (ref variant Engine.scala)."""

    feature_a: float
    feature_b: float
    feature_c: float
    feature_d: float

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "CustomPropertiesQuery":
        return CustomPropertiesQuery(
            float(d["featureA"]),
            float(d["featureB"]),
            float(d["featureC"]),
            float(d["featureD"]),
        )

    def to_array(self) -> np.ndarray:
        return np.array(
            [self.feature_a, self.feature_b, self.feature_c, self.feature_d],
            np.float64,
        )


@dataclasses.dataclass(frozen=True)
class CustomPropertiesDataSourceParams(DataSourceParams):
    label_property: str = "label"
    attr_properties: tuple[str, ...] = (
        "featureA",
        "featureB",
        "featureC",
        "featureD",
    )


class CustomPropertiesDataSource(DataSource):
    params_class = CustomPropertiesDataSourceParams

    def _make_query(self, features_row: np.ndarray):
        return CustomPropertiesQuery(*[float(x) for x in features_row[:4]])


def custom_properties_engine_factory() -> Engine:
    return Engine(
        CustomPropertiesDataSource,
        Preparator,
        {"naive": NaiveBayesAlgorithm, "randomforest": RandomForestAlgorithm},
        Serving,
        query_class=CustomPropertiesQuery,
    )
