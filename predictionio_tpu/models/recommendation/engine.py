"""ALS recommendation engine (DASE components).

Reference parity (behavioral, re-designed for TPU):
  - Query {"user", "num"} / PredictedResult {"itemScores": [{item, score}]}
    — ``recommendation-engine/src/main/scala/Engine.scala:22-39``.
  - DataSource reads "rate" and "buy" events of user->item, mapping buy to
    rating 4.0; k-fold readEval grouping eval queries per user —
    ``DataSource.scala:45-104``.
  - ALSAlgorithm params rank/numIterations/lambda/seed —
    ``ALSAlgorithm.scala:39-90`` (MLlib ALS there; ops.als here).
  - Serving returns the first algorithm's result — ``Serving.scala``.

TPU design: training data is columnar (dense int32 user/item ids + float32
ratings) from one event-store scan; the model holds host-numpy factor tables
plus id vocabularies; serving re-lands factors on device once and answers
queries with a resident jitted dot-product + ``lax.top_k``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    JaxAlgorithm,
    Params,
    SanityCheck,
)
from predictionio_tpu.data.storage.base import ColumnarEvents
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.workflow.context import WorkflowContext


# ---------------------------------------------------------------------------
# Wire types
# ---------------------------------------------------------------------------


DEFAULT_QUERY_NUM = 10


@dataclasses.dataclass(frozen=True)
class Query:
    """``blackList`` mirrors the blacklist-items variant
    (``examples/scala-parallel-recommendation/blacklist-items/src/main/scala/
    Engine.scala:23-27``); None means no filtering."""

    user: str
    num: int = DEFAULT_QUERY_NUM
    black_list: frozenset[str] | None = None

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "Query":
        bl = d.get("blackList")
        return Query(
            user=str(d["user"]),
            num=int(d.get("num", DEFAULT_QUERY_NUM)),
            black_list=frozenset(str(x) for x in bl) if bl is not None else None,
        )


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...]

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "itemScores": [
                {"item": s.item, "score": s.score} for s in self.item_scores
            ]
        }


@dataclasses.dataclass(frozen=True)
class Rating:
    user: str
    item: str
    rating: float


@dataclasses.dataclass(frozen=True)
class ActualResult:
    ratings: tuple[Rating, ...]


# ---------------------------------------------------------------------------
# DataSource
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EvalParams(Params):
    k_fold: int = 2
    query_num: int = 10


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    """``rating_map`` generalises the reading-custom-events variant
    (``reading-custom-events/src/main/scala/DataSource.scala:50-61``: like->4.0,
    dislike->1.0) and train-with-view-event (view->1.0 + implicit ALS): each
    listed event name is assigned a fixed rating value, overriding any
    per-event "rating" property."""

    app_name: str = ""
    event_names: tuple[str, ...] = ("rate", "buy")
    buy_rating: float = 4.0  # ref: map buy event to rating 4
    rating_map: dict[str, float] | None = None
    eval_params: EvalParams | None = None


@dataclasses.dataclass
class TrainingData(SanityCheck):
    """Columnar ratings + vocabularies."""

    user_idx: np.ndarray
    item_idx: np.ndarray
    ratings: np.ndarray
    user_vocab: list[str]
    item_vocab: list[str]

    def sanity_check(self) -> None:
        if len(self.user_idx) == 0:
            raise ValueError(
                "no rating events found; check app data (ref: empty RDD check)"
            )
        if not np.all(np.isfinite(self.ratings)):
            raise ValueError("non-finite rating values present")


def _columnar_to_ratings(
    col: ColumnarEvents,
    buy_rating: float,
    rating_map: dict[str, float] | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    ratings = col.ratings.copy()
    if rating_map:
        names = np.asarray(col.event_names)
        for event_name, value in rating_map.items():
            ratings[names == event_name] = float(value)
    else:
        buys = np.asarray([n == "buy" for n in col.event_names], dtype=bool)
        ratings[buys] = buy_rating
    valid = np.isfinite(ratings) & (col.entity_ids >= 0) & (col.target_ids >= 0)
    return col.entity_ids[valid], col.target_ids[valid], ratings[valid]


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def _read_columnar(self, ctx: WorkflowContext) -> ColumnarEvents:
        store = ctx.p_event_store()
        return store.to_columnar_cached(
            app_name=self.params.app_name or ctx.app_name,
            channel_name=ctx.channel_name,
            event_names=list(self.params.event_names),
            entity_type="user",
            target_entity_type="item",
            rating_key="rating",
        )

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        col = self._read_columnar(ctx)
        u, i, r = _columnar_to_ratings(
            col, self.params.buy_rating, self.params.rating_map
        )
        return TrainingData(u, i, r, col.entity_vocab, col.target_vocab)

    def read_eval(self, ctx: WorkflowContext):
        """k-fold split by rating index (ref DataSource.scala:81-104)."""
        if self.params.eval_params is None:
            raise ValueError("Must specify evalParams for evaluation")
        ep = self.params.eval_params
        col = self._read_columnar(ctx)
        u, i, r = _columnar_to_ratings(
            col, self.params.buy_rating, self.params.rating_map
        )
        idx = np.arange(len(u))
        folds = []
        for fold in range(ep.k_fold):
            test_mask = idx % ep.k_fold == fold
            td = TrainingData(
                u[~test_mask], i[~test_mask], r[~test_mask],
                col.entity_vocab, col.target_vocab,
            )
            # group test ratings per user -> one query per user
            qa: list[tuple[Query, ActualResult]] = []
            test_u, test_i, test_r = u[test_mask], i[test_mask], r[test_mask]
            for user_id in np.unique(test_u):
                sel = test_u == user_id
                ratings = tuple(
                    Rating(
                        col.entity_vocab[int(user_id)],
                        col.target_vocab[int(ti)],
                        float(tr),
                    )
                    for ti, tr in zip(test_i[sel], test_r[sel])
                )
                qa.append(
                    (
                        Query(col.entity_vocab[int(user_id)], ep.query_num),
                        ActualResult(ratings),
                    )
                )
            folds.append((td, {}, qa))
        return folds


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td


@dataclasses.dataclass(frozen=True)
class CustomPreparatorParams(Params):
    filepath: str


class CustomPreparator(BasePreparator):
    """customize-data-prep variant (ref ``customize-data-prep/src/main/scala/
    Preparator.scala:29-44``): drop ratings whose item appears in the
    exclusion file (one item id per line)."""

    params_class = CustomPreparatorParams
    params: CustomPreparatorParams

    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        with open(self.params.filepath) as fh:
            no_train_items = {line.strip() for line in fh if line.strip()}
        if not no_train_items:
            return td
        excluded = np.asarray(
            [item in no_train_items for item in td.item_vocab], dtype=bool
        )
        # drop the items from the vocab too, not just their ratings:
        # rating-less items would get all-zero factors and could still be
        # served at score 0.0 (MLlib never materialises factors for them)
        new_of_old = np.cumsum(~excluded) - 1
        keep = ~excluded[td.item_idx]
        return TrainingData(
            td.user_idx[keep],
            new_of_old[td.item_idx[keep]].astype(td.item_idx.dtype),
            td.ratings[keep],
            td.user_vocab,
            [it for it, ex in zip(td.item_vocab, excluded) if not ex],
        )


# ---------------------------------------------------------------------------
# Algorithm
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.1
    seed: int | None = 3
    implicit_prefs: bool = False
    alpha: float = 1.0
    # train with the ALX-style mesh-sharded solver (ops/als_sharded.py)
    # across all visible devices; single-device falls back transparently
    distributed: bool = False
    # "f32" | "bf16": gather the fixed factor side in bf16 during the
    # solver's Gram accumulation (halves the gather-bound loop's row bytes;
    # accumulators and solves stay f32 — see ops/als.ALSConfig.gather_dtype)
    gather_dtype: str = "f32"
    # "cg" | "cg_fused" | "cholesky": per-entity SPD solver; "cg_fused"
    # keeps the normal-equation systems VMEM-resident (one HBM read
    # instead of f+4 — see ops/als.ALSConfig.solver)
    solver: str = "cg"


@dataclasses.dataclass
class ALSModel(SanityCheck):
    user_factors: np.ndarray  # [n_users, f] host numpy (checkpoint form)
    item_factors: np.ndarray  # [n_items, f]
    user_vocab: list[str]
    item_vocab: list[str]

    def __post_init__(self):
        self._user_index: dict[str, int] | None = None
        self._item_index: dict[str, int] | None = None
        self._serving_index = None

    def sanity_check(self) -> None:
        if not (
            np.all(np.isfinite(self.user_factors))
            and np.all(np.isfinite(self.item_factors))
        ):
            raise ValueError("ALS produced non-finite factors")

    # -- serving-side helpers ------------------------------------------------
    def user_index(self, user: str) -> int | None:
        if self._user_index is None:
            self._user_index = {u: i for i, u in enumerate(self.user_vocab)}
        return self._user_index.get(user)

    def item_index(self, item: str) -> int | None:
        if self._item_index is None:
            self._item_index = {it: i for i, it in enumerate(self.item_vocab)}
        return self._item_index.get(item)

    def serving_index(self):
        """Both factor tables resident on device; index-addressed top-k
        with one upload + one fetch per query (ops.als.ServingIndex)."""
        if self._serving_index is None:
            from predictionio_tpu.ops.als import ServingIndex

            self._serving_index = ServingIndex(self.user_factors, self.item_factors)
        return self._serving_index

    def __getstate__(self):
        return {
            "user_factors": self.user_factors,
            "item_factors": self.item_factors,
            "user_vocab": self.user_vocab,
            "item_vocab": self.item_vocab,
        }

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._user_index = None
        self._item_index = None
        self._serving_index = None


class ALSAlgorithm(JaxAlgorithm):
    params_class = ALSAlgorithmParams
    params: ALSAlgorithmParams

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> ALSModel:
        cfg = ALSConfig(
            rank=self.params.rank,
            iterations=self.params.num_iterations,
            reg=self.params.lambda_,
            implicit=self.params.implicit_prefs,
            alpha=self.params.alpha,
            seed=self.params.seed if self.params.seed is not None else 0,
            gather_dtype=self.params.gather_dtype,
            solver=self.params.solver,
        )
        from predictionio_tpu.obs import xray

        prof = xray.current_profile()
        if prof is not None:
            # capacity planner prediction recorded BEFORE the allocation
            # happens; the profile's live-memory samples are the runtime
            # cross-check (pio doctor --capacity answers this preflight)
            import jax

            prof.set_estimate(
                xray.estimate_factors(
                    len(pd.user_vocab),
                    len(pd.item_vocab),
                    self.params.rank,
                    mesh=jax.device_count() if self.params.distributed else 1,
                    nnz=int(pd.user_idx.shape[0]),
                    gather_dtype=self.params.gather_dtype,
                )
            )
        if self.params.distributed:
            from predictionio_tpu.ops.als_sharded import als_train_sharded

            uf, vf = als_train_sharded(
                pd.user_idx,
                pd.item_idx,
                pd.ratings,
                len(pd.user_vocab),
                len(pd.item_vocab),
                cfg,
            )
        else:
            uf, vf = als_train(
                pd.user_idx,
                pd.item_idx,
                pd.ratings,
                len(pd.user_vocab),
                len(pd.item_vocab),
                cfg,
            )
        return ALSModel(
            np.asarray(uf), np.asarray(vf), pd.user_vocab, pd.item_vocab
        )

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        uidx = model.user_index(query.user)
        if uidx is None:
            return PredictedResult(())  # unknown user -> empty result
        mask = None
        if query.black_list:
            # blacklist-items variant (ref blacklist-items/ALSAlgorithm.scala:
            # 95-111 recommendProductsWithFilter): device-side mask, so
            # excluded items never reach the top-k
            mask = np.ones(len(model.item_vocab), dtype=bool)
            for item in query.black_list:
                iidx = model.item_index(item)
                if iidx is not None:
                    mask[iidx] = False
        scores, idx = model.serving_index().serve(
            uidx, min(query.num, len(model.item_vocab)), mask=mask
        )
        return PredictedResult(
            tuple(
                ItemScore(model.item_vocab[int(i)], float(s))
                for s, i in zip(scores, idx)
                if np.isfinite(s)
            )
        )

    def warmup_serving(self, model: ALSModel, max_batch: int) -> None:
        """Pre-compile the single-query program plus every pow2 batch bucket
        for the default result size, so the first request burst after deploy
        or /reload pays no XLA compiles."""
        index = model.serving_index()
        k = min(DEFAULT_QUERY_NUM, len(model.item_vocab))
        index.warmup(k)
        index.warmup_buckets(k, max_batch)

    def predict_batch(
        self, model: ALSModel, queries: Sequence[Query]
    ) -> list[PredictedResult]:
        """Serving micro-batch: all mask-free known-user queries become ONE
        batched top-k kernel ([B] indices -> [B,2,k] packed result); unknown
        users answer empty and blacklist queries (per-query device mask) fall
        back to the single-query path. This is what lets the query server
        sustain batched-kernel throughput end-to-end instead of one device
        round-trip per request."""
        return self.predict_batch_dispatch(model, queries)()

    def predict_batch_dispatch(
        self, model: ALSModel, queries: Sequence[Query]
    ):
        """Pipelined serving: dispatch the batched top-k kernel now, fetch in
        the returned finalize — the query server overlaps batch n's transport
        with batch n+1's dispatch (ops.als.ServingIndex.serve_batch_async).
        User indices are assembled into a reusable staging buffer
        (ops.topk.scratch) and only the packed [B,2,k] result is fetched."""
        from predictionio_tpu.ops import topk
        from predictionio_tpu.ops.als import next_pow2

        results: list[PredictedResult | None] = [None] * len(queries)
        batch_pos: list[int] = []
        batch_idx: list[int] = []
        masked_pos: list[int] = []
        for i, q in enumerate(queries):
            uidx = model.user_index(q.user)
            if uidx is None:
                results[i] = PredictedResult(())
            elif q.black_list:
                # per-query device mask: single-query path, but deferred to
                # finalize — a blocking predict here would stall the shared
                # dispatch thread for a full device round-trip
                masked_pos.append(i)
            else:
                batch_pos.append(i)
                batch_idx.append(uidx)
        n_items = len(model.item_vocab)
        handle = None
        if batch_pos:
            # bucket B and k to powers of two: every distinct shape compiles
            # its own XLA program, and ragged request arrivals would
            # otherwise trigger a compile storm (each a full round-trip on a
            # tunneled chip); buckets cap the universe at ~log2(max_batch)
            # programs, pre-warmed via ServingIndex.warmup_buckets
            k = min(max(queries[i].num for i in batch_pos), n_items)
            kk = min(next_pow2(k), n_items)
            bucket = next_pow2(len(batch_pos))
            # pad rows serve user 0, dropped on unpack
            idxs = topk.scratch().zeros("rec.uidx", (bucket,), np.int32)
            idxs[: len(batch_pos)] = batch_idx
            handle = model.serving_index().serve_batch_async(idxs, kk)

        def finalize() -> list[PredictedResult]:
            for i in masked_pos:
                results[i] = self.predict(model, queries[i])
            if handle is not None:
                scores, idx = topk.fetch_topk(handle)
                for row, i in enumerate(batch_pos):
                    num = min(queries[i].num, n_items)
                    results[i] = PredictedResult(
                        tuple(
                            ItemScore(model.item_vocab[int(it)], float(s))
                            for s, it in zip(scores[row, :num], idx[row, :num])
                            if np.isfinite(s)
                        )
                    )
            return results  # type: ignore[return-value]

        return finalize


class Serving(BaseServing):
    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        return predictions[0]


@dataclasses.dataclass(frozen=True)
class ServingParams(Params):
    filepath: str


class FilterServing(BaseServing):
    """customize-serving variant (ref ``customize-serving/src/main/scala/
    Serving.scala:26-43``): re-read the disabled-items file on every request
    (ops can edit it live, no redeploy) and drop those items from the
    first algorithm's result."""

    params_class = ServingParams
    params: ServingParams

    def serve(
        self, query: Query, predictions: Sequence[PredictedResult]
    ) -> PredictedResult:
        with open(self.params.filepath) as fh:
            disabled = {line.strip() for line in fh if line.strip()}
        return PredictedResult(
            tuple(
                s for s in predictions[0].item_scores if s.item not in disabled
            )
        )


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        {"": Preparator, "custom": CustomPreparator},
        {"als": ALSAlgorithm},
        {"": Serving, "filter": FilterServing},
        query_class=Query,
    )
