"""ALS recommendation engine (DASE components).

Reference parity (behavioral, re-designed for TPU):
  - Query {"user", "num"} / PredictedResult {"itemScores": [{item, score}]}
    — ``recommendation-engine/src/main/scala/Engine.scala:22-39``.
  - DataSource reads "rate" and "buy" events of user->item, mapping buy to
    rating 4.0; k-fold readEval grouping eval queries per user —
    ``DataSource.scala:45-104``.
  - ALSAlgorithm params rank/numIterations/lambda/seed —
    ``ALSAlgorithm.scala:39-90`` (MLlib ALS there; ops.als here).
  - Serving returns the first algorithm's result — ``Serving.scala``.

TPU design: training data is columnar (dense int32 user/item ids + float32
ratings) from one event-store scan; the model holds host-numpy factor tables
plus id vocabularies; serving re-lands factors on device once and answers
queries with a resident jitted dot-product + ``lax.top_k``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    JaxAlgorithm,
    Params,
    SanityCheck,
)
from predictionio_tpu.data.storage.base import ColumnarEvents
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.workflow.context import WorkflowContext


# ---------------------------------------------------------------------------
# Wire types
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "Query":
        return Query(user=str(d["user"]), num=int(d.get("num", 10)))


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...]

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "itemScores": [
                {"item": s.item, "score": s.score} for s in self.item_scores
            ]
        }


@dataclasses.dataclass(frozen=True)
class Rating:
    user: str
    item: str
    rating: float


@dataclasses.dataclass(frozen=True)
class ActualResult:
    ratings: tuple[Rating, ...]


# ---------------------------------------------------------------------------
# DataSource
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EvalParams(Params):
    k_fold: int = 2
    query_num: int = 10


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: tuple[str, ...] = ("rate", "buy")
    buy_rating: float = 4.0  # ref: map buy event to rating 4
    eval_params: EvalParams | None = None


@dataclasses.dataclass
class TrainingData(SanityCheck):
    """Columnar ratings + vocabularies."""

    user_idx: np.ndarray
    item_idx: np.ndarray
    ratings: np.ndarray
    user_vocab: list[str]
    item_vocab: list[str]

    def sanity_check(self) -> None:
        if len(self.user_idx) == 0:
            raise ValueError(
                "no rating events found; check app data (ref: empty RDD check)"
            )
        if not np.all(np.isfinite(self.ratings)):
            raise ValueError("non-finite rating values present")


def _columnar_to_ratings(
    col: ColumnarEvents, buy_rating: float
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    ratings = col.ratings.copy()
    buys = np.asarray([n == "buy" for n in col.event_names], dtype=bool)
    ratings[buys] = buy_rating
    valid = np.isfinite(ratings) & (col.entity_ids >= 0) & (col.target_ids >= 0)
    return col.entity_ids[valid], col.target_ids[valid], ratings[valid]


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def _read_columnar(self, ctx: WorkflowContext) -> ColumnarEvents:
        store = ctx.p_event_store()
        return store.to_columnar(
            app_name=self.params.app_name or ctx.app_name,
            channel_name=ctx.channel_name,
            event_names=list(self.params.event_names),
            entity_type="user",
            target_entity_type="item",
            rating_key="rating",
        )

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        col = self._read_columnar(ctx)
        u, i, r = _columnar_to_ratings(col, self.params.buy_rating)
        return TrainingData(u, i, r, col.entity_vocab, col.target_vocab)

    def read_eval(self, ctx: WorkflowContext):
        """k-fold split by rating index (ref DataSource.scala:81-104)."""
        if self.params.eval_params is None:
            raise ValueError("Must specify evalParams for evaluation")
        ep = self.params.eval_params
        col = self._read_columnar(ctx)
        u, i, r = _columnar_to_ratings(col, self.params.buy_rating)
        idx = np.arange(len(u))
        folds = []
        for fold in range(ep.k_fold):
            test_mask = idx % ep.k_fold == fold
            td = TrainingData(
                u[~test_mask], i[~test_mask], r[~test_mask],
                col.entity_vocab, col.target_vocab,
            )
            # group test ratings per user -> one query per user
            qa: list[tuple[Query, ActualResult]] = []
            test_u, test_i, test_r = u[test_mask], i[test_mask], r[test_mask]
            for user_id in np.unique(test_u):
                sel = test_u == user_id
                ratings = tuple(
                    Rating(
                        col.entity_vocab[int(user_id)],
                        col.target_vocab[int(ti)],
                        float(tr),
                    )
                    for ti, tr in zip(test_i[sel], test_r[sel])
                )
                qa.append(
                    (
                        Query(col.entity_vocab[int(user_id)], ep.query_num),
                        ActualResult(ratings),
                    )
                )
            folds.append((td, {}, qa))
        return folds


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td


# ---------------------------------------------------------------------------
# Algorithm
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.1
    seed: int | None = 3
    implicit_prefs: bool = False
    alpha: float = 1.0


@dataclasses.dataclass
class ALSModel(SanityCheck):
    user_factors: np.ndarray  # [n_users, f] host numpy (checkpoint form)
    item_factors: np.ndarray  # [n_items, f]
    user_vocab: list[str]
    item_vocab: list[str]

    def __post_init__(self):
        self._user_index: dict[str, int] | None = None
        self._serving_index = None

    def sanity_check(self) -> None:
        if not (
            np.all(np.isfinite(self.user_factors))
            and np.all(np.isfinite(self.item_factors))
        ):
            raise ValueError("ALS produced non-finite factors")

    # -- serving-side helpers ------------------------------------------------
    def user_index(self, user: str) -> int | None:
        if self._user_index is None:
            self._user_index = {u: i for i, u in enumerate(self.user_vocab)}
        return self._user_index.get(user)

    def serving_index(self):
        """Both factor tables resident on device; index-addressed top-k
        with one upload + one fetch per query (ops.als.ServingIndex)."""
        if self._serving_index is None:
            from predictionio_tpu.ops.als import ServingIndex

            self._serving_index = ServingIndex(self.user_factors, self.item_factors)
        return self._serving_index

    def __getstate__(self):
        return {
            "user_factors": self.user_factors,
            "item_factors": self.item_factors,
            "user_vocab": self.user_vocab,
            "item_vocab": self.item_vocab,
        }

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._user_index = None
        self._serving_index = None


class ALSAlgorithm(JaxAlgorithm):
    params_class = ALSAlgorithmParams
    params: ALSAlgorithmParams

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> ALSModel:
        cfg = ALSConfig(
            rank=self.params.rank,
            iterations=self.params.num_iterations,
            reg=self.params.lambda_,
            implicit=self.params.implicit_prefs,
            alpha=self.params.alpha,
            seed=self.params.seed if self.params.seed is not None else 0,
        )
        uf, vf = als_train(
            pd.user_idx,
            pd.item_idx,
            pd.ratings,
            len(pd.user_vocab),
            len(pd.item_vocab),
            cfg,
        )
        return ALSModel(
            np.asarray(uf), np.asarray(vf), pd.user_vocab, pd.item_vocab
        )

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        uidx = model.user_index(query.user)
        if uidx is None:
            return PredictedResult(())  # unknown user -> empty result
        scores, idx = model.serving_index().serve(
            uidx, min(query.num, len(model.item_vocab))
        )
        return PredictedResult(
            tuple(
                ItemScore(model.item_vocab[int(i)], float(s))
                for s, i in zip(scores, idx)
                if np.isfinite(s)
            )
        )


class Serving(BaseServing):
    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        Preparator,
        {"als": ALSAlgorithm},
        Serving,
        query_class=Query,
    )
