"""ALS recommendation template.

Reference parity: the quickstart recommendation engine
(``tests/pio_tests/engines/recommendation-engine/src/main/scala/`` —
Engine.scala Query/PredictedResult, DataSource.scala rate/buy ingestion with
k-fold readEval, ALSAlgorithm.scala MLlib ALS, Serving.scala first-serving)
re-built on the TPU ALS solver in ``predictionio_tpu.ops.als``.
"""

from predictionio_tpu.models.recommendation.engine import (
    ActualResult,
    ALSAlgorithm,
    ALSAlgorithmParams,
    ALSModel,
    DataSource,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Preparator,
    Query,
    Serving,
    TrainingData,
    engine_factory,
)

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "ALSModel",
    "ActualResult",
    "DataSource",
    "DataSourceParams",
    "ItemScore",
    "PredictedResult",
    "Preparator",
    "Query",
    "Serving",
    "TrainingData",
    "engine_factory",
]
