"""The ``pio_seq_*`` metric family (docs/observability.md).

Registered eagerly (AnnInstruments discipline): the family exists at zero
from process start so scrapers and the docs metrics-contract test see it
before the first session folds in. The stream pipeline binds it to the
:class:`~predictionio_tpu.stream.trainers.SequentialStreamTrainer` via its
``instruments`` kwarg."""

from __future__ import annotations

from predictionio_tpu.obs.metrics import MetricsRegistry


class SeqInstruments:
    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.transitions = r.counter(
            "pio_seq_transitions_total",
            "session transitions (prev item -> next item) absorbed by the "
            "streaming sequential trainer",
        )
        self.items_touched = r.counter(
            "pio_seq_items_touched_total",
            "items whose outgoing transition row changed, summed over "
            "absorbed micro-batches",
        )
        self.states = r.gauge(
            "pio_seq_states",
            "states (items) in the last published transition matrix",
        )
        self.pairs = r.gauge(
            "pio_seq_pairs",
            "distinct (from, to) transition pairs in the last published "
            "matrix",
        )
        self.sessions = r.gauge(
            "pio_seq_sessions",
            "live per-user session cursors the stream trainer tracks "
            "(bounded by its max_users)",
        )
        self.snapshots = r.counter(
            "pio_seq_snapshots_total",
            "stream snapshots rebuilt into a servable SequentialModel",
        )

    def on_absorb(self, transitions: int, items_touched: int) -> None:
        self.transitions.inc(float(transitions))
        self.items_touched.inc(float(items_touched))

    def on_snapshot(self, states: int, pairs: int, sessions: int) -> None:
        self.states.set(float(states))
        self.pairs.set(float(pairs))
        self.sessions.set(float(sessions))
        self.snapshots.inc()
