"""Session / next-item engine (DASE components).

Reference parity (behavioral):
  - the e2 MarkovChain (``e2/.../engine/MarkovChain.scala:26-55``) finally
    gets a template consumer: the transition-matrix scorer below is
    EXACTLY ``e2.markov_chain.train_markov_chain`` over consecutive-pair
    coordinates — a parity unit test holds the two outputs equal.
  - ordered per-user reads ride the PR-5 ``find_after`` contract (strict
    ``(creation_time_us, event_id)`` total order, bounded pages), so the
    session order the trainer sees is the ingest order, not scan luck.

TPU design: the optional attention scorer is the serving consumer of
``ops/attention.fused_attention`` (the pallas kernel benched in BENCH_r03):
session items gather their input embeddings, one causal single-head
attention pass over the short context window produces the session vector,
and scoring+masking+selection is the shared fused
``ops/topk.dot_top_k_async`` program over the resident output table — only
the packed (k scores, k indices) result ever crosses the wire. When an ANN
index is pinned to the lane the session vector handle feeds
``ann.search_async`` zero-copy, same as the two-tower engine.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Iterator, Sequence

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    JaxAlgorithm,
    LocalAlgorithm,
    Params,
    SanityCheck,
)
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.store.event_store import resolve_app
from predictionio_tpu.e2.markov_chain import MarkovChainModel, train_markov_chain
from predictionio_tpu.ops import topk
from predictionio_tpu.workflow.context import WorkflowContext

# ---------------------------------------------------------------------------
# Query / result
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Query:
    """``recentItems`` is the caller-supplied session tail (most recent
    LAST); when absent, the model's stored last-item for ``user`` answers
    (ref e-commerce template's recent-event lookup)."""

    user: str | None = None
    recent_items: tuple[str, ...] = ()
    num: int = 10

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "Query":
        return Query(
            user=d.get("user"),
            recent_items=tuple(d.get("recentItems") or ()),
            num=int(d.get("num", 10)),
        )


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float

    def to_json_dict(self) -> dict[str, Any]:
        return {"item": self.item, "score": self.score}


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...]

    def to_json_dict(self) -> dict[str, Any]:
        return {"itemScores": [s.to_json_dict() for s in self.item_scores]}


@dataclasses.dataclass(frozen=True)
class ActualResult:
    """The user's true continuation (ordered) for eval folds."""

    items: tuple[str, ...]


# ---------------------------------------------------------------------------
# DataSource
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EvalParams(Params):
    k_fold: int = 3
    query_num: int = 10
    # how many trailing items of each held-out user's session become the
    # actual continuation (the prefix becomes the query's recentItems)
    holdout_tail: int = 2


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str
    channel_name: str | None = None
    event_names: tuple[str, ...] = ("view",)
    entity_type: str = "user"
    target_entity_type: str = "item"
    # find_after page size and total-event bound for one training read
    page: int = 2048
    max_events: int = 500_000
    eval_params: EvalParams | None = None


@dataclasses.dataclass
class TrainingData(SanityCheck):
    """Ordered per-user sessions, dictionary-encoded: ``sequences[i]`` is
    user ``users[i]``'s item-index sequence in event order."""

    users: list[str]
    sequences: list[np.ndarray]
    item_vocab: list[str]

    def sanity_check(self) -> None:
        if len(self.users) != len(self.sequences):
            raise ValueError("users/sequences length mismatch")
        if not any(len(s) >= 2 for s in self.sequences):
            raise ValueError(
                "no session with >= 2 events — nothing to learn transitions from"
            )


def transition_coordinates(
    sequences: Sequence[np.ndarray],
) -> list[tuple[int, int, float]]:
    """Consecutive-pair (from, to, 1.0) coordinates — the exact coordinate
    form ``e2.markov_chain.train_markov_chain`` consumes (it sums the
    duplicates itself; emitting raw pairs keeps the parity trivially
    auditable)."""
    coords: list[tuple[int, int, float]] = []
    for seq in sequences:
        for a, b in zip(seq[:-1], seq[1:]):
            coords.append((int(a), int(b), 1.0))
    return coords


def sequences_from_events(
    events: Iterator[Event],
    *,
    event_names: Sequence[str],
    entity_type: str,
    target_entity_type: str,
    vocab: dict[str, int] | None = None,
) -> tuple[dict[str, list[int]], list[str]]:
    """Fold an ORDERED event iterator into per-user item-index sequences.
    The iterator's order IS the session order — callers must feed a
    ``find_after``-ordered stream (see ``_iter_ordered``)."""
    names = set(event_names)
    index: dict[str, int] = dict(vocab) if vocab else {}
    item_vocab: list[str] = [None] * len(index)  # type: ignore[list-item]
    for item, i in index.items():
        item_vocab[i] = item
    per_user: dict[str, list[int]] = {}
    for e in events:
        if e.event not in names or e.entity_type != entity_type:
            continue
        if e.target_entity_type != target_entity_type or e.target_entity_id is None:
            continue
        idx = index.get(e.target_entity_id)
        if idx is None:
            idx = len(item_vocab)
            index[e.target_entity_id] = idx
            item_vocab.append(e.target_entity_id)
        per_user.setdefault(e.entity_id, []).append(idx)
    return per_user, item_vocab


def _iter_ordered(
    levents, app_id: int, channel_id: int | None, page: int, max_events: int
) -> Iterator[Event]:
    """Bounded ordered scan: ``find_after`` pages in ``(creation_time_us,
    event_id)`` order up to the head observed at entry, so a live ingest
    stream cannot keep the read open forever."""
    head = levents.seq_head(app_id, channel_id)
    if head is None:
        return
    from predictionio_tpu.data.storage.base import event_seq_key

    cursor = None
    seen = 0
    while seen < max_events:
        batch = list(
            levents.find_after(
                app_id, channel_id, cursor, min(page, max_events - seen)
            )
        )
        if not batch:
            return
        for e in batch:
            key = event_seq_key(e)
            if key > head:
                return
            cursor = key
            seen += 1
            yield e
        if len(batch) < page:
            return


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def _ordered_events(self, ctx: WorkflowContext) -> Iterator[Event]:
        app_id, channel_id = resolve_app(
            ctx.storage, self.params.app_name, self.params.channel_name
        )
        levents = ctx.storage.get_l_events()
        return _iter_ordered(
            levents, app_id, channel_id, self.params.page, self.params.max_events
        )

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        per_user, vocab = sequences_from_events(
            self._ordered_events(ctx),
            event_names=self.params.event_names,
            entity_type=self.params.entity_type,
            target_entity_type=self.params.target_entity_type,
        )
        users = sorted(per_user)
        return TrainingData(
            users,
            [np.asarray(per_user[u], np.int32) for u in users],
            vocab,
        )

    def read_eval(self, ctx: WorkflowContext):
        """k-fold by USER through the tuning grid's ``EventStoreSplitter``
        (the PR-14 follow-up): fold assignment is the splitter's sticky
        sha256 bucket, so eval-grid cells across processes and hosts agree
        on which users are held out without exchanging state."""
        if self.params.eval_params is None:
            raise ValueError("Must specify evalParams for evaluation")
        ep = self.params.eval_params
        from predictionio_tpu.tuning.grid import EventStoreSplitter

        app_id, channel_id = resolve_app(
            ctx.storage, self.params.app_name, self.params.channel_name
        )
        splitter = EventStoreSplitter(
            ctx.storage.get_l_events(),
            app_id,
            ep.k_fold,
            channel_id,
            num=ep.query_num,
            entity_type=self.params.entity_type,
            event_names=self.params.event_names,
            page=self.params.page,
        )
        per_user, vocab = sequences_from_events(
            splitter.iter_ordered(),
            event_names=self.params.event_names,
            entity_type=self.params.entity_type,
            target_entity_type=self.params.target_entity_type,
        )
        folds = []
        for fold in range(ep.k_fold):
            keep = splitter.keep_for_training(fold)
            users = sorted(u for u in per_user if keep(u))
            td = TrainingData(
                users,
                [np.asarray(per_user[u], np.int32) for u in users],
                vocab,
            )
            qa: list[tuple[Query, ActualResult]] = []
            for u in sorted(per_user):
                if keep(u):
                    continue
                seq = per_user[u]
                if len(seq) < 2:
                    continue
                tail = min(ep.holdout_tail, len(seq) - 1)
                qa.append(
                    (
                        Query(
                            user=u,
                            recent_items=tuple(
                                vocab[i] for i in seq[:-tail]
                            ),
                            num=ep.query_num,
                        ),
                        ActualResult(tuple(vocab[i] for i in seq[-tail:])),
                    )
                )
            folds.append((td, {}, qa))
        return folds


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SequentialModel(SanityCheck):
    """One model type serves both scorers: the Markov fields are always
    present (the stream trainer folds into them live); the attention
    fields are present when the attention algorithm trained. ``item_out``
    doubles as ``item_factors`` so the ANN lifecycle's
    ``item_vectors_of`` picks the table up unchanged."""

    item_vocab: list[str]
    markov: MarkovChainModel | None = None
    # raw summed pair counts — what the streaming trainer merges into;
    # the markov model is always rebuilt from these (exact e2 math)
    pair_counts: dict[tuple[int, int], float] = dataclasses.field(
        default_factory=dict
    )
    user_last: dict[str, int] = dataclasses.field(default_factory=dict)
    top_n: int = 10
    # attention scorer state (None for markov-only models)
    item_in: np.ndarray | None = None  # [n, f] session-side embeddings
    item_out: np.ndarray | None = None  # [n, f] scoring table
    context: int = 8

    def __post_init__(self):
        self._lock = threading.Lock()
        self._dev_in = None
        self._dev_out = None
        self._index: dict[str, int] | None = None

    @property
    def item_factors(self) -> np.ndarray | None:
        return self.item_out

    def item_index(self) -> dict[str, int]:
        idx = self._index
        if idx is None or len(idx) != len(self.item_vocab):
            idx = self._index = {v: i for i, v in enumerate(self.item_vocab)}
        return idx

    def device_in(self):
        import jax.numpy as jnp

        with self._lock:
            if self._dev_in is None and self.item_in is not None:
                self._dev_in = jnp.asarray(self.item_in, jnp.float32)
            return self._dev_in

    def device_out(self):
        import jax.numpy as jnp

        with self._lock:
            if self._dev_out is None and self.item_out is not None:
                self._dev_out = jnp.asarray(self.item_out, jnp.float32)
            return self._dev_out

    def __getstate__(self):
        state = dict(self.__dict__)
        for k in ("_lock", "_dev_in", "_dev_out", "_index"):
            state.pop(k, None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        self._dev_in = None
        self._dev_out = None
        self._index = None

    def sanity_check(self) -> None:
        if not self.item_vocab:
            raise ValueError("empty item vocab")

    def session_indices(self, query: Query) -> list[int]:
        """Resolve the query's session tail to item indices: explicit
        ``recentItems`` win; a bare ``user`` falls back to the stored
        last item of their training/stream history."""
        idx = self.item_index()
        session = [
            idx[i] for i in query.recent_items if i in idx
        ]
        if not session and query.user is not None:
            last = self.user_last.get(query.user)
            if last is not None:
                session = [last]
        return session


def build_markov(
    sequences: Sequence[np.ndarray], n_states: int, top_n: int
) -> tuple[MarkovChainModel, dict[tuple[int, int], float]]:
    """Train the transition model through the REAL e2 entry point — the
    parity test holds this against a direct ``train_markov_chain`` call on
    the same events. Returns the summed pair counts too (the streaming
    trainer's merge substrate; ``train_markov_chain`` keeps only top-N
    probabilities, which is lossy)."""
    coords = transition_coordinates(sequences)
    counts: dict[tuple[int, int], float] = {}
    for i, j, c in coords:
        counts[(i, j)] = counts.get((i, j), 0.0) + c
    return train_markov_chain(coords, n_states, top_n), counts


def markov_from_counts(
    counts: dict[tuple[int, int], float], n_states: int, top_n: int
) -> MarkovChainModel:
    return train_markov_chain(
        [(i, j, c) for (i, j), c in counts.items()], n_states, top_n
    )


def last_items(sequences: Sequence[np.ndarray], users: Sequence[str]) -> dict[str, int]:
    return {
        u: int(seq[-1]) for u, seq in zip(users, sequences) if len(seq)
    }


# ---------------------------------------------------------------------------
# Markov algorithm (host-born sparse scores -> sanctioned host ending)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MarkovAlgorithmParams(Params):
    top_n: int = 10


class MarkovAlgorithm(LocalAlgorithm):
    """Transition-matrix next-item scorer. The scores are host-born sparse
    transition probabilities (<= top_n of them) — ``topk.host_top_k`` is
    the sanctioned ending, same as the cooccurrence algorithm."""

    params_class = MarkovAlgorithmParams
    params: MarkovAlgorithmParams

    def train(self, ctx: WorkflowContext, td: TrainingData) -> SequentialModel:
        markov, counts = build_markov(
            td.sequences, len(td.item_vocab), self.params.top_n
        )
        return SequentialModel(
            item_vocab=list(td.item_vocab),
            markov=markov,
            pair_counts=counts,
            user_last=last_items(td.sequences, td.users),
            top_n=self.params.top_n,
        )

    def predict(self, model: SequentialModel, query: Query) -> PredictedResult:
        session = model.session_indices(query)
        if not session or model.markov is None:
            return PredictedResult(())
        n = len(model.item_vocab)
        scores = np.zeros(n, np.float64)
        for j, p in model.markov.transition_probs(session[-1]):
            if j < n:
                scores[j] = p
        mask = np.ones(n, bool)
        mask[np.asarray(session, np.int64)] = False
        mask &= scores > 0.0
        s, idx = topk.host_top_k(scores, mask, query.num)
        return PredictedResult(
            tuple(
                ItemScore(model.item_vocab[int(i)], float(v))
                for v, i in zip(s, idx)
            )
        )


# ---------------------------------------------------------------------------
# Attention algorithm (fused_attention encode -> fused top-k / ANN)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AttentionAlgorithmParams(Params):
    rank: int = 32
    num_iterations: int = 10
    lambda_: float = 0.1
    seed: int = 3
    # session window the attention encoder attends over; short by design
    # (the pallas kernel's single-block path covers it on TPU)
    context: int = 8
    top_n: int = 10


class AttentionAlgorithm(JaxAlgorithm):
    """Short-context attention next-item scorer.

    Train: implicit ALS over the transition-pair matrix factorizes
    transitions into an input table (session side) and an output table
    (scoring side) — attention over the input embeddings of the session
    window produces the session vector; the output table scores it.
    Markov is the window=1 special case of this program.

    Serve: gather -> causal single-head ``fused_attention`` -> last
    position = session vector (device-resident) -> shared
    ``topk.dot_top_k_async`` (or ``ann.search_async`` when a lane index is
    pinned). No host argsort anywhere on this path — the packed [B,2,k]
    result is the only fetch."""

    params_class = AttentionAlgorithmParams
    params: AttentionAlgorithmParams

    def train(self, ctx: WorkflowContext, td: TrainingData) -> SequentialModel:
        from predictionio_tpu.ops.als import ALSConfig, als_train

        n = len(td.item_vocab)
        markov, counts = build_markov(td.sequences, n, self.params.top_n)
        if counts:
            from_idx = np.asarray([i for i, _ in counts], np.int32)
            to_idx = np.asarray([j for _, j in counts], np.int32)
            weight = np.asarray(list(counts.values()), np.float32)
        else:
            from_idx = np.empty(0, np.int32)
            to_idx = np.empty(0, np.int32)
            weight = np.empty(0, np.float32)
        cfg = ALSConfig(
            rank=self.params.rank,
            iterations=self.params.num_iterations,
            reg=self.params.lambda_,
            implicit=True,
            seed=self.params.seed,
        )
        item_in, item_out = als_train(from_idx, to_idx, weight, n, n, cfg)
        item_in = np.asarray(item_in, np.float32)
        item_out = np.asarray(item_out, np.float32)
        return SequentialModel(
            item_vocab=list(td.item_vocab),
            markov=markov,
            pair_counts=counts,
            user_last=last_items(td.sequences, td.users),
            top_n=self.params.top_n,
            item_in=item_in,
            item_out=item_out,
            context=self.params.context,
        )

    # ------------------------------------------------------------- serving
    @staticmethod
    def _encode(table, hist):
        """Jit-compiled per (B, L) bucket by the jax cache: gather the
        window's input embeddings and run one causal single-head
        attention pass; the last position's output is the session
        vector. Left-pad slots repeat the window's oldest item — a
        documented smoothing bias that keeps the program shape static
        (fused_attention has no key mask by design)."""
        import jax.numpy as jnp

        from predictionio_tpu.ops.attention import fused_attention

        e = table[hist]  # [B, L, f]
        x = e[:, None, :, :]  # [B, H=1, L, f]
        out = fused_attention(x, x, x, causal=True)
        return jnp.asarray(out[:, 0, -1, :])  # [B, f]

    _encode_jit = None

    @classmethod
    def _encoder(cls):
        if cls._encode_jit is None:
            import jax

            cls._encode_jit = jax.jit(cls._encode)
        return cls._encode_jit

    def _stage_batch(
        self, model: SequentialModel, queries: Sequence[Query]
    ):
        """Host staging: resolve sessions, right-align into a [B, L]
        window buffer (left-padded with each row's oldest in-window item),
        and build the candidate mask excluding session items."""
        pool = topk.scratch()
        b = len(queries)
        bb = topk.next_pow2(b)
        L = max(1, self.params.context)
        n = len(model.item_vocab)
        hist = pool.zeros("seq_hist", (bb, L), np.int32)
        mask = pool.full("seq_mask", (bb, n), bool, True)
        mask[b:, :] = False
        sessions: list[list[int]] = []
        for q_i, q in enumerate(queries):
            session = model.session_indices(q)
            sessions.append(session)
            window = session[-L:] if session else []
            if window:
                hist[q_i, :] = window[0]
                hist[q_i, L - len(window):] = window
                mask[q_i, np.asarray(session, np.int64)] = False
            else:
                mask[q_i, :] = False
        return hist, mask, sessions, bb

    def predict_batch_dispatch(
        self, model: SequentialModel, queries: Sequence[Query]
    ):
        from predictionio_tpu.ann.lifecycle import ATTR as _ANN_ATTR

        table_in = model.device_in()
        table_out = model.device_out()
        if table_in is None or table_out is None:
            # markov-only model answering on the attention lane: map the
            # host scorer (still no device work to fuse with)
            alg = MarkovAlgorithm(MarkovAlgorithmParams(top_n=model.top_n))
            results = [alg.predict(model, q) for q in queries]
            return lambda: results
        hist, mask, sessions, bb = self._stage_batch(model, queries)
        n = len(model.item_vocab)
        kk = min(topk.next_pow2(max(1, max(q.num for q in queries))), n)
        ctx_vec = self._encoder()(table_in, topk.upload(hist, np.int32))
        ann = getattr(model, _ANN_ATTR, None)
        if ann is not None and not ann.supports(kk):
            ann.count_fallback(len(queries))
            ann = None
        if ann is not None:
            # exclusion of session items happens in the fused ANN gather
            handle = ann.search_async(
                ctx_vec, kk, exclude=self._exclude_rows(sessions, bb)
            )
        else:
            handle = topk.dot_top_k_async(table_out, ctx_vec, mask, kk)

        def finalize() -> list[PredictedResult]:
            if ann is not None:
                scores, idx = ann.fetch(handle, rows=len(queries))
            else:
                scores, idx = topk.fetch_topk(handle)
            out: list[PredictedResult] = []
            for q_i, q in enumerate(queries):
                banned = set(sessions[q_i])
                picks: list[ItemScore] = []
                for v, i in zip(scores[q_i], idx[q_i]):
                    i = int(i)
                    if not np.isfinite(v) or i < 0 or i in banned:
                        continue
                    picks.append(ItemScore(model.item_vocab[i], float(v)))
                    if len(picks) >= q.num:
                        break
                out.append(PredictedResult(tuple(picks)))
            return out

        return finalize

    @staticmethod
    def _exclude_rows(sessions: list[list[int]], bb: int) -> np.ndarray:
        width = max(1, max((len(s) for s in sessions), default=1))
        ex = np.full((bb, width), -1, np.int32)
        for r, s in enumerate(sessions):
            if s:
                ex[r, : len(s)] = s
        return ex

    def predict_batch(
        self, model: SequentialModel, queries: Sequence[Query]
    ) -> list[PredictedResult]:
        return self.predict_batch_dispatch(model, queries)()

    def predict(self, model: SequentialModel, query: Query) -> PredictedResult:
        return self.predict_batch(model, [query])[0]

    def warmup_serving(self, model: SequentialModel, max_batch: int) -> None:
        """Pre-compile the encode+topk program per pow2 batch bucket (and
        the ANN composition when pinned) so the first burst after
        deploy/reload pays no XLA compiles."""
        if model.device_in() is None:
            return
        vocab = model.item_vocab
        if not vocab:
            return
        probe = Query(recent_items=(vocab[0],), num=min(10, len(vocab)))

        def dispatch(b: int):
            fin = self.predict_batch_dispatch(model, [probe] * b)
            return fin() if callable(fin) else fin

        topk.warmup_pow2_buckets(max_batch, dispatch)


# ---------------------------------------------------------------------------
# Serving / factory
# ---------------------------------------------------------------------------


class Serving(BaseServing):
    def serve(self, query: Query, predictions: Sequence[PredictedResult]):
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        Preparator,
        {"markov": MarkovAlgorithm, "attention": AttentionAlgorithm},
        Serving,
        query_class=Query,
    )
