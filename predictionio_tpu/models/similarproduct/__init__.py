"""Similar-product template.

Reference parity: ``examples/scala-parallel-similarproduct/`` (the
multi-events-multi-algos variant, which supersets the base template):
implicit-ALS item factors scored by cosine similarity against the query
items, an item-cooccurrence algorithm, and a like-event ALS variant, all
selectable per engine.json; business filters (categories, category
blacklist, white/black lists, query-item exclusion) applied at predict time.
"""

from predictionio_tpu.models.similarproduct.engine import (
    ALSAlgorithm,
    CooccurrenceAlgorithm,
    DataSource,
    ItemScore,
    LikeAlgorithm,
    PredictedResult,
    Preparator,
    Query,
    Serving,
    TrainingData,
    engine_factory,
)

__all__ = [
    "ALSAlgorithm",
    "CooccurrenceAlgorithm",
    "DataSource",
    "ItemScore",
    "LikeAlgorithm",
    "PredictedResult",
    "Preparator",
    "Query",
    "Serving",
    "TrainingData",
    "engine_factory",
]
