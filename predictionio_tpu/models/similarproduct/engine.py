"""Similar-product engine (DASE components).

Reference parity (behavioral):
  - Query {items, num, categories?, categoryBlackList?, whiteList?,
    blackList?} -> PredictedResult {itemScores} —
    ``multi-events-multi-algos/src/main/scala/Engine.scala:23-41``.
  - DataSource reads user/item entities (item ``categories`` property) and
    view + like events — ``DataSource.scala``.
  - ALSAlgorithm: implicit ALS on view counts; predict scores every item by
    cosine similarity to each query item's factor, summed —
    ``ALSAlgorithm.scala:136-230``.
  - LikeAlgorithm: same scoring on like events — ``LikeAlgorithm.scala``.
  - CooccurrenceAlgorithm: top-N ordered-pair counts —
    ``CooccurrenceAlgorithm.scala:30-90``.
  - isCandidateItem filters: whitelist, blacklist, query-item exclusion,
    category overlap, category blacklist — ``ALSAlgorithm.scala:236-260``.

TPU design: cosine scoring, candidate masking and selection are ONE fused
jitted program (ops/topk.gather_sum_top_k_async) over the resident
normalized item-factor table; a micro-batch of queries is one device call
and only the (k scores, k indices) pairs ever cross the wire.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    JaxAlgorithm,
    LocalAlgorithm,
    Params,
    SanityCheck,
)
from predictionio_tpu.ops import topk
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.ops.cooccurrence import cooccurrence_top_n, score_by_cooccurrence
from predictionio_tpu.workflow.context import WorkflowContext


@dataclasses.dataclass(frozen=True)
class Query:
    items: tuple[str, ...]
    num: int = 10
    categories: frozenset[str] | None = None
    category_black_list: frozenset[str] | None = None
    white_list: frozenset[str] | None = None
    black_list: frozenset[str] | None = None

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "Query":
        def fset(key):
            v = d.get(key)
            return frozenset(v) if v is not None else None

        return Query(
            items=tuple(d["items"]),
            num=int(d.get("num", 10)),
            categories=fset("categories"),
            category_black_list=fset("categoryBlackList"),
            white_list=fset("whiteList"),
            black_list=fset("blackList"),
        )


@dataclasses.dataclass(frozen=True)
class ItemScore:
    """``properties`` carries returned item attributes for the
    return-item-properties variant (ref ``return-item-properties/src/main/
    scala/Engine.scala:38-45`` adds title/date/imdbUrl fields); they are
    flattened into the wire dict exactly like the reference's named fields."""

    item: str
    score: float
    properties: dict[str, Any] | None = None

    def to_json_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = dict(self.properties or {})
        out["item"] = self.item
        out["score"] = self.score
        return out


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...]

    def to_json_dict(self) -> dict[str, Any]:
        return {"itemScores": [s.to_json_dict() for s in self.item_scores]}


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    """``item_property_names`` enables return-item-properties
    (ref ``return-item-properties/DataSource.scala:60-75``: collect
    title/date/imdbUrl per item); ``rate_event`` adds a rated-interaction
    table for train-with-rate-event (ref ``train-with-rate-event/
    DataSource.scala``: rate events with a rating property, latest rating
    per (user,item) wins)."""

    app_name: str = ""
    item_property_names: tuple[str, ...] = ()
    rate_event: str | None = None


@dataclasses.dataclass
class TrainingData(SanityCheck):
    user_vocab: list[str]
    item_vocab: list[str]
    item_categories: list[frozenset[str] | None]  # aligned with item_vocab
    view_user_idx: np.ndarray
    view_item_idx: np.ndarray
    like_user_idx: np.ndarray
    like_item_idx: np.ndarray
    # return-item-properties: per-item property dicts aligned with item_vocab
    item_properties: list[dict[str, Any] | None] | None = None
    # train-with-rate-event: latest rating per (user, item)
    rate_user_idx: np.ndarray | None = None
    rate_item_idx: np.ndarray | None = None
    rate_values: np.ndarray | None = None

    def sanity_check(self) -> None:
        n_rates = 0 if self.rate_user_idx is None else len(self.rate_user_idx)
        if len(self.view_user_idx) == 0 and len(self.like_user_idx) == 0 and n_rates == 0:
            raise ValueError("no view/like/rate events found; check app data")


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        store = ctx.p_event_store()
        app_name = self.params.app_name or ctx.app_name
        event_names = ["view", "like"]
        if self.params.rate_event:
            event_names.append(self.params.rate_event)
        col = store.to_columnar_cached(
            app_name=app_name,
            channel_name=ctx.channel_name,
            event_names=event_names,
            entity_type="user",
            target_entity_type="item",
            rating_key="rating",
        )
        item_vocab = list(col.target_vocab)
        item_index = {v: i for i, v in enumerate(item_vocab)}
        # item categories (+ optional returned properties) from $set
        # properties of item entities
        item_props = store.aggregate_properties(
            app_name=app_name, entity_type="item", channel_name=ctx.channel_name
        )
        categories: list[frozenset[str] | None] = [None] * len(item_vocab)
        wanted = self.params.item_property_names
        properties: list[dict[str, Any] | None] | None = (
            [None] * len(item_vocab) if wanted else None
        )
        for entity_id, pm in item_props.items():
            idx = item_index.get(entity_id)
            if idx is None:
                item_index[entity_id] = len(item_vocab)
                item_vocab.append(entity_id)
                categories.append(None)
                if properties is not None:
                    properties.append(None)
                idx = item_index[entity_id]
            cats = pm.get_opt("categories")
            if cats is not None:
                categories[idx] = frozenset(cats)
            if properties is not None:
                properties[idx] = {
                    name: pm.get_opt(name)
                    for name in wanted
                    if pm.get_opt(name) is not None
                }
        views = np.asarray([n == "view" for n in col.event_names], bool)
        likes = np.asarray([n == "like" for n in col.event_names], bool)
        valid = (col.entity_ids >= 0) & (col.target_ids >= 0)
        rate_u = rate_i = rate_v = None
        if self.params.rate_event:
            rates = np.asarray(
                [n == self.params.rate_event for n in col.event_names], bool
            )
            sel = rates & valid & np.isfinite(col.ratings)
            # latest rating per (user, item) wins (ref train-with-rate-event/
            # ALSAlgorithm.scala:101-117 reduceByKey on timestamp)
            order = np.argsort(col.timestamps[sel], kind="stable")
            u, i, v = (
                col.entity_ids[sel][order],
                col.target_ids[sel][order],
                col.ratings[sel][order],
            )
            pairs = np.stack([u, i], 1)
            # np.unique keeps the FIRST occurrence; reverse so first == latest
            _, first = np.unique(pairs[::-1], axis=0, return_index=True)
            keep = len(u) - 1 - first
            rate_u, rate_i, rate_v = u[keep], i[keep], v[keep].astype(np.float32)
        return TrainingData(
            user_vocab=col.entity_vocab,
            item_vocab=item_vocab,
            item_categories=categories,
            view_user_idx=col.entity_ids[views & valid],
            view_item_idx=col.target_ids[views & valid],
            like_user_idx=col.entity_ids[likes & valid],
            like_item_idx=col.target_ids[likes & valid],
            item_properties=properties,
            rate_user_idx=rate_u,
            rate_item_idx=rate_i,
            rate_values=rate_v,
        )


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td


# ---------------------------------------------------------------------------
# Shared model + filtering
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SimilarModel(SanityCheck):
    item_factors: np.ndarray  # [n_items, f], L2-normalized rows
    item_vocab: list[str]
    item_categories: list[frozenset[str] | None]
    item_properties: list[dict[str, Any] | None] | None = None

    def __post_init__(self):
        self._index: dict[str, int] | None = None
        self._device_factors = None

    def properties_of(self, i: int) -> dict[str, Any] | None:
        if self.item_properties is None:
            return None
        return self.item_properties[i]

    def sanity_check(self) -> None:
        if not np.all(np.isfinite(self.item_factors)):
            raise ValueError("non-finite item factors")

    def item_index(self, item: str) -> int | None:
        if self._index is None:
            self._index = {v: i for i, v in enumerate(self.item_vocab)}
        return self._index.get(item)

    def device_factors(self):
        if self._device_factors is None:
            import jax.numpy as jnp

            self._device_factors = jnp.asarray(self.item_factors)
        return self._device_factors

    def __getstate__(self):
        return {
            "item_factors": self.item_factors,
            "item_vocab": self.item_vocab,
            "item_categories": self.item_categories,
            "item_properties": self.item_properties,
        }

    def __setstate__(self, state):
        state.setdefault("item_properties", None)
        self.__dict__.update(state)
        self._index = None
        self._device_factors = None


def candidate_mask(
    model: SimilarModel,
    query: Query,
    query_idx: list[int],
    out: np.ndarray | None = None,
) -> np.ndarray:
    """ref isCandidateItem (ALSAlgorithm.scala:236-260). ``out`` writes the
    mask into a preallocated row (the batch path assembles query masks
    directly into its reusable [B, n] staging buffer)."""
    n = len(model.item_vocab)
    if out is None:
        mask = np.ones(n, bool)
    else:
        mask = out
        mask[...] = True
    mask[query_idx] = False  # exclude query items
    if query.white_list is not None:
        wl = np.zeros(n, bool)
        for it in query.white_list:
            idx = model.item_index(it)
            if idx is not None:
                wl[idx] = True
        mask &= wl
    if query.black_list is not None:
        for it in query.black_list:
            idx = model.item_index(it)
            if idx is not None:
                mask[idx] = False
    if query.categories is not None:
        for i in range(n):
            cats = model.item_categories[i]
            # items without categories are discarded when filtering by category
            if cats is None or not (cats & query.categories):
                mask[i] = False
    if query.category_black_list is not None:
        for i in range(n):
            cats = model.item_categories[i]
            if cats is not None and (cats & query.category_black_list):
                mask[i] = False
    return mask


# ---------------------------------------------------------------------------
# Algorithms
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = 3
    # "cg" | "cg_fused" | "cholesky" (see ops/als.ALSConfig.solver)
    solver: str = "cg"


class _ALSBase(JaxAlgorithm):
    params_class = ALSAlgorithmParams
    params: ALSAlgorithmParams

    event_kind = "view"

    def _interactions(self, pd: TrainingData) -> tuple[np.ndarray, np.ndarray]:
        if self.event_kind == "view":
            return pd.view_user_idx, pd.view_item_idx
        return pd.like_user_idx, pd.like_item_idx

    @staticmethod
    def _build_model(item_factors, pd: TrainingData) -> SimilarModel:
        """L2-normalise for cosine scoring and package with vocab/metadata."""
        vf = np.asarray(item_factors)
        norms = np.linalg.norm(vf, axis=1, keepdims=True)
        vf = vf / np.where(norms == 0, 1.0, norms)
        return SimilarModel(
            vf,
            list(pd.item_vocab),
            list(pd.item_categories),
            pd.item_properties,
        )

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> SimilarModel:
        users, items = self._interactions(pd)
        if len(users) == 0:
            raise ValueError(f"no {self.event_kind} events to train on")
        # count interactions as implicit ratings (ref trainImplicit on counts)
        pair, counts = np.unique(
            np.stack([users, items], 1), axis=0, return_counts=True
        )
        cfg = ALSConfig(
            rank=self.params.rank,
            iterations=self.params.num_iterations,
            reg=self.params.lambda_,
            implicit=True,
            alpha=self.params.alpha,
            seed=self.params.seed if self.params.seed is not None else 0,
            solver=self.params.solver,
        )
        _, item_factors = als_train(
            pair[:, 0],
            pair[:, 1],
            counts.astype(np.float32),
            len(pd.user_vocab),
            len(pd.item_vocab),
            cfg,
        )
        return self._build_model(item_factors, pd)

    def predict(self, model: SimilarModel, query: Query) -> PredictedResult:
        return self.predict_batch(model, [query])[0]

    def predict_batch(
        self, model: SimilarModel, queries: Sequence[Query]
    ) -> list[PredictedResult]:
        return self.predict_batch_dispatch(model, queries)()

    @staticmethod
    def _has_filters(q: Query) -> bool:
        return (
            q.categories is not None
            or q.category_black_list is not None
            or q.white_list is not None
            or q.black_list is not None
        )

    def predict_batch_dispatch(self, model: SimilarModel, queries: Sequence[Query]):
        """One fused device call for the whole micro-batch: query-item
        indices and per-query candidate masks are assembled directly into
        reusable staging buffers, the gather->sum-cosine->mask->top-k runs
        as one jitted program, and only [B, k] score/index pairs are
        fetched (in the returned finalize, so the query server overlaps
        transport with the next batch's dispatch).

        With an ANN index pinned (docs/ann.md), scoring routes through
        the clustered search instead: the summed query vector (sum of
        cosines == dot with the summed factor vector) probes nprobe
        buckets, so the corpus-wide matmul disappears. Filter-less
        batches exclude the query's own items inside the kernel by id;
        filtered batches hand their candidate mask to the masked search
        variant. Exact stays the fallback and the sampled recall shadow."""
        from predictionio_tpu.ann.lifecycle import ATTR as _ANN_ATTR

        n = len(model.item_vocab)
        results: list[PredictedResult | None] = [None] * len(queries)
        rows: list[int] = []
        row_qidx: list[list[int]] = []
        max_q = 1
        max_num = 1
        filtered = False
        for i, q in enumerate(queries):
            qidx = [
                j for it in q.items if (j := model.item_index(it)) is not None
            ]
            if not qidx or q.num <= 0:
                results[i] = PredictedResult(())
                continue
            rows.append(i)
            row_qidx.append(qidx)
            max_q = max(max_q, len(qidx))
            max_num = max(max_num, q.num)
            filtered = filtered or self._has_filters(q)
        handle = None
        ann = None
        exact_handle = None
        kk = 0
        if rows:
            # pow2 buckets on batch/query-width/k keep the compile universe
            # at ~log^3 programs (same discipline as ops/als warmup_buckets)
            b = topk.next_pow2(len(rows))
            qcap = topk.next_pow2(max_q)
            pool = topk.scratch()
            qidx_buf = pool.zeros("similar.qidx", (b, qcap), np.int32)
            qw_buf = pool.zeros("similar.qw", (b, qcap), np.float32)
            for row, qidx in enumerate(row_qidx):
                qidx_buf[row, : len(qidx)] = qidx
                qw_buf[row, : len(qidx)] = 1.0
            kk = min(topk.next_pow2(max_num), n)
            ann = getattr(model, _ANN_ATTR, None)
            if ann is not None and not ann.supports(kk, filtered=filtered):
                ann.count_fallback(len(rows))
                ann = None
            mask_buf = None
            sample = ann is not None and ann.take_recall_sample()
            if ann is None or filtered or sample:
                # the exact kernels (and the masked ANN variant) consume
                # the full candidate mask; the filter-less pure-ANN path
                # skips this O(B*n) host assembly entirely
                mask_buf = pool.get("similar.mask", (b, n), np.bool_)
                mask_buf[len(rows):] = True  # pad rows: harmless full mask
                for row, (i, qidx) in enumerate(zip(rows, row_qidx)):
                    candidate_mask(model, queries[i], qidx, out=mask_buf[row])
            if ann is not None:
                qvec_buf = pool.zeros(
                    "similar.qvec", (b, model.item_factors.shape[1]), np.float32
                )
                for row, qidx in enumerate(row_qidx):
                    # sum of per-item cosines == one dot with the summed
                    # normalized factors — the IVF probe sees one vector
                    np.sum(model.item_factors[qidx], axis=0, out=qvec_buf[row])
                if filtered:
                    handle = ann.search_async(qvec_buf, kk, mask=mask_buf)
                else:
                    excl_buf = pool.full(
                        "similar.excl", (b, qcap), np.int32, -1
                    )
                    for row, qidx in enumerate(row_qidx):
                        excl_buf[row, : len(qidx)] = qidx
                    handle = ann.search_async(qvec_buf, kk, exclude=excl_buf)
                if sample:
                    exact_handle = topk.gather_sum_top_k_async(
                        model.device_factors(), qidx_buf, qw_buf, mask_buf, kk
                    )
            else:
                handle = topk.gather_sum_top_k_async(
                    model.device_factors(), qidx_buf, qw_buf, mask_buf, kk
                )

        def finalize() -> list[PredictedResult]:
            if handle is not None:
                if ann is not None:
                    scores, idx = ann.fetch(handle, rows=len(rows))
                    if exact_handle is not None:
                        _, exact_idx = topk.fetch_topk(exact_handle)
                        ann.record_recall(idx, exact_idx, rows=len(rows))
                else:
                    scores, idx = topk.fetch_topk(handle)
                for row, i in enumerate(rows):
                    num = min(queries[i].num, kk)
                    results[i] = PredictedResult(
                        tuple(
                            ItemScore(
                                model.item_vocab[int(it)],
                                float(s),
                                model.properties_of(int(it)),
                            )
                            for s, it in zip(scores[row, :num], idx[row, :num])
                            if np.isfinite(s)
                        )
                    )
            return results  # type: ignore[return-value]

        return finalize

    def warmup_serving(self, model: SimilarModel, max_batch: int) -> None:
        """Pre-compile the single-item-query program for every pow2 batch
        bucket at the default k, so the first burst after deploy/reload
        pays no XLA compiles on the common shape. The exact program warms
        even with an ANN index pinned (it stays the recall shadow and the
        fallback); the index's own buckets warm via AnnServing.warmup."""
        from predictionio_tpu.ann.lifecycle import ATTR as _ANN_ATTR

        n = len(model.item_vocab)
        kk = min(topk.next_pow2(10), n)
        topk.warmup_pow2_buckets(
            max_batch,
            lambda b: topk.gather_sum_top_k_async(
                model.device_factors(),
                np.zeros((b, 1), np.int32),
                np.zeros((b, 1), np.float32),
                np.ones((b, n), bool),
                kk,
            ),
        )
        ann = getattr(model, _ANN_ATTR, None)
        if ann is not None and ann.supports(kk):
            # the filter-less dispatch shape (id exclusion) is the hot one
            topk.warmup_pow2_buckets(
                max_batch,
                lambda b: ann.search_async(
                    np.zeros((b, model.item_factors.shape[1]), np.float32),
                    kk,
                    exclude=np.full((b, 1), -1, np.int32),
                )[0],
            )


class ALSAlgorithm(_ALSBase):
    event_kind = "view"


class RateALSAlgorithm(_ALSBase):
    """train-with-rate-event variant (ref ``train-with-rate-event/
    ALSAlgorithm.scala:66-129``): explicit ALS on the latest rating per
    (user, item) instead of implicit ALS on view counts."""

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> SimilarModel:
        if pd.rate_user_idx is None or len(pd.rate_user_idx) == 0:
            raise ValueError(
                "no rate events to train on; set DataSourceParams.rate_event"
            )
        cfg = ALSConfig(
            rank=self.params.rank,
            iterations=self.params.num_iterations,
            reg=self.params.lambda_,
            implicit=False,
            seed=self.params.seed if self.params.seed is not None else 0,
            solver=self.params.solver,
        )
        _, item_factors = als_train(
            pd.rate_user_idx,
            pd.rate_item_idx,
            pd.rate_values,
            len(pd.user_vocab),
            len(pd.item_vocab),
            cfg,
        )
        return self._build_model(item_factors, pd)


class LikeAlgorithm(_ALSBase):
    """ref LikeAlgorithm.scala — same scoring trained on like events."""

    event_kind = "like"


@dataclasses.dataclass(frozen=True)
class CooccurrenceParams(Params):
    n: int = 20  # top-N cooccurring items kept per item


@dataclasses.dataclass
class CooccurrenceModel:
    top_map: dict[int, list[tuple[int, int]]]
    item_vocab: list[str]
    item_categories: list[frozenset[str] | None]
    item_properties: list[dict[str, Any] | None] | None = None

    def __post_init__(self):
        self._index = {v: i for i, v in enumerate(self.item_vocab)}

    def item_index(self, item: str) -> int | None:
        return self._index.get(item)

    def properties_of(self, i: int) -> dict[str, Any] | None:
        if self.item_properties is None:
            return None
        return self.item_properties[i]

    def __getstate__(self):
        return {
            "top_map": self.top_map,
            "item_vocab": self.item_vocab,
            "item_categories": self.item_categories,
            "item_properties": self.item_properties,
        }

    def __setstate__(self, state):
        state.setdefault("item_properties", None)
        self.__dict__.update(state)
        self._index = {v: i for i, v in enumerate(self.item_vocab)}


class CooccurrenceAlgorithm(LocalAlgorithm):
    params_class = CooccurrenceParams
    params: CooccurrenceParams

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> CooccurrenceModel:
        top_map = cooccurrence_top_n(
            pd.view_user_idx, pd.view_item_idx, len(pd.item_vocab), self.params.n
        )
        return CooccurrenceModel(
            top_map,
            list(pd.item_vocab),
            list(pd.item_categories),
            pd.item_properties,
        )

    def predict(self, model: CooccurrenceModel, query: Query) -> PredictedResult:
        query_idx = [
            i for it in query.items if (i := model.item_index(it)) is not None
        ]
        score_map = score_by_cooccurrence(model.top_map, query_idx)
        shim = SimilarModel(
            np.zeros((len(model.item_vocab), 1), np.float32),
            model.item_vocab,
            model.item_categories,
        )
        mask = candidate_mask(shim, query, query_idx)
        scores = np.full(len(model.item_vocab), -np.inf)
        for i, s in score_map.items():
            scores[i] = s
        # cooccurrence scores are host-born (a sparse count map) — the
        # sanctioned host ending lives in the fused-top-k helper
        sk, si = topk.host_top_k(scores, mask, query.num)
        return PredictedResult(
            tuple(
                ItemScore(model.item_vocab[int(i)], float(s), model.properties_of(int(i)))
                for s, i in zip(sk, si)
            )
        )


class Serving(BaseServing):
    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        Preparator,
        {
            "als": ALSAlgorithm,
            "cooccurrence": CooccurrenceAlgorithm,
            "likealgo": LikeAlgorithm,
            "rateals": RateALSAlgorithm,
        },
        Serving,
        query_class=Query,
    )
