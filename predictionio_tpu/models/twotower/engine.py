"""Two-tower retrieval engine (DASE components).

Wire contract mirrors the recommendation template (Query {user, num} ->
PredictedResult {itemScores}) so the two are drop-in interchangeable behind
the same query server.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    JaxAlgorithm,
    Params,
    SanityCheck,
)
from predictionio_tpu.models.twotower.model import (
    TwoTower,
    TwoTowerConfig,
    train_two_tower,
)
from predictionio_tpu.workflow.context import WorkflowContext


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "Query":
        return Query(user=str(d["user"]), num=int(d.get("num", 10)))


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...]

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "itemScores": [{"item": s.item, "score": s.score} for s in self.item_scores]
        }


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: tuple[str, ...] = ("rate", "buy", "view")


@dataclasses.dataclass
class TrainingData(SanityCheck):
    user_idx: np.ndarray
    item_idx: np.ndarray
    user_vocab: list[str]
    item_vocab: list[str]
    timestamps: np.ndarray | None = None  # event times for history ordering

    def sanity_check(self) -> None:
        if len(self.user_idx) == 0:
            raise ValueError("no interaction events found; check app data")


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        col = ctx.p_event_store().to_columnar_cached(
            app_name=self.params.app_name or ctx.app_name,
            channel_name=ctx.channel_name,
            event_names=list(self.params.event_names),
            entity_type="user",
            target_entity_type="item",
        )
        valid = (col.entity_ids >= 0) & (col.target_ids >= 0)
        return TrainingData(
            col.entity_ids[valid],
            col.target_ids[valid],
            col.entity_vocab,
            col.target_vocab,
            timestamps=col.timestamps[valid],
        )


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td


@dataclasses.dataclass(frozen=True)
class TwoTowerAlgorithmParams(Params):
    embed_dim: int = 64
    hidden: tuple[int, ...] = (128,)
    out_dim: int = 32
    temperature: float = 0.05
    learning_rate: float = 1e-3
    batch_size: int = 4096
    epochs: int = 5
    seed: int = 0
    mesh: str = ""  # e.g. "data=-1,model=2"; empty = all devices on data
    # sequence encoder over each user's recent item history (consumes the
    # pallas fused-attention kernel on TPU, ops/attention.py); 0 disables
    history_len: int = 0
    n_heads: int = 2
    # sequence/context parallelism for the encoder: shard the history axis
    # over the mesh's `model` axis (ring or ulysses attention over ICI,
    # composed with `data`-axis batch sharding). Requires history_len > 0,
    # history_len % model-axis == 0, and a mesh with model > 1; serving is
    # unaffected (attention has no parameters, models load mesh-less).
    context_parallel: bool = False
    sp_impl: str = "ring"  # "ring" | "ulysses"


@dataclasses.dataclass
class TwoTowerModelState(SanityCheck):
    config: TwoTowerConfig
    params: Any  # host numpy pytree
    item_embeddings: np.ndarray
    user_vocab: list[str]
    item_vocab: list[str]
    losses: list[float]
    history: np.ndarray | None = None  # [n_users, T] when the encoder is on

    def __post_init__(self):
        self._user_index: dict[str, int] | None = None
        self._device_items = None
        self._device_params = None
        self._serve_fn = None
        self._embed_fn = None
        self._model: TwoTower | None = None

    def sanity_check(self) -> None:
        if not np.all(np.isfinite(self.item_embeddings)):
            raise ValueError("two-tower training produced non-finite embeddings")

    def user_index(self, user: str) -> int | None:
        if self._user_index is None:
            self._user_index = {u: i for i, u in enumerate(self.user_vocab)}
        return self._user_index.get(user)

    def model(self) -> TwoTower:
        if self._model is None:
            self._model = TwoTower(self.config)
        return self._model

    def device_items(self):
        if self._device_items is None:
            import jax.numpy as jnp

            self._device_items = jnp.asarray(self.item_embeddings)
        return self._device_items

    def device_params(self):
        """Tower params re-landed on device once (the checkpoint form is
        host numpy); serving must never re-upload them per query."""
        if self._device_params is None:
            import jax
            import jax.numpy as jnp

            self._device_params = jax.tree_util.tree_map(
                jnp.asarray, self.params
            )
        return self._device_params

    def serve_topk(self, uidx, hist, k: int):
        """Dispatch the fused user-tower -> dot-products -> top-k program
        for a [B] batch of user indices ([B,T] histories when the sequence
        encoder is on). One compiled program per (B, k) bucket; returns
        the packed [B,2,k] handle (decode with ``ops.topk.fetch_topk``)."""
        if self._serve_fn is None:
            import functools

            import jax
            import jax.numpy as jnp

            from predictionio_tpu.models.twotower.model import TwoTower as _TT
            from predictionio_tpu.ops.topk import pack_batch

            mdl = self.model()

            @functools.partial(
                jax.jit, static_argnames=("k",), donate_argnums=(2, 3)
            )
            def _serve(params, items, uidx, hist, k: int):
                u = mdl.apply(
                    {"params": params}, uidx, hist, method=_TT.embed_users
                )
                scores = u @ items.T  # [B, n_items] on the MXU
                s, i = jax.lax.top_k(scores, k)
                return pack_batch(s, i)

            self._serve_fn = _serve
        from predictionio_tpu.ops.als import upload

        # upload() COPIES: uidx/hist live in reusable scratch buffers the
        # dispatcher overwrites for the next batch while this one is in
        # flight (jnp.asarray would alias them on the CPU backend)
        hist_d = upload(hist) if hist is not None else None
        return self._serve_fn(
            self.device_params(),
            self.device_items(),
            upload(uidx),
            hist_d,
            k,
        )

    def embed_users_async(self, uidx, hist):
        """Dispatch the user-tower forward alone: the [B, out_dim] device
        embedding handle the ANN search composes with (tower -> probe ->
        bucket scoring stay on device, no host round-trip in between)."""
        if self._embed_fn is None:
            import functools

            import jax

            from predictionio_tpu.models.twotower.model import TwoTower as _TT

            mdl = self.model()

            @functools.partial(jax.jit, donate_argnums=(1, 2))
            def _embed(params, uidx, hist):
                return mdl.apply(
                    {"params": params}, uidx, hist, method=_TT.embed_users
                )

            self._embed_fn = _embed
        from predictionio_tpu.ops.als import upload

        hist_d = upload(hist) if hist is not None else None
        return self._embed_fn(self.device_params(), upload(uidx), hist_d)

    def __getstate__(self):
        return {
            "config": self.config,
            "params": self.params,
            "item_embeddings": self.item_embeddings,
            "user_vocab": self.user_vocab,
            "item_vocab": self.item_vocab,
            "losses": self.losses,
            "history": self.history,
        }

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.__dict__.setdefault("history", None)  # pre-encoder blobs
        self._user_index = None
        self._device_items = None
        self._device_params = None
        self._serve_fn = None
        self._embed_fn = None
        self._model = None


class TwoTowerAlgorithm(JaxAlgorithm):
    params_class = TwoTowerAlgorithmParams
    params: TwoTowerAlgorithmParams

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> TwoTowerModelState:
        config = TwoTowerConfig(
            n_users=max(len(pd.user_vocab), 1),
            n_items=max(len(pd.item_vocab), 1),
            embed_dim=self.params.embed_dim,
            hidden=tuple(self.params.hidden),
            out_dim=self.params.out_dim,
            temperature=self.params.temperature,
            learning_rate=self.params.learning_rate,
            batch_size=self.params.batch_size,
            epochs=self.params.epochs,
            seed=self.params.seed,
            history_len=self.params.history_len,
            n_heads=self.params.n_heads,
            context_parallel=self.params.context_parallel,
            sp_impl=self.params.sp_impl,
        )
        mesh = None
        if self.params.mesh:
            from predictionio_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(self.params.mesh)
        history = None
        if config.history_len > 0:
            from predictionio_tpu.models.twotower.model import build_history_matrix

            history = build_history_matrix(
                pd.user_idx,
                pd.item_idx,
                pd.timestamps,
                config.n_users,
                config.history_len,
            )
        result = train_two_tower(
            pd.user_idx, pd.item_idx, config, mesh=mesh, history=history
        )
        return TwoTowerModelState(
            config=config,
            params=result.params,
            item_embeddings=result.item_embeddings,
            user_vocab=pd.user_vocab,
            item_vocab=pd.item_vocab,
            losses=result.losses,
            history=history,
        )

    def predict(self, model: TwoTowerModelState, query: Query) -> PredictedResult:
        return self.predict_batch(model, [query])[0]

    def predict_batch(
        self, model: TwoTowerModelState, queries: Sequence[Query]
    ) -> list[PredictedResult]:
        return self.predict_batch_dispatch(model, queries)()

    def predict_batch_dispatch(
        self, model: TwoTowerModelState, queries: Sequence[Query]
    ):
        """Serving micro-batch as ONE fused device program: user-tower
        forward -> dot products against the resident item table -> top-k,
        with user indices (and histories) assembled into reusable staging
        buffers and only [B, k] results fetched in the finalize. Unknown
        users answer empty without touching the device.

        When the deployed version pins an ANN index (docs/ann.md), the
        dot-products stage routes through it instead: the user embedding
        handle feeds the two-stage clustered search and only nprobe
        buckets are scored — O(batch * nprobe * cap), not O(batch *
        corpus). Exact scoring remains the fallback (no index, or k wider
        than the probe pool); sampled batches ALSO run exact as a shadow
        to measure the live recall proxy."""
        from predictionio_tpu.ann.lifecycle import ATTR as _ANN_ATTR
        from predictionio_tpu.ops import topk

        n = len(model.item_vocab)
        results: list[PredictedResult | None] = [None] * len(queries)
        rows: list[int] = []
        uidxs: list[int] = []
        max_num = 1
        for i, q in enumerate(queries):
            uidx = model.user_index(q.user)
            if uidx is None or q.num <= 0:
                results[i] = PredictedResult(())
                continue
            rows.append(i)
            uidxs.append(uidx)
            max_num = max(max_num, q.num)
        handle = None
        ann = None
        exact_handle = None
        kk = 0
        if rows:
            b = topk.next_pow2(len(rows))
            pool = topk.scratch()
            uidx_buf = pool.zeros("twotower.uidx", (b,), np.int32)
            uidx_buf[: len(rows)] = uidxs  # pad rows serve user 0, dropped
            hist_buf = None
            if model.history is not None:
                hist_buf = pool.get(
                    "twotower.hist", (b, model.history.shape[1]),
                    model.history.dtype,
                )
                np.take(model.history, uidx_buf, axis=0, out=hist_buf)
            kk = min(topk.next_pow2(max_num), n)
            ann = getattr(model, _ANN_ATTR, None)
            if ann is not None and not ann.supports(kk):
                ann.count_fallback(len(rows))
                ann = None
            if ann is not None:
                vec_handle = model.embed_users_async(uidx_buf, hist_buf)
                handle = ann.search_async(vec_handle, kk)
                if ann.take_recall_sample():
                    exact_handle = model.serve_topk(uidx_buf, hist_buf, kk)
            else:
                handle = model.serve_topk(uidx_buf, hist_buf, kk)

        def finalize() -> list[PredictedResult]:
            if handle is not None:
                from predictionio_tpu.ops.topk import fetch_topk

                if ann is not None:
                    scores, idx = ann.fetch(handle, rows=len(rows))
                    if exact_handle is not None:
                        _, exact_idx = fetch_topk(exact_handle)
                        ann.record_recall(idx, exact_idx, rows=len(rows))
                else:
                    scores, idx = fetch_topk(handle)
                for row, i in enumerate(rows):
                    num = min(queries[i].num, kk)
                    results[i] = PredictedResult(
                        tuple(
                            ItemScore(model.item_vocab[int(it)], float(s))
                            for s, it in zip(scores[row, :num], idx[row, :num])
                            if np.isfinite(s)
                        )
                    )
            return results  # type: ignore[return-value]

        return finalize

    def warmup_serving(self, model: TwoTowerModelState, max_batch: int) -> None:
        """Pre-compile the fused tower->score->top-k program for every
        pow2 batch bucket at the default k — and, when an ANN index is
        pinned, the tower->probe->bucket-search composition the dispatch
        path actually runs (plus exact, which stays the shadow/fallback)."""
        from predictionio_tpu.ann.lifecycle import ATTR as _ANN_ATTR
        from predictionio_tpu.ops import topk

        n = len(model.item_vocab)
        kk = min(topk.next_pow2(10), n)
        ann = getattr(model, _ANN_ATTR, None)
        if ann is not None and not ann.supports(kk):
            ann = None

        def dispatch(b: int):
            hist = (
                np.zeros((b, model.history.shape[1]), model.history.dtype)
                if model.history is not None
                else None
            )
            if ann is not None:
                packed, _counts = ann.search_async(
                    model.embed_users_async(np.zeros(b, np.int32), hist), kk
                )
                return packed
            return model.serve_topk(np.zeros(b, np.int32), hist, kk)

        topk.warmup_pow2_buckets(max_batch, dispatch)
        if ann is not None:
            # the exact program stays warm at every bucket too: it is the
            # recall shadow (sampled at arbitrary batch sizes) and the
            # automatic fallback — a shadow must never pay a serving-time
            # compile the watcher would alarm on
            def dispatch_exact(b: int):
                hist = (
                    np.zeros((b, model.history.shape[1]), model.history.dtype)
                    if model.history is not None
                    else None
                )
                return model.serve_topk(np.zeros(b, np.int32), hist, kk)

            topk.warmup_pow2_buckets(max_batch, dispatch_exact)


class Serving(BaseServing):
    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        Preparator,
        {"twotower": TwoTowerAlgorithm},
        Serving,
        query_class=Query,
    )
