"""Two-tower retrieval network + sharded training step.

TPU-first design (no reference counterpart — this is the deep-retrieval
workload from BASELINE.json):

  - Towers: id embedding -> MLP -> L2-normalized output embedding; bf16
    matmuls on the MXU, f32 accumulation for the loss.
  - Loss: in-batch sampled softmax with temperature — logits are one
    [B, B] matmul of user x item embeddings, the canonical retrieval loss.
  - Sharding: batch axis over the mesh's ``data`` axis; the two embedding
    tables are sharded over the ``model`` axis along the vocab dimension
    (they dominate memory at MovieLens-20M scale); dense layers replicated.
    XLA/GSPMD inserts the all-gathers for embedding lookups and the psum for
    the data-parallel gradient — no hand-written collectives.
  - The train step is one jitted function with donated optimizer state.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TwoTowerConfig:
    n_users: int
    n_items: int
    embed_dim: int = 64
    hidden: tuple[int, ...] = (128,)
    out_dim: int = 32
    temperature: float = 0.05
    learning_rate: float = 1e-3
    batch_size: int = 4096
    epochs: int = 5
    seed: int = 0


class Tower(nn.Module):
    vocab: int
    embed_dim: int
    hidden: tuple[int, ...]
    out_dim: int

    @nn.compact
    def __call__(self, ids: jnp.ndarray) -> jnp.ndarray:
        x = nn.Embed(self.vocab, self.embed_dim, name="embed")(ids)
        x = x.astype(jnp.bfloat16)
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"dense_{i}", dtype=jnp.bfloat16)(x))
        x = nn.Dense(self.out_dim, name="out", dtype=jnp.bfloat16)(x)
        x = x.astype(jnp.float32)
        return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-8)


class TwoTower(nn.Module):
    config: TwoTowerConfig

    def setup(self):
        c = self.config
        self.user_tower = Tower(c.n_users, c.embed_dim, c.hidden, c.out_dim)
        self.item_tower = Tower(c.n_items, c.embed_dim, c.hidden, c.out_dim)

    def __call__(self, user_ids, item_ids):
        return self.user_tower(user_ids), self.item_tower(item_ids)

    def embed_users(self, user_ids):
        return self.user_tower(user_ids)

    def embed_items(self, item_ids):
        return self.item_tower(item_ids)


def param_sharding_tree(params: Any, mesh: Mesh) -> Any:
    """Embedding tables sharded over ``model`` along vocab; rest replicated."""

    def spec_for(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if "embed" in names and getattr(leaf, "ndim", 0) == 2:
            return NamedSharding(mesh, P("model", None))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("data"))


def loss_fn(model: TwoTower, params, user_ids, item_ids, temperature: float):
    u, v = model.apply({"params": params}, user_ids, item_ids)
    logits = (u @ v.T) / temperature  # [B, B]
    labels = jnp.arange(u.shape[0])
    # symmetric in-batch softmax (user->item and item->user)
    l1 = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    l2 = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels).mean()
    return 0.5 * (l1 + l2)


def make_train_step(model: TwoTower, tx, temperature: float):
    def train_step(params, opt_state, user_ids, item_ids):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(model, p, user_ids, item_ids, temperature)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


@dataclasses.dataclass
class TrainResult:
    params: Any  # host-numpy pytree
    losses: list[float]
    item_embeddings: np.ndarray  # [n_items, out_dim] precomputed for serving


def train_two_tower(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    config: TwoTowerConfig,
    mesh: Mesh | None = None,
) -> TrainResult:
    """Full training loop: shard the interaction list, run jitted steps.

    Works on any mesh with axes (data, model) — including 1x1 (single chip)
    and the 8-device CPU test mesh.
    """
    if mesh is None:
        from predictionio_tpu.parallel.mesh import make_mesh

        try:
            mesh = make_mesh("data=-1,model=1")
        except ValueError:
            mesh = make_mesh("data=1,model=1")
    model = TwoTower(config)
    rng = jax.random.PRNGKey(config.seed)
    B = min(config.batch_size, max(len(user_idx), 8))
    # round batch to a multiple of the data axis (static shapes)
    data_size = mesh.shape["data"]
    B = max(data_size, (B // data_size) * data_size)
    init_u = jnp.zeros((B,), jnp.int32)
    params = model.init(rng, init_u, init_u)["params"]
    p_shardings = param_sharding_tree(params, mesh)
    params = jax.device_put(params, p_shardings)
    tx = optax.adam(config.learning_rate)
    opt_state = tx.init(params)
    b_sharding = batch_sharding(mesh)

    step = jax.jit(
        make_train_step(model, tx, config.temperature),
        donate_argnums=(0, 1),
    )

    n = len(user_idx)
    rng_np = np.random.default_rng(config.seed)
    losses: list[float] = []
    steps_per_epoch = max(1, n // B)
    for _ in range(config.epochs):
        perm = rng_np.permutation(n)
        for s in range(steps_per_epoch):
            sel = perm[s * B : (s + 1) * B]
            if len(sel) < B:  # pad by wrapping (static shapes)
                sel = np.concatenate([sel, perm[: B - len(sel)]])
            ub = jax.device_put(user_idx[sel].astype(np.int32), b_sharding)
            ib = jax.device_put(item_idx[sel].astype(np.int32), b_sharding)
            params, opt_state, loss = step(params, opt_state, ub, ib)
        losses.append(float(loss))

    # Precompute the full item-embedding table for serving top-k.
    @jax.jit
    def embed_items(params, ids):
        return model.apply({"params": params}, ids, method=TwoTower.embed_items)

    ids = jnp.arange(config.n_items, dtype=jnp.int32)
    item_emb = np.asarray(embed_items(params, ids))
    host_params = jax.tree_util.tree_map(lambda x: np.asarray(x), params)
    return TrainResult(host_params, losses, item_emb)


def user_embedding(model: TwoTower, params, user_ids: jnp.ndarray) -> jnp.ndarray:
    return model.apply({"params": params}, user_ids, method=TwoTower.embed_users)
