"""Two-tower retrieval network + sharded training step.

TPU-first design (no reference counterpart — this is the deep-retrieval
workload from BASELINE.json):

  - Towers: id embedding -> MLP -> L2-normalized output embedding; bf16
    matmuls on the MXU, f32 accumulation for the loss.
  - Loss: in-batch sampled softmax with temperature — logits are one
    [B, B] matmul of user x item embeddings, the canonical retrieval loss.
  - Sharding: batch axis over the mesh's ``data`` axis; the two embedding
    tables are sharded over the ``model`` axis along the vocab dimension
    (they dominate memory at MovieLens-20M scale); dense layers replicated.
    XLA/GSPMD inserts the all-gathers for embedding lookups and the psum for
    the data-parallel gradient — no hand-written collectives.
  - The train step is one jitted function with donated optimizer state.
  - Optional sequence encoder (``history_len > 0``): the user tower fuses a
    causal self-attention encoding of the user's recent item history into
    the id embedding. Attention runs through ``ops.attention.fused_attention``
    — the pallas TPU kernel on TPU, the jnp reference elsewhere. Histories
    are chronological with -1 padding at the END, so causal masking already
    keeps pad keys invisible to real positions and pooling masks the rest;
    no separate key-padding mask is needed.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class TwoTowerConfig:
    n_users: int
    n_items: int
    embed_dim: int = 64
    hidden: tuple[int, ...] = (128,)
    out_dim: int = 32
    temperature: float = 0.05
    learning_rate: float = 1e-3
    batch_size: int = 4096
    epochs: int = 5
    seed: int = 0
    # mid-training checkpoint/resume (the reference has no step-level
    # checkpointing, SURVEY.md section 5 — `pio train` is all-or-nothing;
    # this closes that gap). Directory for epoch checkpoints; None disables.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 1  # epochs between checkpoints
    resume: bool = True  # continue from the newest checkpoint if present
    # sequence encoder: 0 disables; > 0 = length of the per-user item
    # history consumed by causal self-attention in the user tower
    history_len: int = 0
    n_heads: int = 2
    # sequence/context parallelism for the history encoder: when True and a
    # mesh is passed to ``train_two_tower``, the encoder's attention shards
    # the history sequence over the mesh's ``model`` axis (ring attention's
    # K/V ppermute or Ulysses' all_to_alls over ICI) composed with the
    # batch's ``data``-axis sharding — dp x sp on one 2-D mesh. This is how
    # histories longer than one device's memory train; at short
    # history_len it is a correctness-exercised path, not a win.
    context_parallel: bool = False
    sp_impl: str = "ring"  # "ring" | "ulysses"
    # sampled-softmax log-Q debiasing of in-batch negatives (see loss_fn);
    # uses the training set's empirical item frequency
    logq_correction: bool = True

    def __post_init__(self):
        if self.history_len > 0 and self.embed_dim % self.n_heads:
            raise ValueError(
                f"embed_dim ({self.embed_dim}) must be divisible by n_heads "
                f"({self.n_heads}) for the history encoder"
            )
        if self.sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be ring|ulysses, got {self.sp_impl!r}")
        if self.context_parallel and self.history_len <= 0:
            raise ValueError(
                "context_parallel requires a history encoder (history_len > 0)"
            )


class SeqEncoder(nn.Module):
    """Causal self-attention encoder over a user's recent item history.

    The consumer of ``ops.attention.fused_attention`` (pallas on TPU).
    Input: [B, T] item indices, chronological, -1 padding at the END —
    causal attention means real positions never attend to pads, and the
    masked mean-pool drops pad positions' outputs.
    """

    vocab: int
    embed_dim: int
    n_heads: int
    max_len: int
    # sequence parallelism: a mesh makes attention shard T over ``sp_axis``
    # (ring or ulysses over ICI), composed with the batch's ``dp_axis``
    # sharding. None = single-device fused_attention.
    sp_mesh: Mesh | None = None
    sp_axis: str = "model"
    dp_axis: str = "data"
    sp_impl: str = "ring"

    def _attend(self, q, k, v):  # [B, H, T, Dh] each
        from predictionio_tpu.ops.attention import (
            fused_attention,
            ring_attention,
            ulysses_attention,
        )

        mesh = self.sp_mesh
        sp_n = dict(mesh.shape).get(self.sp_axis, 1) if mesh is not None else 1
        if mesh is None or sp_n <= 1:
            return fused_attention(q, k, v, causal=True)
        T, H = q.shape[2], q.shape[1]
        # fail loud: a silent fallback here would turn the configured
        # sequence parallelism into a no-op nobody notices
        if T % sp_n:
            raise ValueError(
                f"history_len {T} not divisible by mesh axis "
                f"{self.sp_axis}={sp_n}"
            )
        batch_axis = self.dp_axis if self.dp_axis in mesh.shape else None
        if self.sp_impl == "ulysses":
            if H % sp_n:
                raise ValueError(
                    f"n_heads {H} not divisible by mesh axis "
                    f"{self.sp_axis}={sp_n} (ulysses splits heads)"
                )
            return ulysses_attention(
                q, k, v, mesh, axis=self.sp_axis, causal=True,
                batch_axis=batch_axis,
            )
        return ring_attention(
            q, k, v, mesh, axis=self.sp_axis, causal=True, batch_axis=batch_axis
        )

    @nn.compact
    def __call__(self, hist_ids: jnp.ndarray) -> jnp.ndarray:  # [B, T] -> [B, E]

        valid = hist_ids >= 0  # [B, T]
        # invalid slots (end pads AND train-time target masking, which can
        # land mid-sequence) map to a dedicated learned mask token (index
        # ``vocab``) instead of item 0 — causal followers still see the
        # key, but it carries "nothing" rather than a phantom item
        ids = jnp.where(valid, jnp.maximum(hist_ids, 0), self.vocab)
        x = nn.Embed(self.vocab + 1, self.embed_dim, name="hist_embed")(ids)
        pos = self.param(
            "pos",
            nn.initializers.normal(0.02),
            (self.max_len, self.embed_dim),
        )
        x = x + pos[None, : x.shape[1]]
        x = nn.LayerNorm(name="ln")(x)
        B, T, E = x.shape
        H = self.n_heads
        Dh = E // H

        def heads(name):
            y = nn.Dense(E, name=name)(x)
            return y.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)  # [B,H,T,Dh]

        out = self._attend(heads("q"), heads("k"), heads("v"))
        out = out.transpose(0, 2, 1, 3).reshape(B, T, E)
        out = x + nn.Dense(E, name="proj")(out)  # residual
        # masked mean-pool over valid (non-pad) positions
        w = valid.astype(out.dtype)[..., None]
        denom = jnp.maximum(w.sum(axis=1), 1.0)
        return (out * w).sum(axis=1) / denom


class Tower(nn.Module):
    vocab: int
    embed_dim: int
    hidden: tuple[int, ...]
    out_dim: int

    @nn.compact
    def __call__(self, ids: jnp.ndarray, extra: jnp.ndarray | None = None) -> jnp.ndarray:
        x = nn.Embed(self.vocab, self.embed_dim, name="embed")(ids)
        if extra is not None:
            x = x + extra  # history encoding fused into the id embedding
        x = x.astype(jnp.bfloat16)
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"dense_{i}", dtype=jnp.bfloat16)(x))
        x = nn.Dense(self.out_dim, name="out", dtype=jnp.bfloat16)(x)
        x = x.astype(jnp.float32)
        return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-8)


class TwoTower(nn.Module):
    config: TwoTowerConfig
    # mesh for the history encoder's sequence parallelism (None = off);
    # attention carries no parameters, so checkpoints from a
    # context-parallel train load into a mesh-less serving model unchanged
    sp_mesh: Mesh | None = None

    def setup(self):
        c = self.config
        self.user_tower = Tower(c.n_users, c.embed_dim, c.hidden, c.out_dim)
        self.item_tower = Tower(c.n_items, c.embed_dim, c.hidden, c.out_dim)
        if c.history_len > 0:
            self.hist_encoder = SeqEncoder(
                c.n_items, c.embed_dim, c.n_heads, c.history_len,
                sp_mesh=self.sp_mesh if c.context_parallel else None,
                sp_impl=c.sp_impl,
            )

    def _user_extra(self, user_hist):
        if self.config.history_len > 0 and user_hist is not None:
            return self.hist_encoder(user_hist)
        return None

    def __call__(self, user_ids, item_ids, user_hist=None):
        return (
            self.user_tower(user_ids, self._user_extra(user_hist)),
            self.item_tower(item_ids),
        )

    def embed_users(self, user_ids, user_hist=None):
        return self.user_tower(user_ids, self._user_extra(user_hist))

    def embed_items(self, item_ids):
        return self.item_tower(item_ids)


def param_sharding_tree(params: Any, mesh: Mesh) -> Any:
    """Embedding tables sharded over ``model`` along vocab; rest replicated."""

    def spec_for(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if "embed" in names and getattr(leaf, "ndim", 0) == 2:
            return NamedSharding(mesh, P("model", None))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("data"))


def loss_fn(
    model: TwoTower,
    params,
    user_ids,
    item_ids,
    temperature: float,
    user_hist=None,
    item_log_q=None,
):
    u, v = model.apply({"params": params}, user_ids, item_ids, user_hist)
    logits = (u @ v.T) / temperature  # [B, B]
    B = u.shape[0]
    labels = jnp.arange(B)
    # sampled-softmax log-Q correction (Bengio & Senecal; the standard
    # retrieval-tower debiasing): in-batch negatives are drawn from the
    # empirical item distribution, so popular items are over-penalized as
    # negatives unless log Q(item_j) is subtracted from column j. The same
    # subtraction is a row-constant shift of logits.T, so the item->user
    # direction's softmax is untouched.
    if item_log_q is not None:
        logits = logits - item_log_q[item_ids][None, :]
    # duplicate-collision masking: when item j' == item j (same catalog item
    # drawn twice into the batch), position j' is a FALSE negative for
    # example j — its "wrong" logit is the true item's own score. Masking
    # the off-diagonal duplicates (symmetric, so it also fixes the
    # transposed direction) matters exactly when batch size is comparable
    # to the catalog, where collisions are ubiquitous.
    same_item = item_ids[None, :] == item_ids[:, None]
    dup = same_item & ~jnp.eye(B, dtype=bool)
    logits = jnp.where(dup, jnp.float32(-1e9), logits)
    # symmetric in-batch softmax (user->item and item->user)
    l1 = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    l2 = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels).mean()
    return 0.5 * (l1 + l2)


def make_train_step(
    model: TwoTower,
    tx,
    temperature: float,
    with_history: bool = False,
    item_log_q=None,
):
    if with_history:
        # history matrix [n_users, T] rides on device; per-batch rows are
        # gathered INSIDE the step (one fused gather, no host transfer)
        def train_step_h(params, opt_state, user_ids, item_ids, hist_matrix):
            h = hist_matrix[user_ids]
            # anti-leakage: the training target must not sit in its own
            # example's history (the encoder would just copy its embedding
            # and the in-batch softmax would collapse into a shortcut);
            # masked slots become the learned mask token in SeqEncoder
            h = jnp.where(h == item_ids[:, None], -1, h)
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(
                    model, p, user_ids, item_ids, temperature, h, item_log_q
                )
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return train_step_h

    def train_step(params, opt_state, user_ids, item_ids):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(
                model, p, user_ids, item_ids, temperature, None, item_log_q
            )
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


@dataclasses.dataclass
class TrainResult:
    params: Any  # host-numpy pytree
    losses: list[float]
    item_embeddings: np.ndarray  # [n_items, out_dim] precomputed for serving


def build_history_matrix(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    timestamps: np.ndarray | None,
    n_users: int,
    history_len: int,
) -> np.ndarray:
    """Per-user last-``history_len`` item indices, chronological, -1 padded
    at the END (the layout SeqEncoder requires)."""
    hist = np.full((n_users, history_len), -1, np.int32)
    n = len(user_idx)
    if n == 0:
        return hist
    if timestamps is not None:
        order = np.lexsort((item_idx, timestamps, user_idx))
    else:
        # no timestamps: preserve each user's ORIGINAL event order (stable
        # sort by user only) — sorting by item id would fabricate a
        # "recency" the encoder then learns from
        order = np.argsort(user_idx, kind="stable")
    u_sorted, i_sorted = user_idx[order], item_idx[order]
    # vectorized last-K per user: each row's position within its user's
    # run -> keep only the last K rows of each run -> scatter into the K
    # slots. O(n) after the sort, no per-user python loop (the loop was
    # ~proportional to n_users; the sort dominates either way)
    starts = np.searchsorted(u_sorted, np.arange(n_users))
    deg = np.searchsorted(u_sorted, np.arange(n_users), side="right") - starts
    pos = np.arange(n) - starts[u_sorted]
    drop = np.maximum(deg - history_len, 0)[u_sorted]  # rows trimmed from front
    keep = pos >= drop
    hist[u_sorted[keep], (pos - drop)[keep]] = i_sorted[keep]
    return hist


def train_two_tower(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    config: TwoTowerConfig,
    mesh: Mesh | None = None,
    history: np.ndarray | None = None,
) -> TrainResult:
    """Full training loop: shard the interaction list, run jitted steps.

    Works on any mesh with axes (data, model) — including 1x1 (single chip)
    and the 8-device CPU test mesh. ``history`` ([n_users, history_len],
    -1-padded) enables the sequence encoder when config.history_len > 0.
    """
    if mesh is None:
        from predictionio_tpu.parallel.mesh import make_mesh

        try:
            mesh = make_mesh("data=-1,model=1")
        except ValueError:
            mesh = make_mesh("data=1,model=1")
    model = TwoTower(config, sp_mesh=mesh if config.context_parallel else None)
    rng = jax.random.PRNGKey(config.seed)
    B = min(config.batch_size, max(len(user_idx), 8))
    # round batch to a multiple of the data axis (static shapes)
    data_size = mesh.shape["data"]
    B = max(data_size, (B // data_size) * data_size)
    with_history = config.history_len > 0 and history is not None
    init_u = jnp.zeros((B,), jnp.int32)
    init_h = (
        jnp.zeros((B, config.history_len), jnp.int32) if with_history else None
    )
    params = model.init(rng, init_u, init_u, init_h)["params"]
    p_shardings = param_sharding_tree(params, mesh)
    params = jax.device_put(params, p_shardings)
    tx = optax.adam(config.learning_rate)
    opt_state = tx.init(params)
    b_sharding = batch_sharding(mesh)

    item_log_q = None
    if config.logq_correction and len(item_idx):
        freq = np.bincount(
            np.asarray(item_idx, np.int64), minlength=config.n_items
        ).astype(np.float64)
        q = freq / max(1.0, freq.sum())
        item_log_q = jax.device_put(
            jnp.asarray(np.log(np.maximum(q, 1e-12)), jnp.float32),
            NamedSharding(mesh, P()),
        )
    step = jax.jit(
        make_train_step(
            model,
            tx,
            config.temperature,
            with_history=with_history,
            item_log_q=item_log_q,
        ),
        donate_argnums=(0, 1),
    )
    hist_dev = (
        jax.device_put(
            np.asarray(history, np.int32), NamedSharding(mesh, P())
        )
        if with_history
        else None
    )

    n = len(user_idx)
    losses: list[float] = []
    start_epoch = 0
    # signature guards resume against a DIFFERENT run reusing the dir: a
    # changed config (e.g. the catalog grew, so restored embedding tables
    # would be silently too small — XLA clamps out-of-range gathers) or
    # changed training data must not resume, and a COMPLETED run's
    # checkpoint is deleted below so a scheduled retrain can never skip all
    # its epochs and return the stale parameters (code-review r4)
    run_signature = _train_signature(config, user_idx, item_idx)
    if config.checkpoint_dir and config.resume:
        state = load_train_checkpoint(config.checkpoint_dir)
        if state is not None and state.get("signature") != run_signature:
            logger.warning(
                "ignoring checkpoint in %s: it belongs to a different "
                "config/dataset", config.checkpoint_dir
            )
            state = None
        if state is not None:
            params = jax.device_put(state["params"], p_shardings)
            # optimizer moments follow their parameter's sharding
            opt_state = jax.tree_util.tree_map(
                lambda x: np.asarray(x), state["opt_state"]
            )
            opt_state = _shard_opt_state(opt_state, params, p_shardings)
            start_epoch = int(state["epoch"])
            losses = list(state["losses"])

    # One sequential rng stream for all epochs; a resumed run replays (and
    # discards) the permutations of already-completed epochs so it shuffles
    # identically to an uninterrupted run.
    shuffle_rng = np.random.default_rng(config.seed)
    steps_per_epoch = max(1, n // B)
    for epoch in range(config.epochs):
        perm = shuffle_rng.permutation(n)
        if epoch < start_epoch:
            continue
        for s in range(steps_per_epoch):
            sel = perm[s * B : (s + 1) * B]
            if len(sel) < B:  # pad by wrapping (static shapes)
                sel = np.concatenate([sel, perm[: B - len(sel)]])
            ub = jax.device_put(user_idx[sel].astype(np.int32), b_sharding)
            ib = jax.device_put(item_idx[sel].astype(np.int32), b_sharding)
            if with_history:
                params, opt_state, loss = step(params, opt_state, ub, ib, hist_dev)
            else:
                params, opt_state, loss = step(params, opt_state, ub, ib)
        losses.append(float(loss))
        if config.checkpoint_dir and (epoch + 1) % max(1, config.checkpoint_every) == 0:
            save_train_checkpoint(
                config.checkpoint_dir, params, opt_state, epoch + 1, losses,
                signature=run_signature,
            )
    if config.checkpoint_dir:
        # the checkpoint exists for crash-resume of THIS run; once complete
        # it must not survive to turn the next train into a silent no-op
        clear_train_checkpoint(config.checkpoint_dir)

    # Precompute the full item-embedding table for serving top-k.
    @jax.jit
    def embed_items(params, ids):
        return model.apply({"params": params}, ids, method=TwoTower.embed_items)

    ids = jnp.arange(config.n_items, dtype=jnp.int32)
    item_emb = np.asarray(embed_items(params, ids))
    host_params = jax.tree_util.tree_map(lambda x: np.asarray(x), params)
    return TrainResult(host_params, losses, item_emb)


def user_embedding(
    model: TwoTower, params, user_ids: jnp.ndarray, user_hist: jnp.ndarray | None = None
) -> jnp.ndarray:
    return model.apply(
        {"params": params}, user_ids, user_hist, method=TwoTower.embed_users
    )


# ---------------------------------------------------------------------------
# Mid-training checkpoint/resume
# ---------------------------------------------------------------------------

_CKPT_NAME = "twotower_train_ckpt.bin"


def _train_signature(
    config: TwoTowerConfig, user_idx: np.ndarray, item_idx: np.ndarray
) -> str:
    """Identity of one training run: the model-shaping config fields plus a
    cheap fingerprint of the interaction data. A checkpoint from a run with
    a different signature must never be resumed — restored embedding
    tables of the wrong vocab size gather out-of-bounds SILENTLY (XLA
    clamps), and a different dataset makes 'resume' meaningless."""
    import hashlib

    u = np.asarray(user_idx, np.int64)
    i = np.asarray(item_idx, np.int64)
    h = hashlib.sha1()
    for a in (u[:4096], u[-4096:], i[:4096], i[-4096:]):
        h.update(np.ascontiguousarray(a).tobytes())
    key = (
        config.n_users, config.n_items, config.embed_dim, tuple(config.hidden),
        config.out_dim, config.history_len, config.n_heads, config.seed,
        config.batch_size, len(u), h.hexdigest(),
    )
    return hashlib.sha1(repr(key).encode()).hexdigest()


def save_train_checkpoint(
    directory, params, opt_state, epoch: int, losses, signature: str = ""
) -> str:
    """Atomic epoch checkpoint: params + optimizer moments + progress,
    all pulled to host numpy so the blob is device- and sharding-agnostic
    (same contract as the model repository, ``workflow/model_io.py``)."""
    import os

    from predictionio_tpu.workflow.model_io import serialize_models

    host = jax.tree_util.tree_map(lambda x: np.asarray(x), (params, opt_state))
    blob = serialize_models(
        [
            {
                "params": host[0],
                "opt_state": host[1],
                "epoch": epoch,
                "losses": list(losses),
                "signature": signature,
            }
        ]
    )
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, _CKPT_NAME)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(blob)
    os.replace(tmp, path)
    return path


def clear_train_checkpoint(directory) -> None:
    """Remove a run's checkpoint (called when training completes)."""
    import contextlib
    import os

    with contextlib.suppress(FileNotFoundError):
        os.unlink(os.path.join(directory, _CKPT_NAME))


def load_train_checkpoint(directory) -> dict | None:
    import os

    from predictionio_tpu.workflow.model_io import deserialize_models

    path = os.path.join(directory, _CKPT_NAME)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as fh:
        return deserialize_models(fh.read())[0]


def _shard_opt_state(host_opt_state, params, p_shardings):
    """Re-land restored optimizer moments with each parameter's sharding.

    Optax moment trees (mu/nu) mirror the parameter pytree *structurally*, so
    any subtree of the optimizer state whose treedef equals the parameter
    treedef gets the parameter shardings mapped leaf-for-leaf; everything else
    (scalar ``count`` etc.) is replicated. Structural matching avoids the
    suffix-collision hazard of name-based matching when one parameter path is
    a suffix of another.
    """
    param_treedef = jax.tree_util.tree_structure(params)

    def mirrors_params(node):
        try:
            return jax.tree_util.tree_structure(node) == param_treedef
        except Exception:
            return False

    def put(node):
        if mirrors_params(node):
            return jax.tree_util.tree_map(jax.device_put, node, p_shardings)
        return jax.device_put(node)

    return jax.tree_util.tree_map(put, host_opt_state, is_leaf=mirrors_params)
