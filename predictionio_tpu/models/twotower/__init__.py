"""Two-tower deep retrieval template (flagship model).

The reference has no deep-learning model; BASELINE.json adds "two-tower deep
retrieval as a JAX P2LAlgorithm (MovieLens-20M, data-parallel on v5e-16)" as
a target workload. This package provides:

  - ``model.py``   — flax two-tower network, in-batch-softmax training step,
                     explicit mesh shardings (batch over ``data``, embedding
                     tables over ``model``), jit-compiled with donation.
  - ``engine.py``  — the DASE template wrapping it (DataSource over rate/view
                     events, TwoTowerAlgorithm, top-k retrieval serving).
"""

from predictionio_tpu.models.twotower.engine import (
    DataSource,
    ItemScore,
    PredictedResult,
    Preparator,
    Query,
    Serving,
    TrainingData,
    TwoTowerAlgorithm,
    TwoTowerAlgorithmParams,
    TwoTowerModelState,
    engine_factory,
)

__all__ = [
    "DataSource",
    "ItemScore",
    "PredictedResult",
    "Preparator",
    "Query",
    "Serving",
    "TrainingData",
    "TwoTowerAlgorithm",
    "TwoTowerAlgorithmParams",
    "TwoTowerModelState",
    "engine_factory",
]
