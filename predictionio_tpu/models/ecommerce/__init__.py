"""E-commerce recommendation template.

Reference parity: ``examples/scala-parallel-ecommercerecommendation/
train-with-rate-event/`` — implicit ALS + popularity fallback + business
rules, with *live* event-store lookups on the serving hot path: seen-item
exclusion (``unseenOnly``), the ``unavailableItems`` constraint entity, and
recent-interaction-based scoring for users without factors.
"""

from predictionio_tpu.models.ecommerce.engine import (
    DataSource,
    ECommAlgorithm,
    ECommAlgorithmParams,
    ECommModel,
    ItemScore,
    PredictedResult,
    Preparator,
    Query,
    Serving,
    TrainingData,
    engine_factory,
)

__all__ = [
    "DataSource",
    "ECommAlgorithm",
    "ECommAlgorithmParams",
    "ECommModel",
    "ItemScore",
    "PredictedResult",
    "Preparator",
    "Query",
    "Serving",
    "TrainingData",
    "engine_factory",
]
