"""E-commerce recommendation engine (DASE components).

Reference parity (behavioral), all from
``train-with-rate-event/src/main/scala/``:
  - Query {user, num, categories?, whiteList?, blackList?} ->
    PredictedResult {itemScores} — ``Engine.scala:23-38``.
  - ECommAlgorithmParams {appName, unseenOnly, seenEvents, similarEvents,
    rank, numIterations, lambda, seed} — ``ECommAlgorithm.scala:38-47``.
  - Train: implicit ALS on rate events (weighted by rating), popularity
    counts from buy events for the cold fallback — ``ECommAlgorithm.scala:
    76-158, 211-240``.
  - Predict (``:243-330``): known user -> dot(userFactor, itemFactors);
    unknown/cold user -> summed similarity of items to the user's recent
    ``similarEvents`` (live LEventStore lookup, last 10), falling back to
    popularity counts when no recent items; ``unseenOnly`` excludes items
    from the user's live ``seenEvents``; the ``unavailableItems`` constraint
    entity ($set on entityType "constraint") is re-read per query.

TPU design: factor tables live on device; scoring, business-rule masking
and selection run as ONE fused jitted program (ops/topk) with only the
(k scores, k indices) pairs fetched; the live lookups stay host-side
(row-store reads) and a micro-batch of known-user queries is a single
batched device call.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    JaxAlgorithm,
    Params,
    SanityCheck,
)
from predictionio_tpu.ops import topk
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.workflow.context import WorkflowContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10
    categories: frozenset[str] | None = None
    white_list: frozenset[str] | None = None
    black_list: frozenset[str] | None = None

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "Query":
        def fset(key):
            v = d.get(key)
            return frozenset(v) if v is not None else None

        return Query(
            user=str(d["user"]),
            num=int(d.get("num", 10)),
            categories=fset("categories"),
            white_list=fset("whiteList"),
            black_list=fset("blackList"),
        )


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...]

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "itemScores": [{"item": s.item, "score": s.score} for s in self.item_scores]
        }


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""


@dataclasses.dataclass
class TrainingData(SanityCheck):
    user_vocab: list[str]
    item_vocab: list[str]
    item_categories: list[frozenset[str] | None]
    rate_user_idx: np.ndarray
    rate_item_idx: np.ndarray
    rate_values: np.ndarray
    buy_user_idx: np.ndarray
    buy_item_idx: np.ndarray

    def sanity_check(self) -> None:
        if len(self.rate_user_idx) == 0:
            raise ValueError("no rate events found; check app data")


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        store = ctx.p_event_store()
        app_name = self.params.app_name or ctx.app_name
        col = store.to_columnar_cached(
            app_name=app_name,
            channel_name=ctx.channel_name,
            event_names=["rate", "buy"],
            entity_type="user",
            target_entity_type="item",
            rating_key="rating",
        )
        item_vocab = list(col.target_vocab)
        item_index = {v: i for i, v in enumerate(item_vocab)}
        item_props = store.aggregate_properties(
            app_name=app_name, entity_type="item", channel_name=ctx.channel_name
        )
        categories: list[frozenset[str] | None] = [None] * len(item_vocab)
        for entity_id, pm in item_props.items():
            idx = item_index.get(entity_id)
            if idx is None:
                continue
            cats = pm.get_opt("categories")
            if cats is not None:
                categories[idx] = frozenset(cats)
        rates = np.asarray([n == "rate" for n in col.event_names], bool)
        buys = np.asarray([n == "buy" for n in col.event_names], bool)
        valid = (col.entity_ids >= 0) & (col.target_ids >= 0)
        rate_mask = rates & valid & np.isfinite(col.ratings)
        buy_mask = buys & valid
        return TrainingData(
            user_vocab=col.entity_vocab,
            item_vocab=item_vocab,
            item_categories=categories,
            rate_user_idx=col.entity_ids[rate_mask],
            rate_item_idx=col.target_ids[rate_mask],
            rate_values=col.ratings[rate_mask],
            buy_user_idx=col.entity_ids[buy_mask],
            buy_item_idx=col.target_ids[buy_mask],
        )


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    app_name: str = ""
    unseen_only: bool = False
    seen_events: tuple[str, ...] = ("buy", "view")
    similar_events: tuple[str, ...] = ("view",)
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = 3
    # "cg" | "cg_fused" | "cholesky" (see ops/als.ALSConfig.solver)
    solver: str = "cg"
    # adjust-score variant: enable the per-request weightedItems constraint
    # lookup (off by default — it costs one event-store query per predict)
    adjust_score: bool = False
    # TTL for serving-time storage lookups (seen/recent items per user,
    # unavailable-items + weightedItems constraints). The DEFAULT is 0 =
    # always-live per-query reads, matching the reference's semantics
    # (ECommAlgorithm.scala:252-300): a `$set` of unavailableItems or a new
    # seen/buy event affects the very next prediction. Operators opt into a
    # positive TTL (e.g. 5.0) to trade freshness (lag bounded by the TTL)
    # for a p50 with ZERO storage round trips once the cache is warm.
    cache_ttl_s: float = 0.0


@dataclasses.dataclass
class ECommModel(SanityCheck):
    user_factors: np.ndarray  # [n_users, f]
    item_factors: np.ndarray  # [n_items, f]
    popular_counts: np.ndarray  # [n_items] buy counts
    user_vocab: list[str]
    item_vocab: list[str]
    item_categories: list[frozenset[str] | None]

    def __post_init__(self):
        import uuid

        self._user_index: dict[str, int] | None = None
        self._item_index: dict[str, int] | None = None
        self._device_items = None
        # identity token for serving-side caches: values derived FROM this
        # model (index arrays, weight vectors) must never be served to a
        # different (e.g. hot-swapped) model
        self._cache_token = uuid.uuid4().hex

    def sanity_check(self) -> None:
        if not (
            np.all(np.isfinite(self.user_factors))
            and np.all(np.isfinite(self.item_factors))
        ):
            raise ValueError("non-finite ALS factors")

    def user_index(self, user: str) -> int | None:
        if self._user_index is None:
            self._user_index = {u: i for i, u in enumerate(self.user_vocab)}
        return self._user_index.get(user)

    def item_index(self, item: str) -> int | None:
        if self._item_index is None:
            self._item_index = {v: i for i, v in enumerate(self.item_vocab)}
        return self._item_index.get(item)

    def device_items(self):
        if self._device_items is None:
            import jax.numpy as jnp

            self._device_items = jnp.asarray(self.item_factors)
        return self._device_items

    def __getstate__(self):
        return {
            "user_factors": self.user_factors,
            "item_factors": self.item_factors,
            "popular_counts": self.popular_counts,
            "user_vocab": self.user_vocab,
            "item_vocab": self.item_vocab,
            "item_categories": self.item_categories,
        }

    def __setstate__(self, state):
        import uuid

        self.__dict__.update(state)
        self._user_index = None
        self._item_index = None
        self._device_items = None
        self._cache_token = uuid.uuid4().hex


class ECommAlgorithm(JaxAlgorithm):
    params_class = ECommAlgorithmParams
    params: ECommAlgorithmParams

    @property
    def _lookup_cache(self):
        """Lazy per-instance TTL cache for the serving-time storage reads
        (one shared cache: keys are namespaced tuples)."""
        cache = getattr(self, "_lookup_cache_obj", None)
        if cache is None:
            from predictionio_tpu.utils.ttl_cache import TTLCache

            cache = TTLCache(ttl_s=self.params.cache_ttl_s)
            self._lookup_cache_obj = cache
        return cache

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> ECommModel:
        cfg = ALSConfig(
            rank=self.params.rank,
            iterations=self.params.num_iterations,
            reg=self.params.lambda_,
            implicit=True,
            alpha=self.params.alpha,
            seed=self.params.seed if self.params.seed is not None else 0,
            solver=self.params.solver,
        )
        uf, vf = als_train(
            pd.rate_user_idx,
            pd.rate_item_idx,
            pd.rate_values,
            len(pd.user_vocab),
            len(pd.item_vocab),
            cfg,
        )
        popular = np.bincount(
            pd.buy_item_idx, minlength=len(pd.item_vocab)
        ).astype(np.float32)
        return ECommModel(
            np.asarray(uf),
            np.asarray(vf),
            popular,
            list(pd.user_vocab),
            list(pd.item_vocab),
            list(pd.item_categories),
        )

    # -- live lookups (ref ECommAlgorithm.scala:252-300), TTL-cached so the
    # steady-state predict path does zero storage round trips ---------------
    # NOTE failure handling: the _live loaders RAISE on storage errors and
    # the degraded fallback is applied OUTSIDE get_or_load — caching the
    # fallback would silently disable a business filter for a whole TTL
    # window after one storage blip; this way only successful reads cache
    # and the next query retries the store.
    def _seen_items(self, ctx: WorkflowContext, user: str) -> set[str]:
        try:
            return self._lookup_cache.get_or_load(
                ("seen", user), lambda: self._seen_items_live(ctx, user)
            )
        except Exception:
            logger.exception("seen-items lookup failed; serving without filter")
            return set()

    def _seen_items_live(self, ctx: WorkflowContext, user: str) -> set[str]:
        events = ctx.l_event_store().find_by_entity(
            app_name=self.params.app_name or ctx.app_name,
            entity_type="user",
            entity_id=user,
            event_names=list(self.params.seen_events),
            limit=None,
        )
        return {e.target_entity_id for e in events if e.target_entity_id is not None}

    def _unavailable_items(self, ctx: WorkflowContext) -> set[str]:
        try:
            return self._lookup_cache.get_or_load(
                ("unavailable",), lambda: self._unavailable_items_live(ctx)
            )
        except Exception:
            logger.exception("unavailable-items lookup failed; assuming none")
            return set()

    def _unavailable_items_live(self, ctx: WorkflowContext) -> set[str]:
        """$set events on (constraint, unavailableItems), latest wins
        (ref :268-284)."""
        events = list(
            ctx.l_event_store().find_by_entity(
                app_name=self.params.app_name or ctx.app_name,
                entity_type="constraint",
                entity_id="unavailableItems",
                event_names=["$set"],
                limit=1,
            )
        )
        if events:
            return set(events[0].properties.get_or_else("items", []))
        return set()

    def _item_weights(self, ctx: WorkflowContext, model: ECommModel) -> np.ndarray | None:
        try:
            # keyed by model identity: the weight vector is sized/indexed
            # against THIS model's item vocab
            return self._lookup_cache.get_or_load(
                ("weights", model._cache_token),
                lambda: self._item_weights_live(ctx, model),
            )
        except Exception:
            logger.exception("weightedItems lookup failed; weights ignored")
            return None

    def _item_weights_live(self, ctx: WorkflowContext, model: ECommModel) -> np.ndarray | None:
        """adjust-score variant (ref adjust-score/ECommAlgorithm.scala:56-58,
        256-263,400-430): latest $set on (constraint, weightedItems) carries
        ``weights``: [{"items": [...], "weight": w}]; scores of listed items
        are multiplied by w, everything else by 1.0. Returns None when no
        constraint is set so the multiply can be skipped entirely."""
        events = list(
            ctx.l_event_store().find_by_entity(
                app_name=self.params.app_name or ctx.app_name,
                entity_type="constraint",
                entity_id="weightedItems",
                event_names=["$set"],
                limit=1,
            )
        )
        if not events:
            return None
        groups = events[0].properties.get_or_else("weights", [])
        if not groups:
            return None
        weights = np.ones(len(model.item_vocab), np.float64)
        for group in groups:
            w = float(group.get("weight", 1.0))
            for it in group.get("items", []):
                idx = model.item_index(str(it))
                if idx is not None:
                    weights[idx] = w
        return weights

    def _recent_item_indices(self, ctx: WorkflowContext, model: ECommModel, user: str) -> list[int]:
        try:
            # keyed by model identity: returns indices INTO this model's
            # item table
            return self._lookup_cache.get_or_load(
                ("recent", model._cache_token, user),
                lambda: self._recent_item_indices_live(ctx, model, user),
            )
        except Exception:
            logger.exception("recent-items lookup failed")
            return []

    def _recent_item_indices_live(self, ctx: WorkflowContext, model: ECommModel, user: str) -> list[int]:
        """Last 10 similar-event items (ref :302-320)."""
        events = ctx.l_event_store().find_by_entity(
            app_name=self.params.app_name or ctx.app_name,
            entity_type="user",
            entity_id=user,
            event_names=list(self.params.similar_events),
            limit=10,
        )
        out = []
        for e in events:
            if e.target_entity_id is not None:
                idx = model.item_index(e.target_entity_id)
                if idx is not None:
                    out.append(idx)
        return out

    def _candidate_mask(
        self,
        ctx: WorkflowContext,
        model: ECommModel,
        query: Query,
        out: np.ndarray,
    ) -> None:
        """Business-rule + query filters written into a preallocated [n]
        mask row (seen items, unavailable constraint, white/black lists,
        category overlap — ref ECommAlgorithm.scala:243-330)."""
        n = len(model.item_vocab)
        out[...] = True
        if self.params.unseen_only:
            for it in self._seen_items(ctx, query.user):
                idx = model.item_index(it)
                if idx is not None:
                    out[idx] = False
        for it in self._unavailable_items(ctx):
            idx = model.item_index(it)
            if idx is not None:
                out[idx] = False
        if query.white_list is not None:
            wl = np.zeros(n, bool)
            for it in query.white_list:
                idx = model.item_index(it)
                if idx is not None:
                    wl[idx] = True
            out &= wl
        if query.black_list is not None:
            for it in query.black_list:
                idx = model.item_index(it)
                if idx is not None:
                    out[idx] = False
        if query.categories is not None:
            for i in range(n):
                cats = model.item_categories[i]
                if cats is None or not (cats & query.categories):
                    out[i] = False

    def _weights(self, ctx: WorkflowContext, model: ECommModel):
        if not self.params.adjust_score:
            return None
        return self._item_weights(ctx, model)

    @staticmethod
    def _result_rows(
        model: ECommModel, scores: np.ndarray, idx: np.ndarray, num: int
    ) -> PredictedResult:
        return PredictedResult(
            tuple(
                ItemScore(model.item_vocab[int(i)], float(s))
                for s, i in zip(scores[:num], idx[:num])
                if np.isfinite(s)
            )
        )

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        return self.predict_with_context(
            WorkflowContext(mode="serving"), model, query
        )

    def predict_with_context(
        self, ctx: WorkflowContext, model: ECommModel, query: Query
    ) -> PredictedResult:
        n = len(model.item_vocab)
        pool = topk.scratch()
        mask = pool.get("ecomm.mask1", (1, n), np.bool_)
        self._candidate_mask(ctx, model, query, mask[0])
        weights = self._weights(ctx, model)
        kk = min(topk.next_pow2(min(query.num, n)), n)
        uidx = model.user_index(query.user)
        if uidx is not None:
            handle = topk.dot_top_k_async(
                model.device_items(),
                model.user_factors[uidx][None],
                mask,
                kk,
                weights=weights,
            )
        else:
            recent = self._recent_item_indices(ctx, model, query.user)
            if recent:
                handle = topk.gather_sum_top_k_async(
                    model.device_items(),
                    np.asarray(recent, np.int32)[None],
                    np.ones((1, len(recent)), np.float32),
                    mask,
                    kk,
                    weights=weights,
                )
            else:
                # popularity fallback: the scores are host-born counts —
                # nothing device-resident to fuse with, so this is the
                # sanctioned host ending (ops/topk.host_top_k)
                scores = model.popular_counts.astype(np.float64)
                if weights is not None:
                    scores = scores * weights
                sk, si = topk.host_top_k(scores, mask[0], min(query.num, n))
                return self._result_rows(model, sk, si, len(si))
        scores, idx = topk.fetch_topk(handle)
        return self._result_rows(
            model, scores[0], idx[0], min(query.num, kk)
        )

    def predict_batch(
        self, model: ECommModel, queries: Sequence[Query]
    ) -> list[PredictedResult]:
        return self.predict_batch_dispatch(model, queries)()

    def predict_batch_dispatch(self, model: ECommModel, queries: Sequence[Query]):
        """Micro-batch path: every known-user query rides ONE fused
        batched matvec+mask+top-k (user vectors and mask rows assembled
        into reusable staging buffers); cold users (recent-similarity or
        popularity fallback) answer per query in the finalize."""
        ctx = WorkflowContext(mode="serving")
        n = len(model.item_vocab)
        results: list[PredictedResult | None] = [None] * len(queries)
        rows: list[int] = []
        row_uidx: list[int] = []
        cold: list[int] = []
        max_num = 1
        for i, q in enumerate(queries):
            if q.num <= 0:
                results[i] = PredictedResult(())
                continue
            uidx = model.user_index(q.user)
            if uidx is None:
                cold.append(i)
                continue
            rows.append(i)
            row_uidx.append(uidx)
            max_num = max(max_num, q.num)
        handle = None
        kk = 0
        if rows:
            weights = self._weights(ctx, model)
            f = model.user_factors.shape[1]
            b = topk.next_pow2(len(rows))
            pool = topk.scratch()
            vec_buf = pool.zeros("ecomm.vecs", (b, f), np.float32)
            np.take(
                model.user_factors, np.asarray(row_uidx, np.int64), axis=0,
                out=vec_buf[: len(rows)],
            )
            mask_buf = pool.get("ecomm.mask", (b, n), np.bool_)
            mask_buf[len(rows):] = True
            for row, i in enumerate(rows):
                self._candidate_mask(ctx, model, queries[i], mask_buf[row])
            kk = min(topk.next_pow2(max_num), n)
            handle = topk.dot_top_k_async(
                model.device_items(), vec_buf, mask_buf, kk, weights=weights
            )

        def finalize() -> list[PredictedResult]:
            for i in cold:
                results[i] = self.predict_with_context(ctx, model, queries[i])
            if handle is not None:
                scores, idx = topk.fetch_topk(handle)
                for row, i in enumerate(rows):
                    results[i] = self._result_rows(
                        model, scores[row], idx[row], min(queries[i].num, kk)
                    )
            return results  # type: ignore[return-value]

        return finalize

    def warmup_serving(self, model: ECommModel, max_batch: int) -> None:
        n = len(model.item_vocab)
        f = model.user_factors.shape[1]
        kk = min(topk.next_pow2(10), n)
        # with adjust_score the serving path routes to the WEIGHTED kernel
        # only while a weightedItems constraint is actually set (a live
        # event-store lookup — unknowable here), so warm BOTH variants:
        # whichever one serves, its programs are compiled
        variants: list[np.ndarray | None] = [None]
        if self.params.adjust_score:
            variants.append(np.ones(n, np.float32))
        for weights in variants:
            topk.warmup_pow2_buckets(
                max_batch,
                lambda b: topk.dot_top_k_async(
                    model.device_items(),
                    np.zeros((b, f), np.float32),
                    np.ones((b, n), bool),
                    kk,
                    weights=weights,
                ),
            )


class Serving(BaseServing):
    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        Preparator,
        {"ecomm": ECommAlgorithm},
        Serving,
        query_class=Query,
    )
