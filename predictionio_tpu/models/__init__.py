"""Bundled engine templates (ref ``examples/`` + the integration-test
recommendation engine).

Each template package exposes ``engine_factory()`` plus its Query /
PredictedResult types and ships a default ``engine.json`` in
``predictionio_tpu/models/<name>/engine.json``.
"""
