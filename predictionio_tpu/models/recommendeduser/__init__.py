"""Recommended-user template (similar users from follow events).

Reference parity: ``examples/scala-parallel-similarproduct/recommended-user/``
— follow events user->user, implicit ALS, query {users, num} returns
similarUserScores.
"""

from predictionio_tpu.models.recommendeduser.engine import (
    ALSAlgorithm,
    DataSource,
    DataSourceParams,
    PredictedResult,
    Preparator,
    Query,
    Serving,
    SimilarUserModel,
    SimilarUserScore,
    TrainingData,
    engine_factory,
)

__all__ = [
    "ALSAlgorithm",
    "DataSource",
    "DataSourceParams",
    "PredictedResult",
    "Preparator",
    "Query",
    "Serving",
    "SimilarUserModel",
    "SimilarUserScore",
    "TrainingData",
    "engine_factory",
]
