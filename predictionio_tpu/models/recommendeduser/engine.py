"""Recommended-user engine: similar users from follow events.

Reference parity (behavioral, re-designed for TPU):
``examples/scala-parallel-similarproduct/recommended-user/src/main/scala/``
  - Query {"users", "num", "whiteList"?, "blackList"?} ->
    PredictedResult {"similarUserScores": [{user, score}]} (Engine.scala:23-33).
  - DataSource reads follow events (user -> user)
    (DataSource.scala:56-84).
  - ALSAlgorithm: implicit ALS on (follower, followed) counts; similar-user
    scoring = summed cosine of followed-user factors against the query
    users' factors, excluding the query users themselves.

TPU design: identical serving shape to the similar-product engine — the
followed-user factor table is L2-normalised, landed on device once, and a
micro-batch of queries is ONE fused gather->sum-cosine->mask->top-k
program (ops/topk); only (k scores, k indices) per query cross the wire.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    JaxAlgorithm,
    Params,
    SanityCheck,
)
from predictionio_tpu.ops import topk
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.workflow.context import WorkflowContext


@dataclasses.dataclass(frozen=True)
class Query:
    users: tuple[str, ...]
    num: int = 10
    white_list: frozenset[str] | None = None
    black_list: frozenset[str] | None = None

    @staticmethod
    def from_json_dict(d: dict[str, Any]) -> "Query":
        def fset(key):
            v = d.get(key)
            return frozenset(str(x) for x in v) if v is not None else None

        return Query(
            users=tuple(str(u) for u in d["users"]),
            num=int(d.get("num", 10)),
            white_list=fset("whiteList"),
            black_list=fset("blackList"),
        )


@dataclasses.dataclass(frozen=True)
class SimilarUserScore:
    user: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    similar_user_scores: tuple[SimilarUserScore, ...]

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "similarUserScores": [
                {"user": s.user, "score": s.score}
                for s in self.similar_user_scores
            ]
        }


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    follow_event: str = "follow"


@dataclasses.dataclass
class TrainingData(SanityCheck):
    user_vocab: list[str]  # followers
    followed_vocab: list[str]  # followed users (scoring table)
    follower_idx: np.ndarray
    followed_idx: np.ndarray

    def sanity_check(self) -> None:
        if len(self.follower_idx) == 0:
            raise ValueError("no follow events found; check app data")


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        col = ctx.p_event_store().to_columnar_cached(
            app_name=self.params.app_name or ctx.app_name,
            channel_name=ctx.channel_name,
            event_names=[self.params.follow_event],
            entity_type="user",
            target_entity_type="user",
        )
        valid = (col.entity_ids >= 0) & (col.target_ids >= 0)
        return TrainingData(
            user_vocab=col.entity_vocab,
            followed_vocab=col.target_vocab,
            follower_idx=col.entity_ids[valid],
            followed_idx=col.target_ids[valid],
        )


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = 3
    # "cg" | "cg_fused" | "cholesky" (see ops/als.ALSConfig.solver)
    solver: str = "cg"


@dataclasses.dataclass
class SimilarUserModel(SanityCheck):
    followed_factors: np.ndarray  # [n_followed, f], L2-normalized
    followed_vocab: list[str]

    def __post_init__(self):
        self._index: dict[str, int] | None = None
        self._device_factors = None

    def sanity_check(self) -> None:
        if not np.all(np.isfinite(self.followed_factors)):
            raise ValueError("non-finite followed-user factors")

    def user_index(self, user: str) -> int | None:
        if self._index is None:
            self._index = {u: i for i, u in enumerate(self.followed_vocab)}
        return self._index.get(user)

    def device_factors(self):
        if self._device_factors is None:
            import jax.numpy as jnp

            self._device_factors = jnp.asarray(self.followed_factors)
        return self._device_factors

    def __getstate__(self):
        return {
            "followed_factors": self.followed_factors,
            "followed_vocab": self.followed_vocab,
        }

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._index = None
        self._device_factors = None


class ALSAlgorithm(JaxAlgorithm):
    params_class = ALSAlgorithmParams
    params: ALSAlgorithmParams

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> SimilarUserModel:
        pair, counts = np.unique(
            np.stack([pd.follower_idx, pd.followed_idx], 1),
            axis=0,
            return_counts=True,
        )
        cfg = ALSConfig(
            rank=self.params.rank,
            iterations=self.params.num_iterations,
            reg=self.params.lambda_,
            implicit=True,
            alpha=self.params.alpha,
            seed=self.params.seed if self.params.seed is not None else 0,
            solver=self.params.solver,
        )
        _, followed_factors = als_train(
            pair[:, 0],
            pair[:, 1],
            counts.astype(np.float32),
            len(pd.user_vocab),
            len(pd.followed_vocab),
            cfg,
        )
        vf = np.asarray(followed_factors)
        norms = np.linalg.norm(vf, axis=1, keepdims=True)
        vf = vf / np.where(norms == 0, 1.0, norms)
        return SimilarUserModel(vf, list(pd.followed_vocab))

    @staticmethod
    def _candidate_mask(
        model: SimilarUserModel, query: Query, query_idx: list[int], out: np.ndarray
    ) -> None:
        """Whitelist/blacklist/self-exclusion mask written into a
        preallocated [n] row of the batch staging buffer."""
        out[...] = True
        out[query_idx] = False  # never recommend the query users back
        if query.white_list is not None:
            wl = np.zeros(out.shape[0], bool)
            for u in query.white_list:
                idx = model.user_index(u)
                if idx is not None:
                    wl[idx] = True
            out &= wl
        if query.black_list is not None:
            for u in query.black_list:
                idx = model.user_index(u)
                if idx is not None:
                    out[idx] = False

    def predict(self, model: SimilarUserModel, query: Query) -> PredictedResult:
        return self.predict_batch(model, [query])[0]

    def predict_batch(
        self, model: SimilarUserModel, queries: Sequence[Query]
    ) -> list[PredictedResult]:
        return self.predict_batch_dispatch(model, queries)()

    def predict_batch_dispatch(
        self, model: SimilarUserModel, queries: Sequence[Query]
    ):
        """One fused device call per micro-batch (see ops/topk): queries
        are assembled into reusable staging buffers, scoring + masking +
        selection run on device, and the finalize fetches only [B, k]."""
        n = len(model.followed_vocab)
        results: list[PredictedResult | None] = [None] * len(queries)
        rows: list[int] = []
        row_qidx: list[list[int]] = []
        max_q = 1
        max_num = 1
        for i, q in enumerate(queries):
            qidx = [
                j for u in q.users if (j := model.user_index(u)) is not None
            ]
            if not qidx or q.num <= 0:
                results[i] = PredictedResult(())
                continue
            rows.append(i)
            row_qidx.append(qidx)
            max_q = max(max_q, len(qidx))
            max_num = max(max_num, q.num)
        handle = None
        kk = 0
        if rows:
            b = topk.next_pow2(len(rows))
            qcap = topk.next_pow2(max_q)
            pool = topk.scratch()
            qidx_buf = pool.zeros("recuser.qidx", (b, qcap), np.int32)
            qw_buf = pool.zeros("recuser.qw", (b, qcap), np.float32)
            mask_buf = pool.get("recuser.mask", (b, n), np.bool_)
            mask_buf[len(rows):] = True
            for row, (i, qidx) in enumerate(zip(rows, row_qidx)):
                qidx_buf[row, : len(qidx)] = qidx
                qw_buf[row, : len(qidx)] = 1.0
                self._candidate_mask(model, queries[i], qidx, mask_buf[row])
            kk = min(topk.next_pow2(max_num), n)
            handle = topk.gather_sum_top_k_async(
                model.device_factors(), qidx_buf, qw_buf, mask_buf, kk
            )

        def finalize() -> list[PredictedResult]:
            if handle is not None:
                scores, idx = topk.fetch_topk(handle)
                for row, i in enumerate(rows):
                    num = min(queries[i].num, kk)
                    results[i] = PredictedResult(
                        tuple(
                            SimilarUserScore(
                                model.followed_vocab[int(u)], float(s)
                            )
                            for s, u in zip(scores[row, :num], idx[row, :num])
                            if np.isfinite(s)
                        )
                    )
            return results  # type: ignore[return-value]

        return finalize

    def warmup_serving(self, model: SimilarUserModel, max_batch: int) -> None:
        n = len(model.followed_vocab)
        kk = min(topk.next_pow2(10), n)
        topk.warmup_pow2_buckets(
            max_batch,
            lambda b: topk.gather_sum_top_k_async(
                model.device_factors(),
                np.zeros((b, 1), np.int32),
                np.zeros((b, 1), np.float32),
                np.ones((b, n), bool),
                kk,
            ),
        )


class Serving(BaseServing):
    def serve(
        self, query: Query, predictions: Sequence[PredictedResult]
    ) -> PredictedResult:
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        Preparator,
        {"als": ALSAlgorithm},
        Serving,
        query_class=Query,
    )
