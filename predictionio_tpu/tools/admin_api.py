"""REST admin server (port 7071).

Reference parity: ``tools/.../admin/AdminAPI.scala:39-160`` +
``CommandClient.scala`` — GET /, GET /cmd/app, POST /cmd/app (new),
DELETE /cmd/app/{name} and /cmd/app/{name}/data.

Beyond the reference: GET /cmd/models and /cmd/models/{engine_key} expose
the model registry's inventory (versions, rollout state, history) so
fleet tooling can see what every engine serves without touching disk.
"""

from __future__ import annotations

from aiohttp import web

from predictionio_tpu.data.storage.base import AccessKey, App
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.registry.store import ArtifactStore


class AdminServer:
    def __init__(
        self,
        storage: Storage | None = None,
        registry_dir: str | None = None,
    ):
        self.storage = storage or Storage.instance()
        self.registry = ArtifactStore(registry_dir)

    async def handle_root(self, request: web.Request) -> web.Response:
        import predictionio_tpu

        return web.json_response(
            {"status": "alive", "version": predictionio_tpu.__version__}
        )

    async def handle_list_apps(self, request: web.Request) -> web.Response:
        apps = self.storage.get_meta_data_apps().get_all()
        keys = self.storage.get_meta_data_access_keys()
        return web.json_response(
            [
                {
                    "name": a.name,
                    "id": a.id,
                    "description": a.description,
                    "accessKeys": [k.key for k in keys.get_by_app_id(a.id)],
                }
                for a in apps
            ]
        )

    async def handle_new_app(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            name = body["name"]
            requested_id = int(body.get("id") or 0)
        except Exception:
            return web.json_response(
                {"message": "name required (id, if given, must be an integer)"},
                status=400,
            )
        apps = self.storage.get_meta_data_apps()
        if apps.get_by_name(name):
            return web.json_response(
                {"message": f"App {name} already exists."}, status=409
            )
        app_id = apps.insert(App(requested_id, name, body.get("description")))
        if app_id is None:
            return web.json_response({"message": "unable to create app"}, status=500)
        self.storage.get_l_events().init(app_id)
        key = self.storage.get_meta_data_access_keys().insert(
            AccessKey("", app_id, ())
        )
        return web.json_response({"name": name, "id": app_id, "accessKey": key}, status=201)

    async def handle_delete_app(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        apps = self.storage.get_meta_data_apps()
        app = apps.get_by_name(name)
        if app is None:
            return web.json_response({"message": "Not Found"}, status=404)
        channels = self.storage.get_meta_data_channels()
        for c in channels.get_by_app_id(app.id):
            self.storage.get_l_events().remove(app.id, c.id)
            channels.delete(c.id)
        self.storage.get_l_events().remove(app.id)
        for k in self.storage.get_meta_data_access_keys().get_by_app_id(app.id):
            self.storage.get_meta_data_access_keys().delete(k.key)
        apps.delete(app.id)
        return web.json_response({"message": f"App {name} deleted."})

    async def handle_delete_app_data(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        app = self.storage.get_meta_data_apps().get_by_name(name)
        if app is None:
            return web.json_response({"message": "Not Found"}, status=404)
        self.storage.get_l_events().remove(app.id)
        self.storage.get_l_events().init(app.id)
        return web.json_response({"message": f"Data of app {name} deleted."})

    async def handle_list_models(self, request: web.Request) -> web.Response:
        """Registry inventory: one row per engine with its rollout state."""
        out = []
        for key in self.registry.engines():
            versions = self.registry.versions_by_key(key)
            state = self.registry.state_by_key(key)
            out.append(
                {
                    "engineKey": key,
                    "engineId": versions[-1].engine_id if versions else "",
                    "versions": len(versions),
                    "stable": state.stable,
                    "candidate": state.candidate,
                    "mode": state.mode,
                    "fraction": state.fraction,
                }
            )
        return web.json_response(
            {"registryDir": self.registry.base_dir, "engines": out}
        )

    async def handle_show_models(self, request: web.Request) -> web.Response:
        key = request.match_info["engine_key"]
        versions = self.registry.versions_by_key(key)
        if not versions:
            return web.json_response({"message": "Not Found"}, status=404)
        return web.json_response(
            {
                "engineKey": key,
                "state": self.registry.state_by_key(key).to_json_dict(),
                "versions": [m.to_json_dict() for m in versions],
            }
        )

    def make_app(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self.handle_root),
                web.get("/cmd/app", self.handle_list_apps),
                web.post("/cmd/app", self.handle_new_app),
                web.delete("/cmd/app/{name}", self.handle_delete_app),
                web.delete("/cmd/app/{name}/data", self.handle_delete_app_data),
                web.get("/cmd/models", self.handle_list_models),
                web.get("/cmd/models/{engine_key}", self.handle_show_models),
            ]
        )
        return app


def run_admin_server(
    ip: str = "127.0.0.1", port: int = 7071, registry_dir: str | None = None
) -> None:
    server = AdminServer(registry_dir=registry_dir)
    web.run_app(server.make_app(), host=ip, port=port, print=None)
