"""CLI console, import/export, admin API, dashboard (ref ``tools/``)."""
