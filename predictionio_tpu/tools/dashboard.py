"""Evaluation + observability dashboard (port 9000).

Reference parity: ``tools/.../dashboard/Dashboard.scala:44-107`` — an HTML
page listing completed EvaluationInstances newest-first with links to their
HTML metric reports, plus the JSON results.

Beyond the reference: the dashboard can be pointed at running servers'
``/metrics`` endpoints (``pio dashboard --metrics-url http://host:8000``,
repeatable) and renders live breaker/queue/latency panels — qps totals,
p50/p95/p99, shed/deadline counts, breaker states, and jit recompile
counts — instead of only the legacy hourly stats. Panels are fetched
server-side at page load with a short timeout; an unreachable server
renders as such rather than failing the page.
"""

from __future__ import annotations

import html
from typing import Any, Sequence

from aiohttp import web

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.tools.top import (
    format_number as _fmt,
    parse_prometheus,
    summarize,
)

_PAGE = """<!DOCTYPE html>
<html><head><title>predictionio_tpu dashboard</title>
<style>
body {{ font-family: sans-serif; margin: 2rem; }}
table {{ border-collapse: collapse; width: 100%; margin-bottom: 2rem; }}
th, td {{ border: 1px solid #ccc; padding: 0.4rem 0.8rem; text-align: left; }}
th {{ background: #f0f0f0; }}
.panel {{ display: inline-block; vertical-align: top; border: 1px solid #ccc;
  border-radius: 6px; padding: 0.8rem 1.2rem; margin: 0 1rem 1rem 0; }}
.panel h3 {{ margin: 0 0 0.5rem 0; font-size: 0.95rem; }}
.panel td {{ border: none; padding: 0.1rem 0.8rem 0.1rem 0; }}
.state-open {{ color: #b00; font-weight: bold; }}
.state-half-open {{ color: #b60; font-weight: bold; }}
.state-closed {{ color: #080; }}
.unreachable {{ color: #b00; }}
</style></head>
<body>
<h1>Dashboard</h1>
{observability}
<h2>Evaluations</h2>
<table>
<tr><th>ID</th><th>Start</th><th>End</th><th>Evaluation</th><th>Batch</th>
<th>Result</th><th></th></tr>
{rows}
</table>
</body></html>"""


def render_metrics_panel(url: str, metrics_text: str | None) -> str:
    """One server's panel: breaker / queue / latency, from a raw /metrics
    scrape (None = the fetch failed)."""
    title = html.escape(url)
    if metrics_text is None:
        return (
            f'<div class="panel"><h3>{title}</h3>'
            '<p class="unreachable">unreachable</p></div>'
        )
    s = summarize(parse_prometheus(metrics_text))
    breaker_cells = (
        " ".join(
            f'<span class="state-{html.escape(str(state))}">'
            f"{html.escape(name)}={html.escape(str(state))}</span>"
            for name, state in sorted((s.get("breakers") or {}).items())
        )
        or "-"
    )
    rows = [
        ("requests", _fmt(s["requests_total"])),
        ("errors (5xx)", _fmt(s["errors_total"])),
        ("p50 / p95 / p99", f"{_fmt(s['p50_ms'])} / {_fmt(s['p95_ms'])} / "
                            f"{_fmt(s['p99_ms'])} ms"),
        ("queue depth", f"{_fmt(s['queue_depth'])} / "
                        f"{_fmt(s['queue_high_water'])} high water"),
        ("shed / deadline", f"{_fmt(s['shed_total'])} / "
                            f"{_fmt(s['deadline_total'])}"),
        ("watchdog trips", _fmt(s["watchdog_total"])),
        ("jit recompiles", _fmt(s["recompiles"])),
        ("storage retries", _fmt(s["retries_total"])),
        ("breakers", breaker_cells),
    ]
    body = "\n".join(
        f"<tr><td>{html.escape(k)}</td><td>{v}</td></tr>" for k, v in rows
    )
    return (
        f'<div class="panel"><h3>{title}</h3><table>{body}</table></div>'
    )


class Dashboard:
    def __init__(
        self,
        storage: Storage | None = None,
        metrics_urls: Sequence[str] = (),
    ):
        self.storage = storage or Storage.instance()
        self.metrics_urls = list(metrics_urls)

    async def _fetch_metrics(self, url: str) -> str | None:
        """Scrape one server's /metrics; None on any failure. Split out so
        tests can stub the network."""
        import aiohttp

        try:
            timeout = aiohttp.ClientTimeout(total=2.0)
            async with aiohttp.ClientSession(timeout=timeout) as session:
                async with session.get(f"{url}/metrics") as resp:
                    if resp.status != 200:
                        return None
                    return await resp.text()
        except Exception:
            return None

    async def _observability_html(self) -> str:
        if not self.metrics_urls:
            return (
                "<p><i>No metrics sources configured — start with "
                "<code>pio dashboard --metrics-url http://host:port</code> "
                "to see live serving panels.</i></p>"
            )
        import asyncio

        # all sources scraped concurrently: page latency is bounded by the
        # slowest single fetch (~2s timeout), not the sum over down servers
        texts = await asyncio.gather(
            *(self._fetch_metrics(u) for u in self.metrics_urls)
        )
        panels = [
            render_metrics_panel(url, text)
            for url, text in zip(self.metrics_urls, texts)
        ]
        return "<h2>Serving</h2>\n" + "\n".join(panels)

    async def handle_index(self, request: web.Request) -> web.Response:
        instances = self.storage.get_meta_data_evaluation_instances().get_completed()
        rows = []
        for i in instances:
            rows.append(
                "<tr>"
                f"<td>{html.escape(i.id)}</td>"
                f"<td>{i.start_time.isoformat()}</td>"
                f"<td>{i.end_time.isoformat()}</td>"
                f"<td>{html.escape(i.evaluation_class)}</td>"
                f"<td>{html.escape(i.batch)}</td>"
                f"<td>{html.escape(i.evaluator_results)}</td>"
                f'<td><a href="/engine_instances/{html.escape(i.id)}/'
                'evaluator_results.html">HTML</a> '
                f'<a href="/engine_instances/{html.escape(i.id)}/'
                'evaluator_results.json">JSON</a></td>'
                "</tr>"
            )
        return web.Response(
            text=_PAGE.format(
                rows="\n".join(rows),
                observability=await self._observability_html(),
            ),
            content_type="text/html",
        )

    async def handle_results_html(self, request: web.Request) -> web.Response:
        instance = self.storage.get_meta_data_evaluation_instances().get(
            request.match_info["iid"]
        )
        if instance is None:
            return web.Response(status=404, text="Not Found")
        return web.Response(
            text=instance.evaluator_results_html or "<p>(no HTML results)</p>",
            content_type="text/html",
        )

    async def handle_results_json(self, request: web.Request) -> web.Response:
        instance = self.storage.get_meta_data_evaluation_instances().get(
            request.match_info["iid"]
        )
        if instance is None:
            return web.json_response({"message": "Not Found"}, status=404)
        return web.Response(
            text=instance.evaluator_results_json or "{}",
            content_type="application/json",
        )

    def make_app(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self.handle_index),
                web.get(
                    "/engine_instances/{iid}/evaluator_results.html",
                    self.handle_results_html,
                ),
                web.get(
                    "/engine_instances/{iid}/evaluator_results.json",
                    self.handle_results_json,
                ),
            ]
        )
        return app


def run_dashboard(
    ip: str = "127.0.0.1", port: int = 9000, metrics_urls: Sequence[str] = ()
) -> None:
    web.run_app(
        Dashboard(metrics_urls=metrics_urls).make_app(),
        host=ip,
        port=port,
        print=None,
    )
