"""Evaluation dashboard (port 9000).

Reference parity: ``tools/.../dashboard/Dashboard.scala:44-107`` — an HTML
page listing completed EvaluationInstances newest-first with links to their
HTML metric reports, plus the JSON results.
"""

from __future__ import annotations

import html

from aiohttp import web

from predictionio_tpu.data.storage.registry import Storage

_PAGE = """<!DOCTYPE html>
<html><head><title>predictionio_tpu dashboard</title>
<style>
body {{ font-family: sans-serif; margin: 2rem; }}
table {{ border-collapse: collapse; width: 100%; }}
th, td {{ border: 1px solid #ccc; padding: 0.4rem 0.8rem; text-align: left; }}
th {{ background: #f0f0f0; }}
</style></head>
<body>
<h1>Evaluation Dashboard</h1>
<table>
<tr><th>ID</th><th>Start</th><th>End</th><th>Evaluation</th><th>Batch</th>
<th>Result</th><th></th></tr>
{rows}
</table>
</body></html>"""


class Dashboard:
    def __init__(self, storage: Storage | None = None):
        self.storage = storage or Storage.instance()

    async def handle_index(self, request: web.Request) -> web.Response:
        instances = self.storage.get_meta_data_evaluation_instances().get_completed()
        rows = []
        for i in instances:
            rows.append(
                "<tr>"
                f"<td>{html.escape(i.id)}</td>"
                f"<td>{i.start_time.isoformat()}</td>"
                f"<td>{i.end_time.isoformat()}</td>"
                f"<td>{html.escape(i.evaluation_class)}</td>"
                f"<td>{html.escape(i.batch)}</td>"
                f"<td>{html.escape(i.evaluator_results)}</td>"
                f'<td><a href="/engine_instances/{html.escape(i.id)}/'
                'evaluator_results.html">HTML</a> '
                f'<a href="/engine_instances/{html.escape(i.id)}/'
                'evaluator_results.json">JSON</a></td>'
                "</tr>"
            )
        return web.Response(
            text=_PAGE.format(rows="\n".join(rows)), content_type="text/html"
        )

    async def handle_results_html(self, request: web.Request) -> web.Response:
        instance = self.storage.get_meta_data_evaluation_instances().get(
            request.match_info["iid"]
        )
        if instance is None:
            return web.Response(status=404, text="Not Found")
        return web.Response(
            text=instance.evaluator_results_html or "<p>(no HTML results)</p>",
            content_type="text/html",
        )

    async def handle_results_json(self, request: web.Request) -> web.Response:
        instance = self.storage.get_meta_data_evaluation_instances().get(
            request.match_info["iid"]
        )
        if instance is None:
            return web.json_response({"message": "Not Found"}, status=404)
        return web.Response(
            text=instance.evaluator_results_json or "{}",
            content_type="application/json",
        )

    def make_app(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self.handle_index),
                web.get(
                    "/engine_instances/{iid}/evaluator_results.html",
                    self.handle_results_html,
                ),
                web.get(
                    "/engine_instances/{iid}/evaluator_results.json",
                    self.handle_results_json,
                ),
            ]
        )
        return app


def run_dashboard(ip: str = "127.0.0.1", port: int = 9000) -> None:
    web.run_app(Dashboard().make_app(), host=ip, port=port, print=None)
