"""Bulk import/export between files and the event store.

Reference parity: ``tools/.../imprt/FileToEvents.scala:45-120`` (JSON lines
-> PEvents.write) and ``tools/.../export/EventsToFile.scala:85-95`` (the
json-or-parquet switch: ``--format parquet`` wrote the events DataFrame via
Spark SQL). Formats here:

- ``json`` — wire-format JSON lines, byte-compatible with the event API;
- ``parquet`` — one row per event with wire-named columns (``eventId``,
  ``event``, ``entityType``, ..., ``eventTime`` as a tz-aware timestamp);
  ``properties`` is a JSON-encoded string column rather than the
  reference's Spark struct (schema-free properties don't fit a fixed
  arrow struct; every consumer that reads the reference's output can
  json-decode the column). Import accepts both layouts' common columns.
- ``npz`` — dense columnar arrays (this framework's training feed).
"""

from __future__ import annotations

import json
import logging

import numpy as np

from predictionio_tpu.data.event import Event, parse_event_time
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.data.store.event_store import resolve_app

logger = logging.getLogger(__name__)


def import_events(
    input_path: str,
    app_name: str,
    channel_name: str | None = None,
    storage: Storage | None = None,
    batch_size: int = 10000,
) -> int:
    """JSON-lines or parquet file -> event store. Returns number imported.
    Parquet is selected by a ``.parquet`` extension."""
    storage = storage or Storage.instance()
    app_id, channel_id = resolve_app(storage, app_name, channel_name)
    levents = storage.get_l_events()
    levents.init(app_id, channel_id)
    count = 0
    batch: list[Event] = []

    def flush():
        nonlocal count, batch
        if batch:
            levents.insert_batch(batch, app_id, channel_id)
            count += len(batch)
            batch = []

    if input_path.endswith(".parquet"):
        for ev in _iter_parquet_events(input_path):
            batch.append(ev)
            if len(batch) >= batch_size:
                flush()
    else:
        with open(input_path) as f:
            for line_no, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    batch.append(Event.from_json_dict(json.loads(line)))
                except Exception as exc:
                    raise ValueError(f"{input_path}:{line_no}: {exc}") from exc
                if len(batch) >= batch_size:
                    flush()
    flush()
    logger.info("imported %d events into app %s", count, app_name)
    return count


def _iter_parquet_events(path: str):
    """Yield Events from a parquet file with wire-named columns (the layout
    ``export_events(format="parquet")`` writes; extra columns ignored)."""
    import pyarrow.parquet as pq

    pf = pq.ParquetFile(path)
    row_no = 0
    for rb in pf.iter_batches():
        for row in rb.to_pylist():
            row_no += 1
            try:
                d = {k: v for k, v in row.items() if v is not None}
                props = d.get("properties")
                if isinstance(props, str):
                    d["properties"] = json.loads(props)
                for key in ("eventTime", "creationTime"):
                    ts = d.get(key)
                    if ts is not None and not isinstance(ts, str):
                        d[key] = ts.isoformat()
                yield Event.from_json_dict(d)
            except Exception as exc:
                # same operator-facing contract as the JSON-lines path:
                # file:row: cause (and a ValueError cmd_import will catch)
                raise ValueError(f"{path}:{row_no}: {exc}") from exc


def export_events(
    output_path: str,
    app_name: str,
    channel_name: str | None = None,
    storage: Storage | None = None,
    format: str = "json",
) -> int:
    """Event store -> file. format=json (wire rows), parquet (wire-named
    columns, ref EventsToFile.scala:85-95), or npz (columnar)."""
    storage = storage or Storage.instance()
    app_id, channel_id = resolve_app(storage, app_name, channel_name)
    pevents = storage.get_p_events()
    if format == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        # real timestamp columns (the reference's Spark schema types
        # eventTime/creationTime as TimestampType, EventsToFile.scala) and
        # a streaming writer: the whole store must never be materialized
        # as one python list at ML-20M scale
        schema = pa.schema(
            [
                ("eventId", pa.string()),
                ("event", pa.string()),
                ("entityType", pa.string()),
                ("entityId", pa.string()),
                ("targetEntityType", pa.string()),
                ("targetEntityId", pa.string()),
                ("properties", pa.string()),
                ("prId", pa.string()),
                ("eventTime", pa.timestamp("us", tz="UTC")),
                ("creationTime", pa.timestamp("us", tz="UTC")),
            ]
        )

        def row(e: Event) -> dict:
            d = e.to_json_dict(with_creation_time=True)
            props = d.get("properties")
            return {
                "eventId": d.get("eventId"),
                "event": d["event"],
                "entityType": d["entityType"],
                "entityId": d["entityId"],
                "targetEntityType": d.get("targetEntityType"),
                "targetEntityId": d.get("targetEntityId"),
                "properties": json.dumps(props, sort_keys=True)
                if props
                else None,
                "prId": d.get("prId"),
                "eventTime": parse_event_time(d["eventTime"]),
                "creationTime": parse_event_time(d["creationTime"])
                if d.get("creationTime")
                else None,
            }

        count = 0
        batch: list[dict] = []
        with pq.ParquetWriter(output_path, schema) as writer:
            for e in pevents.find(app_id, channel_id):
                batch.append(row(e))
                if len(batch) >= 10000:
                    writer.write_batch(
                        pa.RecordBatch.from_pylist(batch, schema=schema)
                    )
                    count += len(batch)
                    batch = []
            if batch:
                writer.write_batch(
                    pa.RecordBatch.from_pylist(batch, schema=schema)
                )
                count += len(batch)
        return count
    if format == "json":
        count = 0
        with open(output_path, "w") as f:
            for e in pevents.find(app_id, channel_id):
                f.write(
                    json.dumps(e.to_json_dict(with_creation_time=True), sort_keys=True)
                    + "\n"
                )
                count += 1
        return count
    if format == "npz":
        col = pevents.to_columnar(app_id, channel_id)
        np.savez_compressed(
            output_path,
            entity_ids=col.entity_ids,
            target_ids=col.target_ids,
            event_codes=col.event_codes,
            timestamps=col.timestamps,
            ratings=col.ratings,
            entity_vocab=np.array(col.entity_vocab, dtype=object),
            target_vocab=np.array(col.target_vocab, dtype=object),
            event_vocab=np.array(col.event_vocab, dtype=object),
        )
        return len(col)
    raise ValueError(f"unknown export format {format!r} (json|parquet|npz)")
