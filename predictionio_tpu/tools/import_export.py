"""Bulk import/export between JSON-lines files and the event store.

Reference parity: ``tools/.../imprt/FileToEvents.scala:45-120`` (JSON lines
-> PEvents.write) and ``tools/.../export/EventsToFile.scala`` (PEvents.find
-> JSON lines; the reference also offered parquet via Spark SQL — here the
columnar export (.npz) plays that role for training feeds).
"""

from __future__ import annotations

import json
import logging

import numpy as np

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.data.store.event_store import resolve_app

logger = logging.getLogger(__name__)


def import_events(
    input_path: str,
    app_name: str,
    channel_name: str | None = None,
    storage: Storage | None = None,
    batch_size: int = 10000,
) -> int:
    """JSON-lines file -> event store. Returns number imported."""
    storage = storage or Storage.instance()
    app_id, channel_id = resolve_app(storage, app_name, channel_name)
    levents = storage.get_l_events()
    levents.init(app_id, channel_id)
    count = 0
    batch: list[Event] = []
    with open(input_path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                batch.append(Event.from_json_dict(json.loads(line)))
            except Exception as exc:
                raise ValueError(f"{input_path}:{line_no}: {exc}") from exc
            if len(batch) >= batch_size:
                levents.insert_batch(batch, app_id, channel_id)
                count += len(batch)
                batch = []
    if batch:
        levents.insert_batch(batch, app_id, channel_id)
        count += len(batch)
    logger.info("imported %d events into app %s", count, app_name)
    return count


def export_events(
    output_path: str,
    app_name: str,
    channel_name: str | None = None,
    storage: Storage | None = None,
    format: str = "json",
) -> int:
    """Event store -> file. format=json (wire rows) or npz (columnar)."""
    storage = storage or Storage.instance()
    app_id, channel_id = resolve_app(storage, app_name, channel_name)
    pevents = storage.get_p_events()
    if format == "json":
        count = 0
        with open(output_path, "w") as f:
            for e in pevents.find(app_id, channel_id):
                f.write(
                    json.dumps(e.to_json_dict(with_creation_time=True), sort_keys=True)
                    + "\n"
                )
                count += 1
        return count
    if format == "npz":
        col = pevents.to_columnar(app_id, channel_id)
        np.savez_compressed(
            output_path,
            entity_ids=col.entity_ids,
            target_ids=col.target_ids,
            event_codes=col.event_codes,
            timestamps=col.timestamps,
            ratings=col.ratings,
            entity_vocab=np.array(col.entity_vocab, dtype=object),
            target_vocab=np.array(col.target_vocab, dtype=object),
            event_vocab=np.array(col.event_vocab, dtype=object),
        )
        return len(col)
    raise ValueError(f"unknown export format {format!r} (json|npz)")
