"""``pio top`` — one-screen terminal summary of a running server's /metrics.

Polls ``<url>/metrics`` (QueryServer or EventServer — both export the same
registry format) and renders the numbers an operator staring at a hot
replica actually wants: qps and error rate (derived from counter deltas
between polls), latency percentiles (recomputed from the histogram's
cumulative buckets — the scrape carries the full distribution, not
pre-baked quantiles), shed/deadline/watchdog pressure, breaker states, and
the jit recompile count that distinguishes "TPU is slow" from "TPU is
compiling".

Stdlib-only (urllib + the text parser below): `pio top` must run on an
operator laptop with nothing but the package installed, against any
Prometheus-format endpoint.
"""

from __future__ import annotations

import os
import re
import sys
import time
import urllib.request
from typing import Any, Callable

# the request-ordered phase vocabulary — single source of truth in
# obs.waterfall (stdlib-only, so it costs `pio top` nothing)
from predictionio_tpu.obs.waterfall import PHASES as _PHASE_ORDER
from predictionio_tpu.resilience import CLOSED, HALF_OPEN, OPEN

# value of the pio_breaker_state gauge -> human name
BREAKER_STATE_NAMES = {0: CLOSED, 1: HALF_OPEN, 2: OPEN}

# value of the pio_rollout_mode gauge -> human name
ROLLOUT_MODE_NAMES = {0: "off", 1: "canary", 2: "shadow"}

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+"
    r"(?P<value>[^ ]+)\s*$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (
        value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    return float(raw)


def parse_prometheus(text: str) -> dict[str, list[tuple[dict[str, str], float]]]:
    """Parse Prometheus text exposition into
    ``{metric_name: [(labels, value), ...]}``. Comment/HELP/TYPE lines are
    skipped; histogram series keep their ``_bucket``/``_sum``/``_count``
    suffixes as distinct names. OpenMetrics exemplar clauses
    (``… # {trace_id="…"} value``) are stripped — the sample value still
    parses even when the scrape negotiated exemplars."""
    out: dict[str, list[tuple[dict[str, str], float]]] = {}
    for line in text.splitlines():
        # exemplar separator is a literal " # " outside label quotes; none
        # of the framework's label values contain one
        line = line.split(" # ", 1)[0].strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        labels = {
            k: _unescape(v) for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        try:
            value = _parse_value(m.group("value"))
        except ValueError:
            continue
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


Metrics = dict[str, list[tuple[dict[str, str], float]]]


def _total(metrics: Metrics, name: str, **match: str) -> float:
    return sum(
        v
        for labels, v in metrics.get(name, ())
        if all(labels.get(k) == mv for k, mv in match.items())
    )


def _max(metrics: Metrics, name: str) -> float:
    return max((v for _labels, v in metrics.get(name, ())), default=0.0)


def _histogram_quantile(
    metrics: Metrics, name: str, q: float, **match: str
) -> float:
    """Recompute a quantile from ``<name>_bucket{le=...}`` cumulative
    counts, summed across label sets matching ``match`` (linear
    interpolation in-bucket, mirroring obs.metrics.Histogram)."""
    buckets: dict[float, float] = {}
    for labels, v in metrics.get(f"{name}_bucket", ()):
        if any(labels.get(k) != mv for k, mv in match.items()):
            continue
        le = _parse_value(labels.get("le", "+Inf"))
        buckets[le] = buckets.get(le, 0.0) + v
    if not buckets:
        return 0.0
    bounds = sorted(buckets)
    count = buckets.get(float("inf"), max(buckets.values()))
    if count <= 0:
        return 0.0
    target = q * count
    prev_bound, prev_cum = 0.0, 0.0
    for bound in bounds:
        cum = buckets[bound]
        if cum >= target:
            if bound == float("inf"):
                return prev_bound
            width = bound - prev_bound
            in_bucket = cum - prev_cum
            frac = (target - prev_cum) / in_bucket if in_bucket else 1.0
            return prev_bound + width * frac
        prev_bound, prev_cum = bound, cum
    return prev_bound


def summarize(
    metrics: Metrics,
    prev: Metrics | None = None,
    interval_s: float | None = None,
    now: float | None = None,
) -> dict[str, Any]:
    """Digest one scrape (optionally against the previous one for rates)
    into the flat dict ``render`` prints and tests assert on. ``now``
    (unix seconds; injectable for tests) anchors age computations."""
    requests = _total(metrics, "pio_requests_total")
    errors = sum(
        v
        for labels, v in metrics.get("pio_requests_total", ())
        if labels.get("status", "").startswith("5")
    )
    out: dict[str, Any] = {
        "requests_total": requests,
        "errors_total": errors,
        "p50_ms": _histogram_quantile(metrics, "pio_request_seconds", 0.50) * 1e3,
        "p95_ms": _histogram_quantile(metrics, "pio_request_seconds", 0.95) * 1e3,
        "p99_ms": _histogram_quantile(metrics, "pio_request_seconds", 0.99) * 1e3,
        "shed_total": _total(metrics, "pio_load_shed_total"),
        "deadline_total": _total(metrics, "pio_deadline_exceeded_total"),
        "watchdog_total": _total(metrics, "pio_watchdog_trips_total"),
        "queue_depth": _total(metrics, "pio_queue_depth"),
        "queue_high_water": _total(metrics, "pio_queue_high_water"),
        "recompiles": _total(metrics, "pio_jit_cache_misses_total"),
        "xla_compiles": _total(metrics, "pio_xla_compile_events_total"),
        "retries_total": _total(metrics, "pio_storage_retries_total"),
        "events_ingested": _total(metrics, "pio_events_ingested_total"),
        "breakers": {
            labels.get("breaker", "?"): BREAKER_STATE_NAMES.get(int(v), str(v))
            for labels, v in metrics.get("pio_breaker_state", ())
        },
        "rollout_mode": ROLLOUT_MODE_NAMES.get(
            int(_total(metrics, "pio_rollout_mode")), "off"
        ),
        "rollout_fraction": _total(metrics, "pio_rollout_fraction"),
        "rollbacks_total": _total(metrics, "pio_rollbacks_total"),
        "model_versions": _model_versions(metrics),
    }
    out["phases"] = _phase_summary(metrics)
    out["cache_hit_ratio"] = _cache_hit_ratio(metrics)
    out["ann"] = _ann_summary(metrics)
    out["bandit"] = _bandit_summary(metrics)
    out["slo"] = _slo_summary(metrics)
    out["stream"] = _stream_summary(metrics, now)
    out["train"] = _train_summary(metrics)
    out["fleet"] = _fleet_summary(metrics)
    out["autoscaler"] = _autoscaler_summary(metrics)
    out["qps"] = None
    out["shed_rate"] = None
    out["stream_drain_rate"] = None
    out["train_step_rate"] = None
    if prev is not None and interval_s and interval_s > 0:
        d_req = requests - _total(prev, "pio_requests_total")
        d_shed = out["shed_total"] - _total(prev, "pio_load_shed_total")
        out["qps"] = max(0.0, d_req) / interval_s
        out["shed_rate"] = max(0.0, d_shed) / interval_s
        if out["stream"] is not None:
            d_drain = out["stream"]["drains_total"] - _total(
                prev, "pio_stream_drains_total"
            )
            out["stream_drain_rate"] = max(0.0, d_drain) / interval_s
        if out["train"] is not None:
            d_step = out["train"]["steps_total"] - _total(
                prev, "pio_train_steps_total"
            )
            out["train_step_rate"] = max(0.0, d_step) / interval_s
    return out




def _phase_summary(metrics: Metrics) -> dict[str, dict[str, float]] | None:
    """The latency-attribution waterfall, from ``pio_phase_seconds``:
    per-phase p50/p95 (ms) and count, request-ordered. None when the
    endpoint doesn't export the waterfall (e.g. an event server)."""
    if "pio_phase_seconds_bucket" not in metrics:
        return None
    counts: dict[str, float] = {}
    for labels, v in metrics.get("pio_phase_seconds_count", ()):
        phase = labels.get("phase")
        if phase:
            counts[phase] = counts.get(phase, 0.0) + v
    out: dict[str, dict[str, float]] = {}
    for phase in _PHASE_ORDER:
        if not counts.get(phase):
            continue
        out[phase] = {
            "count": counts[phase],
            "p50_ms": _histogram_quantile(
                metrics, "pio_phase_seconds", 0.50, phase=phase
            )
            * 1e3,
            "p95_ms": _histogram_quantile(
                metrics, "pio_phase_seconds", 0.95, phase=phase
            )
            * 1e3,
        }
    return out or None


def _cache_hit_ratio(metrics: Metrics) -> float | None:
    """Result-cache hit ratio from the pio_cache_* counters; None when
    the endpoint has no result cache (an event server) or the cache has
    seen no lookups yet — a disabled cache never moves either counter,
    so a 0/0 endpoint gets no misleading ``cache hit 0%`` column."""
    if "pio_cache_hits_total" not in metrics:
        return None
    hits = _total(metrics, "pio_cache_hits_total")
    misses = _total(metrics, "pio_cache_misses_total")
    total = hits + misses
    return (hits / total) if total else None


def _ann_summary(metrics: Metrics) -> dict[str, Any] | None:
    """The ANN retrieval line, from the ``pio_ann_*`` family: pinned
    index shape, probes per query, candidate fraction, sampled recall.
    None when no index is pinned AND no ANN query was ever served (the
    family registers eagerly at zero, which must not render a line)."""
    indexes = {
        labels.get("version", "?"): {"items": v}
        for labels, v in metrics.get("pio_ann_index_items", ())
        if v > 0
    }
    for labels, v in metrics.get("pio_ann_index_clusters", ()):
        ver = labels.get("version", "?")
        if ver in indexes:
            indexes[ver]["clusters"] = v
    queries = _total(metrics, "pio_ann_queries_total")
    if not indexes and queries <= 0:
        return None
    probes = _total(metrics, "pio_ann_probes_total")
    return {
        "queries_total": queries,
        "fallback_total": _total(metrics, "pio_ann_fallback_total"),
        "probes_per_query": (probes / queries) if queries else None,
        "candidates_frac": _total(metrics, "pio_ann_candidates_frac"),
        "recall_sampled": _total(metrics, "pio_ann_recall_sampled"),
        "recall_samples_total": _total(metrics, "pio_ann_recall_samples_total"),
        "refreshes_total": _total(metrics, "pio_ann_refreshes_total"),
        "rebuilds_total": _total(metrics, "pio_ann_rebuilds_total"),
        "indexes": indexes,
    }


def _bandit_summary(metrics: Metrics) -> dict[str, Any] | None:
    """The bandit line, from the ``pio_bandit_*`` family: per-arm pulls
    and posterior reward rates, the live traffic split, the promote
    probability, and the regret proxy. None while the family sits at its
    eager-registration zero (no policy ever engaged)."""
    if "pio_bandit_active" not in metrics:
        return None
    active = _total(metrics, "pio_bandit_active")
    pulls = {
        labels.get("arm", "?"): v
        for labels, v in metrics.get("pio_bandit_pulls_total", ())
    }
    promoted = _total(metrics, "pio_bandit_promotions_total")
    retired = _total(metrics, "pio_bandit_retirements_total")
    if not active and not pulls and not promoted and not retired:
        return None
    return {
        "active": bool(active),
        "pulls": pulls,
        "reward_rate": {
            labels.get("arm", "?"): v
            for labels, v in metrics.get("pio_bandit_reward_rate", ())
        },
        "fraction": _total(metrics, "pio_bandit_fraction"),
        "p_candidate_better": _total(
            metrics, "pio_bandit_p_candidate_better"
        ),
        "regret_pulls": _total(metrics, "pio_bandit_regret_pulls"),
        "matched_total": _total(metrics, "pio_bandit_matched_rewards_total"),
        "unmatched_total": _total(
            metrics, "pio_bandit_unmatched_rewards_total"
        ),
        "promotions_total": promoted,
        "retirements_total": retired,
    }


def _slo_summary(metrics: Metrics) -> dict[str, dict[str, Any]] | None:
    """The SLO burn-rate block, from the ``pio_slo_*`` gauges: per-SLO
    objective, per-window burn rates, and the alerting bit."""
    if "pio_slo_objective" not in metrics:
        return None
    out: dict[str, dict[str, Any]] = {}
    for labels, v in metrics.get("pio_slo_objective", ()):
        name = labels.get("slo")
        if name:
            out[name] = {"objective": v, "burn": {}, "alerting": False}
    for labels, v in metrics.get("pio_slo_burn_rate", ()):
        name, window = labels.get("slo"), labels.get("window")
        if name in out and window:
            out[name]["burn"][window] = v
    for labels, v in metrics.get("pio_slo_alerting", ()):
        name = labels.get("slo")
        if name in out:
            out[name]["alerting"] = bool(v)
    return out or None


def _stream_summary(metrics: Metrics, now: float | None) -> dict[str, Any] | None:
    """The speed-layer line, from the ``pio_stream_*`` family; None when
    no stream pipeline exports into this endpoint."""
    if not any(
        name in metrics
        for name in ("pio_stream_drains_total", "pio_stream_lag_events")
    ):
        return None
    last_ts = _total(metrics, "pio_stream_last_publish_timestamp")
    age = None
    if last_ts > 0:
        age = max(0.0, (now if now is not None else time.time()) - last_ts)
    return {
        "lag_events": _total(metrics, "pio_stream_lag_events"),
        "lag_seconds": _total(metrics, "pio_stream_lag_seconds"),
        "drains_total": _total(metrics, "pio_stream_drains_total"),
        "events_total": _total(metrics, "pio_stream_events_total"),
        "publishes_total": _total(metrics, "pio_stream_publishes_total"),
        "drift_suppressed": _total(metrics, "pio_stream_drift_suppressed_total"),
        "last_publish_age_s": age,
    }


def _train_summary(metrics: Metrics) -> dict[str, Any] | None:
    """The training screen, from the ``pio_train_*`` family (obs/xray):
    which trainer is in which phase, iterations done, device-time share,
    and the estimated-vs-measured HBM picture. None when no train
    profiler exports into this endpoint."""
    if "pio_train_steps_total" not in metrics:
        return None
    active: dict[str, str] = {}
    for labels, v in metrics.get("pio_train_active", ()):
        if v > 0 and labels.get("trainer"):
            active[labels["trainer"]] = ""
    for labels, v in metrics.get("pio_train_phase", ()):
        trainer, ph = labels.get("trainer"), labels.get("phase")
        if v > 0 and trainer in active and ph:
            active[trainer] = ph
    phase_wall = _total(metrics, "pio_train_phase_seconds_sum")
    device = _total(metrics, "pio_train_device_seconds_total")
    return {
        "steps_total": _total(metrics, "pio_train_steps_total"),
        "rows_total": _total(metrics, "pio_train_rows_total"),
        "active": active,
        "device_time_frac": (device / phase_wall) if phase_wall > 0 else 0.0,
        # busiest trainer, not the sum: per-trainer peaks are independent
        # samples of the same device pool — summing two 6 GB peaks would
        # render an HBM picture no device ever had
        "peak_bytes_per_device": _max(
            metrics, "pio_train_peak_bytes_per_device"
        ),
        "est_bytes_per_device": _max(
            metrics, "pio_train_est_bytes_per_device"
        ),
    }


def _fleet_summary(metrics: Metrics) -> dict[str, Any] | None:
    """The fleet line, from the gateway's federated ``pio_fleet_*``
    family: replica up/inflight states, ejection/readmission/restart
    counters, retry volume, and the gateway-hop p50. None when the
    endpoint isn't a fleet gateway."""
    if (
        "pio_fleet_replicas" not in metrics
        and "pio_fleet_replica_up" not in metrics
    ):
        return None
    replicas: dict[str, dict[str, Any]] = {}
    # rows are created by LIVE-SET gauges only (the exporters prune these
    # when a replica is retired by a scale-in); the monotonic counters
    # below merely annotate surviving rows — a retired replica's ejection
    # history must not resurrect it as a live-but-down entry
    for name, field, cast in (
        ("pio_fleet_replica_up", "up", lambda v: bool(v)),
        ("pio_fleet_replica_inflight", "inflight", float),
        ("pio_fleet_worker_last_crash_unix", "last_crash_unix", float),
    ):
        for labels, v in metrics.get(name, ()):
            rep = labels.get("replica")
            if rep:
                replicas.setdefault(rep, {})[field] = cast(v)
    for name, field in (
        ("pio_fleet_ejections_total", "ejections"),
        ("pio_fleet_readmissions_total", "readmissions"),
    ):
        for labels, v in metrics.get(name, ()):
            rep = labels.get("replica")
            if rep in replicas:
                replicas[rep][field] = float(v)
    # the captured-log path rides an info gauge (bounded: one series per
    # replica); `pio top --fleet` shows it for workers that have crashed,
    # so the excerpt feeding the incident bundle is one `tail` away
    for labels, v in metrics.get("pio_fleet_worker_log_info", ()):
        rep = labels.get("replica")
        if rep and v > 0 and labels.get("path"):
            replicas.setdefault(rep, {})["log_path"] = labels["path"]
    up = sum(1 for info in replicas.values() if info.get("up"))
    # multi-host inventory (--hosts): per-host up/slots/death gauges plus
    # the replica->host info series group the replica list by box; a
    # single-box fleet exports none of these and `hosts` stays empty
    hosts: dict[str, dict[str, Any]] = {}
    for name, field, cast in (
        ("pio_fleet_host_up", "up", lambda v: bool(v)),
        ("pio_fleet_host_slots", "slots", float),
        ("pio_fleet_host_deaths_total", "deaths", float),
    ):
        for labels, v in metrics.get(name, ()):
            host = labels.get("host")
            if host:
                hosts.setdefault(host, {"residents": []})[field] = cast(v)
    for labels, v in metrics.get("pio_fleet_worker_host_info", ()):
        rep, host = labels.get("replica"), labels.get("host")
        if rep and host and v > 0:
            if rep in replicas:
                replicas[rep]["host"] = host
            if host in hosts:
                hosts[host]["residents"].append(rep)
    # resident liveness comes from the SUPERVISOR's worker-named series
    # (`pio_fleet_worker_up{replica="w0"}`) — the gateway's replica rows
    # above are keyed by address, so a name lookup there always misses
    worker_up = {
        labels["replica"]: bool(v)
        for labels, v in metrics.get("pio_fleet_worker_up", ())
        if labels.get("replica")
    }
    for info in hosts.values():
        info["residents"].sort()
        info["residents_up"] = sum(
            1
            for rep in info["residents"]
            if worker_up.get(rep, bool(replicas.get(rep, {}).get("up")))
        )
    return {
        "replicas_total": _total(metrics, "pio_fleet_replicas")
        or float(len(replicas)),
        "replicas_up": float(up),
        "replicas": replicas,
        "hosts": hosts,
        "retries_total": _total(metrics, "pio_fleet_retries_total"),
        "no_replica_total": _total(metrics, "pio_fleet_no_replica_total"),
        "ejections_total": _total(metrics, "pio_fleet_ejections_total"),
        "readmissions_total": _total(metrics, "pio_fleet_readmissions_total"),
        "restarts_total": _total(metrics, "pio_fleet_restarts_total"),
        "crash_loops_total": _total(metrics, "pio_fleet_crash_loops_total"),
        "gateway_p50_ms": _histogram_quantile(
            metrics, "pio_gateway_request_seconds", 0.50
        )
        * 1e3,
    }


def _autoscaler_summary(metrics: Metrics) -> dict[str, Any] | None:
    """The autoscaler line, from the fleet parent's ``pio_autoscaler_*``
    family: live shape per class vs the envelope, plus the decision
    counters. None when no autoscaler runs on the scraped endpoint."""
    if "pio_autoscaler_replicas" not in metrics:
        return None
    shape = {
        labels.get("worker_class", "?"): v
        for labels, v in metrics.get("pio_autoscaler_replicas", ())
    }
    return {
        "shape": shape,
        "min_replicas": _total(metrics, "pio_autoscaler_replicas_min"),
        "max_replicas": _total(metrics, "pio_autoscaler_replicas_max"),
        "cpu_fallback_max": _total(metrics, "pio_autoscaler_cpu_fallback_max"),
        "scale_outs_total": _total(metrics, "pio_autoscaler_scale_outs_total"),
        "scale_ins_total": _total(metrics, "pio_autoscaler_scale_ins_total"),
        "deferred_total": _total(metrics, "pio_autoscaler_deferred_total"),
        "saturated_total": _total(metrics, "pio_autoscaler_saturated_total"),
        "overflow_picks_total": _total(
            metrics, "pio_fleet_overflow_picks_total"
        ),
        "last_scale_unix": _total(metrics, "pio_autoscaler_last_scale_unix"),
        "ticks_total": _total(metrics, "pio_autoscaler_ticks_total"),
        "errors_total": _total(metrics, "pio_autoscaler_errors_total"),
    }


def _model_versions(metrics: Metrics) -> dict[str, dict[str, Any]]:
    """Per-model-version request/error totals and the lanes each version
    serves on, from the ``pio_model_*`` rollout counters."""
    versions: dict[str, dict[str, Any]] = {}
    for name, field in (
        ("pio_model_requests_total", "requests"),
        ("pio_model_errors_total", "errors"),
    ):
        for labels, v in metrics.get(name, ()):
            ver = labels.get("version")
            if not ver:
                continue
            info = versions.setdefault(
                ver, {"requests": 0.0, "errors": 0.0, "lanes": set()}
            )
            info[field] += v
            if v > 0 and labels.get("lane"):
                info["lanes"].add(labels["lane"])
    for info in versions.values():
        info["lanes"] = ",".join(sorted(info["lanes"])) or "-"
    return versions


def format_number(v: Any, suffix: str = "") -> str:
    """'-' for missing, 1 decimal for fractional floats, bare ints
    otherwise. Shared by the terminal screen and the dashboard panels."""
    if v is None:
        return "-"
    if isinstance(v, float) and not v.is_integer():
        return f"{v:.1f}{suffix}"
    return f"{int(v)}{suffix}"


def format_bytes(v: Any) -> str:
    """'-' for missing/zero; 1.2GB-style otherwise (decimal units — HBM
    capacities are quoted decimal)."""
    if not v:
        return "-"
    v = float(v)
    for unit, div in (("GB", 1e9), ("MB", 1e6), ("KB", 1e3)):
        if v >= div:
            return f"{v / div:.1f}{unit}"
    return f"{int(v)}B"


def render(summary: dict[str, Any], url: str) -> str:
    """The one screen."""
    num = format_number
    breakers = summary.get("breakers") or {}
    breaker_line = (
        "  ".join(f"{name}={state}" for name, state in sorted(breakers.items()))
        or "(none)"
    )
    lines = [
        f"pio top — {url}   {time.strftime('%H:%M:%S')}",
        "",
        f"  qps        {num(summary['qps'], '/s'):>12}    "
        f"requests   {num(summary['requests_total']):>12}    "
        f"errors(5xx) {num(summary['errors_total']):>10}",
        f"  p50        {num(summary['p50_ms'], ' ms'):>12}    "
        f"p95        {num(summary['p95_ms'], ' ms'):>12}    "
        f"p99         {num(summary['p99_ms'], ' ms'):>10}",
        f"  shed rate  {num(summary['shed_rate'], '/s'):>12}    "
        f"shed total {num(summary['shed_total']):>12}    "
        f"deadlines   {num(summary['deadline_total']):>10}",
        f"  queue      {num(summary['queue_depth']):>12}    "
        f"high water {num(summary['queue_high_water']):>12}    "
        f"watchdog    {num(summary['watchdog_total']):>10}",
        f"  recompiles {num(summary['recompiles']):>12}    "
        f"xla events {num(summary['xla_compiles']):>12}    "
        f"retries     {num(summary['retries_total']):>10}",
        f"  breakers   {breaker_line}",
    ]
    phases = summary.get("phases") or {}
    if phases:
        # the waterfall line: request-ordered per-phase p50s plus their sum
        # — the at-a-glance answer to "where do the milliseconds go"
        parts = [
            f"{phase.replace('_', ' ')} {info['p50_ms']:.2f}"
            for phase, info in phases.items()
        ]
        total_p50 = sum(info["p50_ms"] for info in phases.values())
        tail = f"   (p50 ms, Σ {total_p50:.2f})"
        hit_ratio = summary.get("cache_hit_ratio")
        if hit_ratio is not None:
            # the hit-ratio column rides the waterfall line: a high ratio
            # explains a Σ well under the e2e p50 (hits skip most phases)
            tail += f"   cache hit {hit_ratio * 100.0:.0f}%"
        lines.append("  waterfall  " + " | ".join(parts) + tail)
    ann = summary.get("ann")
    if ann is not None:
        idx_parts = [
            f"{ver} ({num(info.get('items'))} items/"
            f"{num(info.get('clusters'))} clusters)"
            for ver, info in sorted((ann.get("indexes") or {}).items())
        ]
        line = "  ann        " + (" ".join(idx_parts) or "(no index pinned)")
        line += f"   queries {num(ann['queries_total'])}"
        if ann.get("probes_per_query") is not None:
            line += f"   probes/q {ann['probes_per_query']:.1f}"
        if ann.get("candidates_frac"):
            line += f"   cand {ann['candidates_frac'] * 100.0:.1f}%"
        if ann.get("recall_samples_total"):
            line += f"   recall~{ann['recall_sampled']:.3f}"
        if ann.get("fallback_total"):
            line += f"   fallback {num(ann['fallback_total'])}"
        if ann.get("refreshes_total") or ann.get("rebuilds_total"):
            line += (
                f"   refreshes {num(ann['refreshes_total'])}"
                f"/{num(ann['rebuilds_total'])} rebuilt"
            )
        lines.append(line)
    bandit = summary.get("bandit")
    if bandit is not None:
        arm_parts = []
        for arm in ("stable", "candidate"):
            if arm in bandit.get("pulls", {}) or arm in bandit.get(
                "reward_rate", {}
            ):
                rate = bandit.get("reward_rate", {}).get(arm)
                arm_parts.append(
                    f"{arm} "
                    + (f"{rate:.3f}" if rate is not None else "-")
                    + f" ({num(bandit['pulls'].get(arm, 0))} pulls)"
                )
        state = "live" if bandit.get("active") else "idle"
        line = (
            f"  bandit     [{state}] "
            + (" / ".join(arm_parts) or "(no arms)")
        )
        if bandit.get("active"):
            line += f"   split {bandit.get('fraction', 0.0):.2f}"
            p = bandit.get("p_candidate_better")
            if p is not None and p >= 0:
                line += f"   P(cand>stable) {p:.2f}"
        line += f"   regret {num(bandit.get('regret_pulls'))}"
        line += f"   matched {num(bandit.get('matched_total'))}"
        if bandit.get("unmatched_total"):
            line += f" ({num(bandit['unmatched_total'])} unmatched)"
        line += (
            f"   promoted {num(bandit.get('promotions_total'))}"
            f"   retired {num(bandit.get('retirements_total'))}"
        )
        lines.append(line)
    slos = summary.get("slo") or {}
    if slos:
        parts = []
        for name, info in sorted(slos.items()):
            burns = "/".join(
                f"{info['burn'][w]:.2f}"
                for w in sorted(info["burn"], key=float)
            )
            state = "ALERT" if info.get("alerting") else "ok"
            parts.append(f"{name} burn {burns or '-'} {state}")
        lines.append("  slo        " + "   ".join(parts))
    versions = summary.get("model_versions") or {}
    if versions:
        parts = [
            f"{ver}[{info['lanes']}] req {num(info['requests'])} "
            f"err {num(info['errors'])}"
            for ver, info in sorted(versions.items())
        ]
        mode = summary.get("rollout_mode", "off")
        tail = ""
        if mode != "off":
            tail = f"   mode {mode}"
            if mode == "canary":
                tail += f"@{summary.get('rollout_fraction', 0.0):.2f}"
        if summary.get("rollbacks_total"):
            tail += f"   rollbacks {num(summary['rollbacks_total'])}"
        lines.append("  models     " + "  ".join(parts) + tail)
    stream = summary.get("stream")
    if stream is not None:
        age = stream.get("last_publish_age_s")
        published = f"published {num(stream['publishes_total'])}"
        if age is not None:
            published += f" (age {num(round(age, 1), 's')})"
        drain_rate = summary.get("stream_drain_rate")
        drains = f"drains {num(stream['drains_total'])}"
        if drain_rate is not None:
            drains = f"drains {num(drain_rate, '/s')} ({num(stream['drains_total'])})"
        # the fold-in loop's jit cache misses ride the same endpoint: a
        # vocab-growth recompile storm is a stream incident, so it shows
        # on the stream line, not only in the recompiles row
        lines.append(
            f"  stream     lag {num(stream['lag_events'])} ev / "
            f"{num(round(stream['lag_seconds'], 1), 's')}   {drains}   "
            f"{published}   drift-suppressed {num(stream['drift_suppressed'])}"
            f"   recompiles {num(summary.get('recompiles'))}"
        )
    train = summary.get("train")
    if train is not None:
        active = train.get("active") or {}
        who = (
            "  ".join(
                f"{name}[{ph or 'idle'}]" for name, ph in sorted(active.items())
            )
            or "(idle)"
        )
        steps = f"steps {num(train['steps_total'])}"
        rate = summary.get("train_step_rate")
        if rate is not None:
            steps += f" ({num(rate, '/s')})"
        frac = train.get("device_time_frac") or 0.0
        hbm = (
            f"hbm peak {format_bytes(train.get('peak_bytes_per_device'))}"
            f" / est {format_bytes(train.get('est_bytes_per_device'))}"
        )
        lines.append(
            f"  train      {who}   {steps}   device {frac * 100.0:.0f}%   "
            f"rows {num(train['rows_total'])}   {hbm}"
        )
    fleet = summary.get("fleet")
    if fleet is not None:
        parts = []
        for rep, info in sorted((fleet.get("replicas") or {}).items()):
            if "up" not in info:
                # supervisor-side series (crash time, log path) use the
                # worker NAME as the replica label; without probe state
                # they are not routing targets — the crash line below
                # renders them, a phantom [DOWN] entry here would not
                continue
            state = "up" if info.get("up") else "DOWN"
            inflight = info.get("inflight")
            tag = f"{rep}[{state}"
            if inflight is not None:
                tag += f" {num(inflight)}"
            parts.append(tag + "]")
        line = (
            f"  fleet      {num(fleet['replicas_up'])}/"
            f"{num(fleet['replicas_total'])} up   "
            + ("  ".join(parts) or "(no replicas)")
        )
        line += (
            f"   retries {num(fleet['retries_total'])}"
            f"   ejected {num(fleet['ejections_total'])}"
            f"   readmitted {num(fleet['readmissions_total'])}"
        )
        if fleet.get("restarts_total"):
            line += f"   restarts {num(fleet['restarts_total'])}"
        if fleet.get("crash_loops_total"):
            line += f"   CRASH-LOOPED {num(fleet['crash_loops_total'])}"
        if fleet.get("gateway_p50_ms"):
            line += f"   gw p50 {fleet['gateway_p50_ms']:.2f} ms"
        lines.append(line)
        for host, hinfo in sorted((fleet.get("hosts") or {}).items()):
            # one line per declared host: replicas grouped by box, the
            # up/slots census, and a shouting marker when the whole box
            # is gone (the per-replica DOWNs above are its symptoms)
            residents = hinfo.get("residents") or []
            rep_up = hinfo.get("residents_up")
            if rep_up is None:
                rep_up = sum(
                    1
                    for rep in residents
                    if (fleet.get("replicas") or {}).get(rep, {}).get("up")
                )
            hline = (
                f"  host       {host}  {num(float(rep_up))}/"
                f"{num(hinfo.get('slots'))} slots  "
                + ("  ".join(residents) or "(empty)")
            )
            if not hinfo.get("up", True):
                hline += "   HOST-DOWN"
            if hinfo.get("deaths"):
                hline += f"   deaths {num(hinfo['deaths'])}"
            lines.append(hline)
        for rep, info in sorted((fleet.get("replicas") or {}).items()):
            # the last-crash excerpt: which replica died and where its
            # captured stderr tail lives (the incident bundle's source)
            if info.get("last_crash_unix") and info.get("log_path"):
                lines.append(
                    f"  crash      {rep} last "
                    f"{time.strftime('%H:%M:%S', time.localtime(info['last_crash_unix']))}"
                    f"   log {info['log_path']}"
                )
    scaler = summary.get("autoscaler")
    if scaler is not None:
        shape = scaler.get("shape") or {}
        device = shape.get("device", 0.0)
        cpu = shape.get("cpu-fallback", 0.0)
        line = (
            f"  autoscaler device {num(device)} "
            f"[{num(scaler['min_replicas'])}..{num(scaler['max_replicas'])}]"
        )
        if scaler.get("cpu_fallback_max"):
            line += (
                f"   cpu {num(cpu)}/{num(scaler['cpu_fallback_max'])}"
                f"   overflow {num(scaler['overflow_picks_total'])}"
            )
        line += (
            f"   outs {num(scaler['scale_outs_total'])}"
            f"   ins {num(scaler['scale_ins_total'])}"
        )
        if scaler.get("deferred_total"):
            line += f"   deferred {num(scaler['deferred_total'])}"
        if scaler.get("saturated_total"):
            line += f"   SATURATED {num(scaler['saturated_total'])}"
        if scaler.get("last_scale_unix"):
            line += (
                "   last "
                f"{time.strftime('%H:%M:%S', time.localtime(scaler['last_scale_unix']))}"
            )
        lines.append(line)
    if summary.get("events_ingested"):
        lines.append(f"  ingested   {num(summary['events_ingested']):>12}")
    return "\n".join(lines)


def fetch_metrics(url: str, timeout_s: float = 5.0) -> str:
    with urllib.request.urlopen(f"{url}/metrics", timeout=timeout_s) as resp:
        return resp.read().decode("utf-8", errors="replace")


# ---------------------------------------------------------------------------
# --history: the telemetry ring rendered as series
# ---------------------------------------------------------------------------

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def fetch_stacks(url: str, timeout_s: float = 5.0) -> dict[str, Any]:
    """GET ``<url>/profile/stacks?format=json`` — the host sampler's
    structured snapshot (obs/sampler). Raises on any transport/parse
    failure; the caller degrades the hotspots line, never the screen."""
    import json as _json

    target = url.rstrip("/") + "/profile/stacks?format=json"
    with urllib.request.urlopen(target, timeout=timeout_s) as resp:
        return _json.loads(resp.read().decode("utf-8", errors="replace"))


def render_hotspots(snapshot: dict[str, Any]) -> str:
    """The ``--hotspots`` block: per-role top-of-stack frames from the
    always-on sampler, plus its self-measured overhead — the line that
    says WHICH thread role is hot without leaving the terminal."""
    if "error" in snapshot:
        return f"hotspots: unreachable ({snapshot['error']})"
    overhead = snapshot.get("overheadFrac") or 0.0
    samples = int(snapshot.get("samples") or 0)
    lines = [
        f"hotspots (sampler {overhead * 100:.2f}% ovh, {samples} samples):"
    ]
    hotspots = snapshot.get("hotspots") or {}
    roles = snapshot.get("roles") or {}
    for role in sorted(hotspots, key=lambda r: -roles.get(r, 0)):
        tops = "  ".join(
            f"{e['frame']} {e['frac'] * 100:.0f}%" for e in hotspots[role]
        )
        lines.append(f"  {role:<12} {tops}")
    if len(lines) == 1:
        lines.append("  (no samples yet)")
    return "\n".join(lines)


def sparkline(values: list[float], width: int = 60) -> str:
    """Downsample to ``width`` columns and render with block glyphs;
    empty input renders as '-'. Scaled to the series max (min pinned at
    0 — queue depth and burn are magnitudes, not deltas)."""
    if not values:
        return "-"
    if len(values) > width:
        # mean-pool into width buckets so a spike several records wide
        # survives; a single-record spike still lands in some bucket
        step = len(values) / width
        pooled = []
        for i in range(width):
            lo, hi = int(i * step), max(int(i * step) + 1, int((i + 1) * step))
            chunk = values[lo:hi]
            pooled.append(sum(chunk) / len(chunk))
        values = pooled
    top = max(values)
    if top <= 0:
        return _SPARK_BLOCKS[0] * len(values)
    out = []
    for v in values:
        idx = int((max(0.0, v) / top) * (len(_SPARK_BLOCKS) - 1) + 0.5)
        out.append(_SPARK_BLOCKS[min(idx, len(_SPARK_BLOCKS) - 1)])
    return "".join(out)


def render_history(records: list[dict[str, Any]], window_s: float) -> str:
    """The ``pio top --history`` screen: queue-depth / inflight / burn /
    shed series from the telemetry ring's snapshot records, oldest on
    the left. Works identically whether the records came over HTTP
    (``GET /telemetry/window``) or straight off the on-disk ring — the
    ring surviving a gateway restart is the whole point."""
    if not records:
        return "pio top --history: no telemetry records in the window"
    # the ring carries two record kinds: "fleet" snapshots (the series
    # below) and the autoscaler's "scaling" decisions (rendered as a
    # marker line — they have no gauges to sparkline)
    scaling = [r for r in records if r.get("kind") == "scaling"]
    records = [r for r in records if r.get("kind", "fleet") == "fleet"]
    if not records:
        return "pio top --history: no fleet snapshots in the window"
    t0 = float(records[0].get("t", 0.0))
    t1 = float(records[-1].get("t", t0))
    queue = [float(r.get("gauges", {}).get("queue_depth", 0.0)) for r in records]
    inflight = [float(r.get("gauges", {}).get("inflight", 0.0)) for r in records]
    shed = [float(r.get("counters", {}).get("no_replica", 0.0)) for r in records]
    healthy = [
        float(sum(1 for rep in r.get("replicas", {}).values() if rep.get("healthy")))
        for r in records
    ]
    # fast-window burn per SLO: the series the ROADMAP-2 autoscaler reads
    burns: dict[str, list[float]] = {}
    alerts = 0
    for r in records:
        for name, state in (r.get("slo") or {}).items():
            burn = state.get("burn") or {}
            fast = min(burn, key=float, default=None)
            burns.setdefault(name, []).append(
                float(burn.get(fast, 0.0)) if fast is not None else 0.0
            )
            if state.get("alerting"):
                alerts += 1
    lines = [
        f"pio top --history — {len(records)} snapshots over "
        f"{max(0.0, t1 - t0):.0f}s (window {window_s:.0f}s)   "
        f"{time.strftime('%H:%M:%S', time.localtime(t0))} → "
        f"{time.strftime('%H:%M:%S', time.localtime(t1))}",
        "",
        f"  queue      {sparkline(queue)}  max {format_number(max(queue))}",
        f"  inflight   {sparkline(inflight)}  max {format_number(max(inflight))}",
        f"  healthy    {sparkline(healthy)}  "
        f"min {format_number(min(healthy) if healthy else 0)}",
        f"  shed Σ     {sparkline(shed)}  last {format_number(shed[-1])}",
    ]
    for name, series in sorted(burns.items()):
        lines.append(
            f"  burn {name[:20]:<20} {sparkline(series, width=40)}  "
            f"last {series[-1]:.2f}"
        )
    if alerts:
        lines.append(f"  ALERTING in {alerts} snapshot(s)")
    if scaling:
        last = scaling[-1]
        decision = last.get("decision") or {}
        shape = last.get("shape") or {}
        lines.append(
            f"  scaling    {len(scaling)} decision(s)   last: "
            f"{decision.get('action', '?')} {decision.get('class') or ''} "
            f"({decision.get('reason', '?')}) -> "
            f"device {format_number(shape.get('device', 0))}"
            + (
                f" + cpu {format_number(shape.get('cpu'))}"
                if shape.get("cpu")
                else ""
            )
            + f"   {time.strftime('%H:%M:%S', time.localtime(float(last.get('t', 0.0))))}"
        )
    return "\n".join(lines)


def render_batchpredict(status: dict[str, Any]) -> str:
    """The ``pio top --batchpredict`` progress line, from the run's
    throttled atomic status file (docs/batch_predict.md): live while the
    run is active, final totals after it. One header + one line — the
    offline twin of the serving waterfall line."""
    num = format_number
    state = status.get("state", "?")
    qps = status.get("qps")
    queries = status.get("queries", 0)
    ok = status.get("ok", 0)
    errors = status.get("errors", 0)
    batches = status.get("batches", 0)
    phase_p50 = status.get("phaseP50Ms") or {}
    phases = (
        "  phases "
        + "|".join(
            f"{name} {phase_p50[name]:.1f}"
            for name in ("read", "assemble", "dispatch", "fetch", "write")
            if name in phase_p50
        )
        + " ms"
        if phase_p50
        else ""
    )
    engine = status.get("engineId", "?")
    src = status.get("source", "?")
    return (
        f"pio top — batchpredict {engine} (pid {status.get('pid', '?')}, "
        f"{state})   {time.strftime('%H:%M:%S')}\n"
        f"  batchpredict  {num(queries)} q ({num(ok)} ok, {num(errors)} err)"
        f"  {num(batches)} batches x{num(status.get('batchSize'))}"
        f"  {num(qps, ' q/s')}  src {src}{phases}"
    )


def run_batchpredict_top(
    path: str,
    interval_s: float = 2.0,
    iterations: int | None = None,
    json_mode: bool = False,
    out: Callable[[str], None] = print,
    sleep: Callable[[float], None] = time.sleep,
) -> int:
    """Poll-and-render loop over a batchpredict status file. A missing or
    torn file degrades to an 'unreadable' line (the writer is atomic, so
    torn means 'not started yet'); the loop keeps polling — the usual
    mode is watching a run that is still warming up."""
    import json as _json

    n = 0
    try:
        while iterations is None or n < iterations:
            try:
                with open(path) as fh:
                    status = _json.load(fh)
            except (OSError, ValueError) as exc:
                if json_mode:
                    out(_json.dumps({"batchpredict": path, "error": str(exc)}))
                else:
                    out(f"pio top — batchpredict: {path} unreadable ({exc})")
            else:
                if json_mode:
                    out(_json.dumps({"batchpredict": path, **status}))
                else:
                    out(render_batchpredict(status))
            n += 1
            if iterations is None or n < iterations:
                sleep(interval_s)
    except KeyboardInterrupt:
        pass
    return 0


def render_evalgrid(status: dict[str, Any]) -> str:
    """The ``pio top --eval`` live grid line, from the run's throttled
    atomic status file (docs/evaluation.md): cells done/total, running
    workers, best score so far, ETA — live while the grid runs, final
    totals after."""
    num = format_number
    state = status.get("state", "?")
    done = status.get("cellsDone", 0)
    total = status.get("cellsTotal", 0)
    skipped = status.get("cellsSkipped", 0)
    failed = status.get("cellsFailed", 0)
    best = status.get("bestScore")
    best_str = (
        f"best {best:.4f} (params {status.get('bestParams', '?')})"
        if isinstance(best, (int, float))
        else "best —"
    )
    eta = status.get("etaS") or 0
    eta_str = f"  eta {eta:.0f}s" if eta and state == "running" else ""
    extras = []
    if skipped:
        extras.append(f"{num(skipped)} resumed")
    if failed:
        extras.append(f"{num(failed)} FAILED")
    extra_str = f" ({', '.join(extras)})" if extras else ""
    return (
        f"pio top — eval grid [{status.get('metric', '?')}] "
        f"(pid {status.get('pid', '?')}, {state})   {time.strftime('%H:%M:%S')}\n"
        f"  grid   {num(done)}/{num(total)} cells{extra_str}   "
        f"{num(status.get('folds'))} folds   "
        f"{num(status.get('running'))} running / "
        f"{num(status.get('workers'))} workers   {best_str}{eta_str}"
    )


def run_evalgrid_top(
    path: str,
    interval_s: float = 2.0,
    iterations: int | None = None,
    json_mode: bool = False,
    out: Callable[[str], None] = print,
    sleep: Callable[[float], None] = time.sleep,
) -> int:
    """Poll-and-render loop over an eval-grid status file — the
    batchpredict loop's twin: a missing/torn file degrades to an
    'unreadable' line and the loop keeps polling (the writer is atomic,
    so torn means 'not started yet')."""
    import json as _json

    n = 0
    try:
        while iterations is None or n < iterations:
            try:
                with open(path) as fh:
                    status = _json.load(fh)
            except (OSError, ValueError) as exc:
                if json_mode:
                    out(_json.dumps({"evalgrid": path, "error": str(exc)}))
                else:
                    out(f"pio top — eval grid: {path} unreadable ({exc})")
            else:
                if json_mode:
                    out(_json.dumps({"evalgrid": path, **status}))
                else:
                    out(render_evalgrid(status))
            n += 1
            if iterations is None or n < iterations:
                sleep(interval_s)
    except KeyboardInterrupt:
        pass
    return 0


def render_lifecycle(status: dict[str, Any]) -> str:
    """The ``pio top --lifecycle`` line, from the controller's durable
    state file (docs/lifecycle.md): episode state, what triggered it,
    the grid's progress, the candidate being baked, and the last
    episode's outcome."""
    policy = status.get("policy") or {}
    grid = status.get("grid") or {}
    state = policy.get("state", "?")
    parts = [f"pio top — lifecycle {status.get('engine') or '?'}"]
    if status.get("paused"):
        parts.append("[PAUSED]")
    head = " ".join(parts) + f"   {time.strftime('%H:%M:%S')}"
    detail = [f"  state  {state}"]
    if policy.get("triggerReason"):
        detail.append(f"trigger {policy['triggerReason']}")
    if state == "tuning":
        detail.append(f"grid {grid.get('state') or 'starting'}")
        if grid.get("error"):
            detail.append(f"error {grid['error']}")
    if state == "baking" and policy.get("stagedVersion"):
        detail.append(f"candidate {policy['stagedVersion']}")
    if policy.get("lastOutcome"):
        detail.append(f"last {policy['lastOutcome']}")
    last = status.get("lastDecision") or {}
    if last.get("reason"):
        detail.append(f"({last.get('action')}: {last.get('reason')})")
    return head + "\n" + "   ".join(detail)


def run_lifecycle_top(
    path: str,
    interval_s: float = 2.0,
    iterations: int | None = None,
    json_mode: bool = False,
    out: Callable[[str], None] = print,
    sleep: Callable[[float], None] = time.sleep,
) -> int:
    """Poll-and-render loop over the lifecycle controller's state file —
    the eval-grid loop's twin: a missing/torn file degrades to an
    'unreadable' line and the loop keeps polling (the writer is atomic,
    so torn means 'not started yet')."""
    import json as _json

    n = 0
    try:
        while iterations is None or n < iterations:
            try:
                with open(path) as fh:
                    status = _json.load(fh)
            except (OSError, ValueError) as exc:
                if json_mode:
                    out(_json.dumps({"lifecycle": path, "error": str(exc)}))
                else:
                    out(f"pio top — lifecycle: {path} unreadable ({exc})")
            else:
                if json_mode:
                    out(_json.dumps({"lifecycle": path, **status}))
                else:
                    out(render_lifecycle(status))
            n += 1
            if iterations is None or n < iterations:
                sleep(interval_s)
    except KeyboardInterrupt:
        pass
    return 0


def fetch_telemetry_window(
    url: str, window_s: float, timeout_s: float = 5.0
) -> list[dict[str, Any]]:
    import json as _json

    with urllib.request.urlopen(
        f"{url}/telemetry/window?s={window_s:g}", timeout=timeout_s
    ) as resp:
        data = _json.loads(resp.read().decode("utf-8", errors="replace"))
    return data.get("records", [])


def run_history(
    url: str | None = None,
    obs_dir: str | None = None,
    window_s: float = 600.0,
    json_mode: bool = False,
    out: Callable[[str], None] = print,
) -> int:
    """One-shot history screen, from the gateway's
    ``/telemetry/window`` endpoint (``--url``) or straight off an
    on-disk ring directory (``--obs-dir``, for when the gateway is down
    — the forensic case the ring exists for)."""
    import json as _json

    try:
        if obs_dir:
            ring_dir = os.path.join(obs_dir, "telemetry")
            if not os.path.isdir(ring_dir):
                # read path must not mkdir a typo'd --obs-dir into being
                out(f"pio top --history: no telemetry ring at {ring_dir}")
                return 1
            from predictionio_tpu.obs.tsring import TelemetryRing

            records = TelemetryRing(ring_dir).window(window_s)
        elif url:
            records = fetch_telemetry_window(url, window_s)
        else:
            out("pio top --history needs --url or --obs-dir")
            return 2
    except Exception as exc:
        out(f"pio top --history: telemetry unavailable ({exc})")
        return 1
    if json_mode:
        out(_json.dumps({"window_s": window_s, "records": records}))
    else:
        out(render_history(records, window_s))
    return 0


def run_top(
    url: str,
    interval_s: float = 2.0,
    iterations: int | None = None,
    fetch: Callable[[str], str] | None = None,
    out: Callable[[str], None] = print,
    clear_screen: bool | None = None,
    sleep: Callable[[float], None] = time.sleep,
    json_mode: bool = False,
    urls: list[str] | None = None,
    hotspots: bool = False,
    stacks_fetch: Callable[[str], dict[str, Any]] | None = None,
) -> int:
    """Poll-and-render loop. ``iterations=None`` runs until interrupted;
    fetch/out/sleep are injectable so tests drive it without a network.
    ``json_mode`` emits one machine-readable JSON object per snapshot —
    one per line — so CI and fleet tooling can consume the same digest
    the terminal screen renders. ``urls`` polls SEVERAL endpoints per
    refresh (``--metrics-url`` repeated): fleet dashboards scrape every
    replica directly as well as the gateway's federated view, and each
    endpoint gets its own JSON object (or screen block) per refresh with
    per-endpoint rate state — one unreachable replica degrades only its
    own line, never the whole refresh."""
    import json as _json

    fetch = fetch or fetch_metrics
    endpoints = [u for u in (urls or []) if u] or [url]
    if clear_screen is None:
        clear_screen = sys.stdout.isatty() and not json_mode
    prev: dict[str, Metrics] = {}
    prev_t: dict[str, float] = {}
    n = 0
    # Ctrl-C is a clean exit wherever it lands — mid-fetch (urllib can
    # block up to its timeout against a hung server), mid-render, or in
    # the sleep — never a stack trace
    try:
        while iterations is None or n < iterations:
            screens: list[str] = []
            for u in endpoints:
                try:
                    text = fetch(u)
                except Exception as exc:
                    if json_mode:
                        out(_json.dumps({"url": u, "error": str(exc)}))
                    else:
                        screens.append(f"pio top — {u}: unreachable ({exc})")
                    prev.pop(u, None)
                    prev_t.pop(u, None)
                else:
                    metrics = parse_prometheus(text)
                    now = time.monotonic()
                    last_t = prev_t.get(u)
                    dt = (now - last_t) if last_t is not None else None
                    summary = summarize(
                        metrics, prev=prev.get(u), interval_s=dt
                    )
                    if hotspots:
                        # degradation contract: an endpoint without the
                        # profiling plane (older server, proxy) costs one
                        # "unreachable" line, never the whole refresh
                        try:
                            summary["hotspots"] = (stacks_fetch or fetch_stacks)(u)
                        except Exception as exc:  # noqa: BLE001
                            summary["hotspots"] = {"error": str(exc)}
                    if json_mode:
                        out(
                            _json.dumps(
                                {"url": u, "time": time.time(), **summary}
                            )
                        )
                    else:
                        block = render(summary, u)
                        if hotspots:
                            block += "\n" + render_hotspots(summary["hotspots"])
                        screens.append(block)
                    prev[u], prev_t[u] = metrics, now
            if screens:
                screen = "\n\n".join(screens)
                if clear_screen:
                    out("\x1b[2J\x1b[H" + screen)
                else:
                    out(screen)
            n += 1
            if iterations is None or n < iterations:
                sleep(interval_s)
    except KeyboardInterrupt:
        pass
    return 0
