"""`pio`-style command-line console.

Reference parity: ``tools/.../console/Console.scala:134-630`` verb set —
  version, status, build, train, eval, deploy, undeploy, batchpredict,
  eventserver, adminserver, dashboard,
  app {new, list, show, delete, data-delete, channel-new, channel-delete},
  accesskey {new, list, delete}, template {list, get}, import, export, run.

Beyond the reference: ``lint`` (TPU-aware static analysis), ``top``
(live terminal summary of a running server's /metrics — qps, p95, shed
rate, breaker states, jit recompile count; see docs/observability.md),
and ``models`` (model registry: versioned artifacts, canary/shadow
rollout, promote/rollback/diff; see docs/model_registry.md).

Where the reference assembled a spark-submit command line around JVM mains
(``Runner.runOnSpark``, process boundary #1 in SURVEY.md section 3), this CLI
*is* the workflow process: train/eval/deploy run in-process on the local
devices; multi-host jobs launch this same CLI once per host with
``JAX_COORDINATOR`` env (jax.distributed) — no submission layer needed.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import sys

import predictionio_tpu
# import-light by design (pure stdlib AST walking, no jax/numpy) — safe to
# pull in for every pio verb
from predictionio_tpu.analysis.cli import add_lint_arguments, run_lint
from predictionio_tpu.data.storage.base import AccessKey, App, Channel
from predictionio_tpu.data.storage.registry import Storage

logger = logging.getLogger(__name__)


def _storage() -> Storage:
    return Storage.instance()


def _die(msg: str, code: int = 1) -> int:
    print(f"[ERROR] {msg}", file=sys.stderr)
    return code


# ---------------------------------------------------------------------------
# app / accesskey / channel management (ref commands/App.scala)
# ---------------------------------------------------------------------------


def cmd_app_new(args) -> int:
    storage = _storage()
    apps = storage.get_meta_data_apps()
    if apps.get_by_name(args.name):
        return _die(f"App {args.name} already exists.")
    app_id = apps.insert(App(args.id or 0, args.name, args.description))
    if app_id is None:
        return _die(f"Unable to create app {args.name}.")
    storage.get_l_events().init(app_id)
    key = storage.get_meta_data_access_keys().insert(
        AccessKey(args.access_key or "", app_id, ())
    )
    if key is None:
        return _die(
            f"App {args.name} created (ID {app_id}) but access key "
            f"{args.access_key!r} already exists; create one with `accesskey new`."
        )
    print(f"Created a new app:")
    print(f"      Name: {args.name}")
    print(f"        ID: {app_id}")
    print(f"Access Key: {key}")
    return 0


def cmd_app_list(args) -> int:
    storage = _storage()
    keys = storage.get_meta_data_access_keys()
    print(f"{'Name':<20} | {'ID':>4} | Access Key")
    for app in storage.get_meta_data_apps().get_all():
        app_keys = keys.get_by_app_id(app.id)
        first = app_keys[0].key if app_keys else ""
        print(f"{app.name:<20} | {app.id:>4} | {first}")
    return 0


def cmd_app_show(args) -> int:
    storage = _storage()
    app = storage.get_meta_data_apps().get_by_name(args.name)
    if app is None:
        return _die(f"App {args.name} does not exist.")
    print(f"    App Name: {app.name}")
    print(f"      App ID: {app.id}")
    print(f" Description: {app.description or ''}")
    for k in storage.get_meta_data_access_keys().get_by_app_id(app.id):
        events = ",".join(k.events) if k.events else "(all)"
        print(f"  Access Key: {k.key} | {events}")
    for c in storage.get_meta_data_channels().get_by_app_id(app.id):
        print(f"     Channel: {c.name} (ID {c.id})")
    return 0


def cmd_app_delete(args) -> int:
    storage = _storage()
    apps = storage.get_meta_data_apps()
    app = apps.get_by_name(args.name)
    if app is None:
        return _die(f"App {args.name} does not exist.")
    if not args.force:
        return _die("Refusing to delete without --force (destructive).")
    for c in storage.get_meta_data_channels().get_by_app_id(app.id):
        storage.get_l_events().remove(app.id, c.id)
        storage.get_meta_data_channels().delete(c.id)
    storage.get_l_events().remove(app.id)
    for k in storage.get_meta_data_access_keys().get_by_app_id(app.id):
        storage.get_meta_data_access_keys().delete(k.key)
    apps.delete(app.id)
    print(f"Deleted app {args.name}.")
    return 0


def cmd_app_data_delete(args) -> int:
    storage = _storage()
    app = storage.get_meta_data_apps().get_by_name(args.name)
    if app is None:
        return _die(f"App {args.name} does not exist.")
    if not args.force:
        return _die("Refusing to delete data without --force (destructive).")
    if args.channel:
        channels = storage.get_meta_data_channels().get_by_app_id(app.id)
        ch = next((c for c in channels if c.name == args.channel), None)
        if ch is None:
            return _die(f"Channel {args.channel} does not exist.")
        storage.get_l_events().remove(app.id, ch.id)
        storage.get_l_events().init(app.id, ch.id)
    else:
        storage.get_l_events().remove(app.id)
        storage.get_l_events().init(app.id)
    print(f"Deleted data of app {args.name}.")
    return 0


def cmd_channel_new(args) -> int:
    storage = _storage()
    app = storage.get_meta_data_apps().get_by_name(args.app_name)
    if app is None:
        return _die(f"App {args.app_name} does not exist.")
    cid = storage.get_meta_data_channels().insert(Channel(0, args.channel, app.id))
    if cid is None:
        return _die(
            f"Unable to create channel {args.channel} "
            "(name must match ^[a-zA-Z0-9-]{1,16}$)."
        )
    storage.get_l_events().init(app.id, cid)
    print(f"Created channel {args.channel} (ID {cid}) for app {args.app_name}.")
    return 0


def cmd_channel_delete(args) -> int:
    storage = _storage()
    app = storage.get_meta_data_apps().get_by_name(args.app_name)
    if app is None:
        return _die(f"App {args.app_name} does not exist.")
    channels = storage.get_meta_data_channels().get_by_app_id(app.id)
    ch = next((c for c in channels if c.name == args.channel), None)
    if ch is None:
        return _die(f"Channel {args.channel} does not exist.")
    if not args.force:
        return _die("Refusing to delete without --force (destructive).")
    storage.get_l_events().remove(app.id, ch.id)
    storage.get_meta_data_channels().delete(ch.id)
    print(f"Deleted channel {args.channel}.")
    return 0


def cmd_accesskey_new(args) -> int:
    storage = _storage()
    app = storage.get_meta_data_apps().get_by_name(args.app_name)
    if app is None:
        return _die(f"App {args.app_name} does not exist.")
    key = storage.get_meta_data_access_keys().insert(
        AccessKey(args.key or "", app.id, tuple(args.event or ()))
    )
    if key is None:
        return _die(f"Access key {args.key!r} already exists.")
    print(f"Created new access key: {key}")
    return 0


def cmd_accesskey_list(args) -> int:
    storage = _storage()
    keys = storage.get_meta_data_access_keys()
    if args.app_name:
        app = storage.get_meta_data_apps().get_by_name(args.app_name)
        if app is None:
            return _die(f"App {args.app_name} does not exist.")
        listing = keys.get_by_app_id(app.id)
    else:
        listing = keys.get_all()
    print(f"{'Access Key':<66} | {'App ID':>6} | Allowed Events")
    for k in listing:
        events = ",".join(k.events) if k.events else "(all)"
        print(f"{k.key:<66} | {k.appid:>6} | {events}")
    return 0


def cmd_accesskey_delete(args) -> int:
    _storage().get_meta_data_access_keys().delete(args.key)
    print(f"Deleted access key {args.key}.")
    return 0


# ---------------------------------------------------------------------------
# engine lifecycle (ref commands/Engine.scala)
# ---------------------------------------------------------------------------


def cmd_build(args) -> int:
    """No compilation step exists (Python); build = validate the engine dir
    loads and its variant parses (ref `pio build` sbt packaging)."""
    from predictionio_tpu.workflow.engine_loader import load_engine

    manifest, engine = load_engine(args.engine_dir, args.variant)
    engine.engine_params_from_variant(manifest.variant_json)
    print(f"Engine {manifest.engine_id} is ready (factory {manifest.engine_factory}).")
    return 0


def cmd_unregister(args) -> int:
    """Compatibility verb (ref ``Console.scala:172-177``). In the reference
    0.12.x the parser still accepts ``unregister`` but the dispatch has no
    case for it (engine manifests were removed when ``pio build`` stopped
    registering engines), so it falls through to the help text. Here the
    verb is accepted explicitly: there is nothing to unregister — engines
    are plain directories, never registered anywhere — and saying so beats
    dumping help."""
    print(
        "Nothing to unregister: engines are not registered. An engine is "
        f"just its directory ({args.engine_dir}); remove the directory (or "
        "its trained instances via the metadata store) instead."
    )
    return 0


def _strip_launcher_flags(argv: list[str]) -> list[str]:
    """Drop --num-hosts/--hosts (and their values) so workers don't
    recursively launch fleets."""
    out: list[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in ("--num-hosts", "--hosts"):
            skip = True
            continue
        if a.startswith("--num-hosts=") or a.startswith("--hosts="):
            continue
        out.append(a)
    return out


def cmd_train(args) -> int:
    from predictionio_tpu.controller.engine import TrainOptions
    from predictionio_tpu.parallel.distributed import maybe_initialize_distributed
    from predictionio_tpu.workflow.core_workflow import run_train
    from predictionio_tpu.workflow.engine_loader import load_engine

    if getattr(args, "follow", False) and not args.app_name:
        # fail BEFORE the (possibly hours-long) train, not after it
        return _die("pio train --follow requires --app-name")
    hosts = [h for h in (args.hosts or "").split(",") if h]
    if (args.num_hosts > 1 or hosts) and "PIO_PROCESS_ID" not in os.environ:
        # launcher role (ref Runner.runOnSpark, Runner.scala:185-334): spawn
        # one worker per host running this same train command; workers join
        # via the PIO_COORDINATOR contract and this process supervises
        from predictionio_tpu.parallel.launcher import launch_cli_multihost

        # the argv main() actually PARSED, not the process's sys.argv: a
        # programmatic main(["train", ...]) call (test harness, wrapper)
        # must not spawn workers executing the wrapper's own command line
        invocation = getattr(args, "_invocation_argv", None)
        worker_args = _strip_launcher_flags(
            invocation if invocation is not None else sys.argv[1:]
        )
        return launch_cli_multihost(
            worker_args, num_hosts=args.num_hosts, hosts=hosts or None
        )

    maybe_initialize_distributed()

    manifest, engine = load_engine(args.engine_dir, args.variant)
    engine_params = engine.engine_params_from_variant(manifest.variant_json)
    options = TrainOptions(
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
    )
    instance_id = run_train(
        engine,
        manifest,
        engine_params,
        options=options,
        batch=args.batch or "",
        registry_dir=args.registry_dir,
        keep_versions=args.keep_versions,
    )
    print(f"Training completed. Engine instance ID: {instance_id}")
    if getattr(args, "follow", False):
        # lambda-architecture handoff: the batch train just published the
        # stable; keep tailing the event store and publishing candidates
        print("Entering follow mode (speed layer)...")
        return _run_stream(args, manifest)
    return 0


def _run_stream(args, manifest) -> int:
    """Build and run the speed-layer pipeline (shared by ``pio stream``
    and ``pio train --follow``); see docs/streaming.md."""
    from predictionio_tpu.data.store.event_store import resolve_app
    from predictionio_tpu.registry import ArtifactStore
    from predictionio_tpu.stream import (
        CursorStore,
        EventTailer,
        StreamConfig,
        StreamInstruments,
        StreamPipeline,
        trainer_for_models,
    )
    from predictionio_tpu.workflow import model_io

    if not args.app_name:
        return _die("--app-name is required to tail an event store")
    storage = _storage()
    app_id, channel_id = resolve_app(storage, args.app_name, args.channel or None)
    registry_dir = args.registry_dir or os.environ.get("PIO_REGISTRY_DIR")
    store = ArtifactStore(registry_dir)
    state = store.get_state(manifest.engine_id)
    if not state.stable:
        return _die(
            f"no stable model in registry {store.base_dir} for engine "
            f"{manifest.engine_id}; run `pio train --registry-dir ...` first"
        )
    models = model_io.deserialize_models(
        store.load_blob(manifest.engine_id, state.stable)
    )
    trainer = trainer_for_models(models)
    tailer = EventTailer(
        storage.get_l_events(),
        app_id,
        channel_id,
        batch_limit=args.batch_limit,
        safety_lag_s=getattr(args, "safety_lag", 0.0),
    )
    cursors = CursorStore(getattr(args, "cursor_dir", None))
    cursor = cursors.load(app_id, channel_id)
    if cursor.position is None and not args.from_beginning:
        # fresh cursor: the stable already covers history — start at the
        # store head so only NEW events fold in (--from-beginning replays)
        head = tailer.head_position()
        if head is not None:
            cursor.seed(head)
            cursors.save(cursor)
    config = StreamConfig(
        engine_id=manifest.engine_id,
        engine_version=manifest.version,
        engine_variant=manifest.variant,
        engine_factory=manifest.engine_factory,
        mode=args.mode,
        fraction=args.fraction,
        publish_min_events=args.publish_min_events,
        interval_s=args.interval,
    )
    stage_hook = None
    if getattr(args, "notify_url", None):

        def stage_hook(version, mode, fraction, _url=args.notify_url):
            _http_json(
                f"{_url}/models/candidate",
                method="POST",
                payload={"version": version, "mode": mode, "fraction": fraction},
            )

    instruments = StreamInstruments()
    # --obs-dir: drift breaches become structured signals on the shared
    # telemetry ring (the lifecycle controller's retune sensor) plus
    # rate-limited incident bundles, instead of only a counter bump
    ring = incidents = None
    obs_dir = getattr(args, "obs_dir", None)
    if obs_dir:
        from predictionio_tpu.obs.incidents import IncidentRecorder
        from predictionio_tpu.obs.tsring import TelemetryRing

        ring = TelemetryRing(
            os.path.join(obs_dir, "telemetry"), writer_id="stream"
        )
        incidents = IncidentRecorder(
            os.path.join(obs_dir, "incidents"), metrics=instruments.registry
        )
        incidents.add_source("telemetry-ring", lambda: ring.tail(200))
    pipeline = StreamPipeline(
        tailer,
        trainer,
        cursors,
        store,
        config,
        instruments=instruments,
        stage_hook=stage_hook,
        ring=ring,
        incidents=incidents,
    )
    metrics_server = None
    if getattr(args, "metrics_port", 0):
        from predictionio_tpu.stream.pipeline import serve_metrics

        metrics_server = serve_metrics(instruments.registry, args.metrics_port)
        print(f"Metrics on http://0.0.0.0:{args.metrics_port}/metrics")
    print(
        f"Streaming app {args.app_name} (id {app_id}) -> registry "
        f"{store.base_dir} [{trainer.name}, {config.mode}@{config.fraction:g}]"
    )
    try:
        pipeline.run_forever(max_cycles=args.cycles)
    except KeyboardInterrupt:
        pass
    finally:
        if metrics_server is not None:
            metrics_server.shutdown()
    return 0


def cmd_stream(args) -> int:
    """Speed layer: tail the event store, fold events into the stable
    model incrementally, publish registry candidates continuously."""
    from predictionio_tpu.workflow.engine_loader import load_manifest

    manifest = load_manifest(args.engine_dir, args.variant)
    return _run_stream(args, manifest)


def cmd_eval(args) -> int:
    """Hyperparameter search as the evaluation grid (docs/evaluation.md):
    fold×params cells trained in parallel workers, scored through the
    offline mega-batch path, finished cells persisted to a durable ledger
    (``--resume`` retrains zero finished cells), and — with an engine
    identity and a registry — the winning refit published as a CANDIDATE
    carrying the full grid evidence, riding the same bake gates as every
    other model change."""
    import importlib
    import tempfile

    from predictionio_tpu.workflow.core_workflow import run_grid_evaluation

    # user evaluations live in the engine project's cwd (ref Console eval
    # runs from the engine dir); the installed `pio` script's sys.path[0]
    # is its bin dir, so put the cwd on the path like load_engine does for
    # engine dirs
    cwd = os.getcwd()
    if cwd not in sys.path:
        sys.path.insert(0, cwd)
    source: str = args.evaluation
    # FakeRun-style evaluations (run() but no engine/metric — the
    # `pio eval HelloWorld` dev flow, workflow/fake_workflow.py) have no
    # grid to search: keep them on the sequential parity path, which
    # also honors their no_save contract
    from predictionio_tpu.tuning.cells import resolve_evaluation

    probe = resolve_evaluation(args.evaluation)
    if (
        getattr(probe, "engine", None) is None
        or getattr(probe, "metric", None) is None
    ) and hasattr(probe, "run"):
        from predictionio_tpu.workflow.core_workflow import run_evaluation

        instance_id, result = run_evaluation(probe, batch=args.batch or "")
        print(result.one_liner())
        print(f"Evaluation instance ID: {instance_id}")
        return 0
    if args.engine_params_generator:
        # a separate generator overrides the evaluation's own params list;
        # resolve both here and hand the composed instance to the runner
        # (workers then require a self-contained evaluation path, which
        # the error below explains)
        if args.workers > 0:
            return _die(
                "an explicit engine_params_generator cannot ride to "
                "process workers (they rebuild the evaluation by its "
                "dotted path); set engine_params_generator on the "
                "Evaluation itself, or use --workers 0"
            )
        evaluation = probe
        module_name, _, attr = args.engine_params_generator.rpartition(".")
        generator = getattr(importlib.import_module(module_name), attr)
        if isinstance(generator, type):
            generator = generator()
        evaluation.engine_params_generator = generator
        source = evaluation  # type: ignore[assignment]

    engine_manifest = None
    if args.engine_dir:
        from predictionio_tpu.workflow.engine_loader import load_manifest

        engine_manifest = load_manifest(args.engine_dir, args.variant)
    registry_dir = args.registry_dir or os.environ.get("PIO_REGISTRY_DIR")
    if args.publish and args.no_publish:
        return _die("--publish and --no-publish are mutually exclusive")
    # default: publish when the pieces are in place (engine identity +
    # registry), stay quiet otherwise; --publish forces (and errors
    # loudly on missing pieces), --no-publish always wins
    publish = (
        False
        if args.no_publish
        else (args.publish or bool(engine_manifest and registry_dir))
    )
    if args.resume and not args.workdir:
        return _die(
            "--resume needs the --workdir of the run to resume "
            "(the trial ledger lives there)"
        )
    workdir = args.workdir or tempfile.mkdtemp(prefix="pio_eval_grid_")
    try:
        instance_id, report = run_grid_evaluation(
            source,
            evaluation=probe,  # already resolved above; don't rebuild
            batch=args.batch or "",
            workdir=workdir,
            workers=args.workers,
            folds=args.folds,
            resume=args.resume,
            batch_size=args.batch_size,
            publish=publish,
            registry_dir=registry_dir,
            engine_manifest=engine_manifest,
            stage_mode=args.stage_mode,
            stage_fraction=args.stage_fraction,
            status_path=args.status_file,
            cwd=cwd,
            nice=args.nice,
            worker_class=args.worker_class,
        )
    except ValueError as exc:
        return _die(str(exc))
    print(report.one_liner())
    if report.published_version:
        print(
            f"Winner published to registry as candidate "
            f"{report.published_version} (evidence: {report.cells_total} "
            f"cells, ledger sha {report.ledger_sha256[:12]})"
        )
    print(f"Trial ledger: {report.ledger_path}")
    print(f"Evaluation instance ID: {instance_id}")
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report.to_json_dict(), fh, indent=1, sort_keys=True)
        print(f"Grid report written to {args.out}")
    return 0


def cmd_deploy(args) -> int:
    from predictionio_tpu.workflow.create_server import (
        ServerConfig,
        run_query_server,
    )

    if getattr(args, "autoscale", False) and not args.fleet:
        # silently ignoring elasticity flags would leave the operator
        # believing the fleet sizes itself when nothing is running
        return _die(
            "--autoscale requires --fleet N (the autoscaler drives the "
            "fleet supervisor; docs/fleet.md §Autoscaling)"
        )
    if getattr(args, "hosts", None) and not args.fleet:
        return _die(
            "--hosts requires --fleet N (host placement is the fleet "
            "supervisor's job; docs/fleet.md §Multi-host)"
        )
    if getattr(args, "lifecycle", None) and not args.fleet:
        return _die(
            "--lifecycle requires --fleet N (the controller rides the "
            "fleet parent's obs plane; for a single server run "
            "`pio lifecycle run` alongside it; docs/lifecycle.md)"
        )
    if getattr(args, "gateways", 1) != 1 and not args.fleet:
        return _die(
            "--gateways requires --fleet N (peer gateways front the "
            "fleet's replica set; docs/fleet.md §Gateway tier)"
        )
    if args.fleet:
        # N supervised worker processes behind a gateway (docs/fleet.md):
        # the gateway takes --port, workers take port+1..port+N and get a
        # registry sync interval so rollouts propagate fleet-wide
        from predictionio_tpu.fleet.launch import run_fleet

        try:
            return run_fleet(args, sys.argv[1:])
        except ValueError as exc:
            return _die(str(exc))

    from predictionio_tpu.parallel.distributed import maybe_initialize_distributed

    maybe_initialize_distributed()
    config = ServerConfig(
        ip=args.ip,
        port=args.port,
        accesskey=args.accesskey,
        feedback=args.feedback,
        event_server_url=args.event_server_url,
        feedback_access_key=args.feedback_access_key,
        ssl_certfile=args.ssl_certfile,
        ssl_keyfile=args.ssl_keyfile,
        log_url=args.log_url,
        log_prefix=args.log_prefix or "",
        request_timeout_s=args.request_timeout,
        queue_high_water=args.queue_high_water,
        breaker_threshold=args.breaker_threshold,
        breaker_recovery_s=args.breaker_recovery,
        registry_dir=args.registry_dir,
        sticky_key_field=args.sticky_key,
        candidate_breaker_threshold=args.candidate_breaker_threshold,
        bake_window_s=args.bake_window,
        bake_min_requests=args.bake_min_requests,
        auto_promote=not args.no_auto_promote,
        result_cache_size=args.result_cache_size,
        result_cache_ttl_s=args.result_cache_ttl,
        registry_sync_interval_s=args.registry_sync_interval or 0.0,
        drain_grace_s=args.drain_grace,
        bandit_policy=args.bandit,
        bandit_epsilon=args.bandit_epsilon,
        bandit_min_pulls=args.bandit_min_pulls,
        bandit_app_name=args.bandit_app_name,
        bandit_reward_events=tuple(
            s.strip() for s in args.bandit_reward_event.split(",") if s.strip()
        )
        if args.bandit_reward_event
        else ("reward",),
    )
    print(f"Engine server starting on {args.ip}:{args.port} ...")
    run_query_server(args.engine_dir, args.variant, config=config)
    return 0


def cmd_undeploy(args) -> int:
    """POST /stop to a running engine server (ref commands/Engine.scala:244-267)."""
    import ssl
    import urllib.request

    scheme = "https" if args.ssl else "http"
    url = f"{scheme}://{args.ip}:{args.port}/stop"
    context = ssl._create_unverified_context() if args.ssl else None
    try:
        with urllib.request.urlopen(
            urllib.request.Request(url, method="POST"), timeout=10, context=context
        ) as resp:
            print(resp.read().decode())
        return 0
    except Exception as exc:
        return _die(f"undeploy failed: {exc}")


def cmd_batchpredict(args) -> int:
    """Offline mega-batch prediction (docs/batch_predict.md): stream
    queries from a file or straight off the event store, dispatch
    device-sized batches through the fused kernels (double-buffered), and
    stream the scored top-k back to a file (atomic) and/or the event
    store. Nonzero exit only when setup fails or EVERY query line failed
    — a malformed line becomes a line-aligned error object, not an
    abort."""
    from predictionio_tpu.workflow.batch_predict import run_batch_predict

    if args.from_events and args.input is not None:
        return _die("--from-events and --input are mutually exclusive")
    input_path = (
        None
        if args.from_events
        else (args.input or "batchpredict-input.json")
    )
    try:
        report = run_batch_predict(
            args.engine_dir,
            input_path,
            args.output,
            variant_path=args.variant,
            from_events=args.from_events,
            app_name=args.app_name,
            channel=args.channel,
            query_num=args.query_num,
            to_events=args.to_events,
            batch_size=args.batch,
            limit=args.limit,
            status_path=args.status_file,
        )
    except (RuntimeError, OSError) as exc:
        return _die(f"batchpredict failed: {exc}")
    sinks = ([args.output] if args.output else []) + (
        ["event store"] if args.to_events else []
    )
    print(
        f"Batch predict completed: {report.queries} queries "
        f"({report.ok} ok, {report.errors} errors) in {report.wall_s:.2f}s "
        f"({report.qps:.0f} q/s) -> {', '.join(sinks)}"
    )
    if report.all_failed:
        return _die("batch predict: every query line failed")
    return 0


# ---------------------------------------------------------------------------
# servers / status / data
# ---------------------------------------------------------------------------


def cmd_eventserver(args) -> int:
    from predictionio_tpu.data.api.event_server import (
        EventServerConfig,
        run_event_server,
    )

    print(f"Event server starting on {args.ip}:{args.port} ...")
    run_event_server(
        EventServerConfig(
            ip=args.ip,
            port=args.port,
            stats=args.stats,
            ssl_certfile=args.ssl_certfile,
            ssl_keyfile=args.ssl_keyfile,
            storage_retries=args.storage_retries,
            breaker_threshold=args.breaker_threshold,
            breaker_recovery_s=args.breaker_recovery,
        )
    )
    return 0


def cmd_adminserver(args) -> int:
    from predictionio_tpu.tools.admin_api import run_admin_server

    print(f"Admin server starting on {args.ip}:{args.port} ...")
    run_admin_server(args.ip, args.port, registry_dir=args.registry_dir)
    return 0


def cmd_dashboard(args) -> int:
    from predictionio_tpu.tools.dashboard import run_dashboard

    print(f"Dashboard starting on {args.ip}:{args.port} ...")
    run_dashboard(args.ip, args.port, metrics_urls=args.metrics_url or ())
    return 0


_TOP_DEFAULT_URL = "http://127.0.0.1:8000"


def cmd_top(args) -> int:
    """Live one-screen summary of a running server's /metrics (qps, p95,
    waterfall, SLO burn, shed rate, breaker states, recompile count).
    ``--fleet`` points it at a fleet gateway's federated /metrics (the
    fleet line renders automatically when pio_fleet_* metrics exist);
    repeated ``--metrics-url`` polls several endpoints per refresh —
    with ``--json``, one object per endpoint per refresh. ``--history``
    renders the telemetry ring's queue-depth/burn series instead: from
    the gateway's ``/telemetry/window`` endpoint, or straight off the
    on-disk ring (``--obs-dir``) when the gateway is down."""
    from predictionio_tpu.tools.top import (
        run_batchpredict_top,
        run_evalgrid_top,
        run_history,
        run_lifecycle_top,
        run_top,
    )

    if getattr(args, "lifecycle", None):
        return run_lifecycle_top(
            args.lifecycle,
            interval_s=args.interval,
            iterations=1 if args.once else args.iterations,
            json_mode=args.json,
        )
    if args.eval:
        return run_evalgrid_top(
            args.eval,
            interval_s=args.interval,
            iterations=1 if args.once else args.iterations,
            json_mode=args.json,
        )
    if args.batchpredict:
        return run_batchpredict_top(
            args.batchpredict,
            interval_s=args.interval,
            iterations=1 if args.once else args.iterations,
            json_mode=args.json,
        )
    if args.history:
        url = args.url if (args.fleet or args.url != _TOP_DEFAULT_URL) else None
        if args.obs_dir is None and url is None:
            url = args.url  # default gateway address is still worth a try
        return run_history(
            url=url,
            obs_dir=args.obs_dir,
            window_s=args.history_window,
            json_mode=args.json,
        )
    iterations = 1 if args.once else args.iterations
    # --metrics-url endpoints poll IN ADDITION to a --url the operator
    # actually pointed somewhere (the flag's "too"): replicas scrape
    # directly alongside the gateway's federated view, which stays first
    # in the refresh. An untouched default --url is not silently polled.
    urls = list(args.metrics_url or [])
    url_given = args.fleet or args.url != _TOP_DEFAULT_URL
    if urls and url_given and args.url not in urls:
        urls.insert(0, args.url)
    elif args.fleet and not urls:
        urls = [args.url]  # the gateway IS the fleet view
    return run_top(
        args.url,
        interval_s=args.interval,
        iterations=iterations,
        clear_screen=False if args.once else None,
        json_mode=args.json,
        urls=urls or None,
        hotspots=args.hotspots,
    )


def _lifecycle_state_dir(args) -> str:
    return args.state_dir or os.path.join(args.obs_dir, "lifecycle")


def cmd_lifecycle_run(args) -> int:
    """The standalone lifecycle controller (docs/lifecycle.md): watch the
    obs dir's telemetry ring for drift signals (plus cadence/manual
    triggers), retune on background cpu-fallback grid workers, stage the
    winner, watch the bake, warm the cache on promote. `pio deploy
    --fleet N --lifecycle` embeds the same loop in the fleet parent; this
    command runs it against an already-running server."""
    import asyncio

    from predictionio_tpu.lifecycle import (
        LifecycleConfig,
        LifecycleController,
        LifecyclePolicy,
        build_grid_tuner,
        build_warmer,
    )
    from predictionio_tpu.lifecycle.warm import event_store_queries
    from predictionio_tpu.obs.incidents import IncidentRecorder
    from predictionio_tpu.obs.tsring import TelemetryRing
    from predictionio_tpu.registry import registry_rollout_probe
    from predictionio_tpu.workflow.engine_loader import load_manifest

    manifest = load_manifest(args.engine_dir, args.variant)
    registry_dir = args.registry_dir or os.environ.get("PIO_REGISTRY_DIR")
    if not registry_dir:
        return _die(
            "the lifecycle controller needs a registry "
            "(--registry-dir or $PIO_REGISTRY_DIR)"
        )
    state_dir = _lifecycle_state_dir(args)
    config = LifecycleConfig(
        cadence_s=args.cadence,
        drift_window_s=args.drift_window,
        min_drift_records=args.min_drift_records,
        cooldown_s=args.cooldown,
        tune_timeout_s=args.tune_timeout,
        bake_timeout_s=args.bake_timeout,
        tick_interval_s=args.tick_interval,
        warm_limit=args.warm_limit,
    )
    ring = TelemetryRing(
        os.path.join(args.obs_dir, "telemetry"), writer_id="lifecycle"
    )
    incidents = IncidentRecorder(os.path.join(args.obs_dir, "incidents"))
    incidents.add_source("telemetry-ring", lambda: ring.tail(200))
    cwd = os.getcwd()
    if cwd not in sys.path:
        sys.path.insert(0, cwd)
    tuner = build_grid_tuner(
        args.evaluation,
        workdir=args.workdir or os.path.join(state_dir, "grid"),
        engine_manifest=manifest,
        registry_dir=registry_dir,
        workers=args.workers,
        nice=args.nice,
        folds=args.folds,
        stage_mode=args.stage_mode,
        stage_fraction=args.stage_fraction,
        cwd=cwd,
        env={k: v for k, v in os.environ.items() if k.startswith("PIO_")},
    )
    warmer = None
    if args.serve_url and args.app_name:
        from predictionio_tpu.data.store.event_store import resolve_app

        storage = _storage()
        app_id, _ = resolve_app(storage, args.app_name, None)
        warmer = build_warmer(
            args.serve_url,
            lambda: event_store_queries(
                storage, app_id, limit=args.warm_limit
            ),
            limit=args.warm_limit,
        )
    controller = LifecycleController(
        LifecyclePolicy(config),
        state_dir=state_dir,
        engine_id=manifest.engine_id,
        registry_dir=registry_dir,
        tune=tuner,
        warm=warmer,
        rollout_probe=registry_rollout_probe(registry_dir),
        ring=ring,
        incidents=incidents,
    )
    print(
        f"Lifecycle controller for {manifest.engine_id}: state {state_dir}, "
        f"registry {registry_dir}, "
        f"triggers {'cadence %gs' % args.cadence if args.cadence else 'drift/manual'}"
    )
    try:
        asyncio.run(controller.run())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_lifecycle_status(args) -> int:
    """One status line (or JSON) from the controller's durable state
    file; works whether or not the controller is alive — the file is the
    interface, exactly like `pio top --lifecycle`."""
    from predictionio_tpu.lifecycle import read_json_file
    from predictionio_tpu.lifecycle.controller import STATE_FILE
    from predictionio_tpu.tools.top import render_lifecycle

    path = os.path.join(_lifecycle_state_dir(args), STATE_FILE)
    status = read_json_file(path)
    if status is None:
        return _die(
            f"no lifecycle state at {path} (is a controller running with "
            "this --obs-dir/--state-dir?)"
        )
    if args.json:
        print(json.dumps(status, indent=2, sort_keys=True))
    else:
        print(render_lifecycle(status))
    return 0


def cmd_lifecycle_trigger(args) -> int:
    """Queue one manual retune: bumps the control file's trigger token;
    the controller consumes it on its next tick (bypassing cooldown —
    an operator asked — but never an in-flight episode or a live bake)."""
    from predictionio_tpu.lifecycle import write_control

    data = write_control(_lifecycle_state_dir(args), trigger=True)
    print(
        f"Retune queued (trigger token {data['trigger']}); the controller "
        "starts it on its next tick unless an episode is already running."
    )
    return 0


def cmd_lifecycle_pause(args) -> int:
    """Flip automatic triggers off/on. Pause stops NEW episodes only —
    an in-flight grid, bake, or warm always runs to its outcome (killing
    half-applied lifecycle work is how registries end up wedged)."""
    from predictionio_tpu.lifecycle import write_control

    paused = args.subcommand == "pause"
    write_control(_lifecycle_state_dir(args), paused=paused)
    print(
        "Lifecycle paused (automatic triggers off; `pio lifecycle resume` "
        "re-enables, manual `trigger` still works)."
        if paused
        else "Lifecycle resumed (automatic triggers back on)."
    )
    return 0


def _incidents_dir(args) -> str:
    return os.path.join(args.obs_dir, "incidents")


def cmd_incidents_list(args) -> int:
    """Incident bundles captured by the fleet flight recorder
    (docs/observability.md §Incident flight recorder)."""
    from predictionio_tpu.obs.incidents import list_bundles

    refs = list_bundles(_incidents_dir(args))
    if not refs:
        print(
            f"No incident bundles under {_incidents_dir(args)} "
            "(fleet deploys write them on worker crash / breaker trip / "
            "SLO alert; --obs-dir points elsewhere)"
        )
        return 0
    print(f"Incidents: {_incidents_dir(args)}")
    print(f"{'Bundle':<30} | {'Trigger':<14} | Captured")
    import time as _time

    for ref in refs:
        when = _time.strftime(
            "%Y-%m-%d %H:%M:%S", _time.localtime(ref.captured_at)
        )
        print(f"{ref.bundle_id:<30} | {ref.trigger:<14} | {when}")
    return 0


def cmd_incidents_show(args) -> int:
    from predictionio_tpu.obs.incidents import load_bundle

    try:
        bundle = load_bundle(_incidents_dir(args), args.bundle)
    except (FileNotFoundError, ValueError) as exc:
        return _die(str(exc))
    if args.json:
        print(json.dumps(bundle, indent=2, sort_keys=True, default=repr))
        return 0
    manifest = bundle["manifest"]
    print(f"trigger   {manifest.get('trigger')}")
    print(f"captured  {manifest.get('capturedAt')}")
    print(f"sha256    {manifest.get('sha256')}")
    context = manifest.get("context") or {}
    if context:
        print("context   " + json.dumps(context, sort_keys=True))
    for name, part in sorted(bundle["parts"].items()):
        size = len(json.dumps(part))
        print(f"part      {name}.json ({size} bytes)")
    for name, text in sorted(bundle["texts"].items()):
        print(f"text      {name}.txt ({len(text)} bytes)")
        n = max(0, args.tail_lines)
        tail = text.strip().splitlines()[-n:] if n else []
        for line in tail:
            print(f"  | {line}")
    return 0


def cmd_incidents_export(args) -> int:
    from predictionio_tpu.obs.incidents import export_bundle

    try:
        dest = export_bundle(_incidents_dir(args), args.bundle, args.dest)
    except (FileNotFoundError, ValueError, OSError) as exc:
        return _die(str(exc))
    print(f"Exported to {dest}")
    return 0


def _profile_dir(args) -> str:
    # CLI flag > PIO_PROFILE_DIR (the training compat alias) > the
    # serving default (ServerConfig.profile_dir)
    return (
        args.profile_dir
        or os.environ.get("PIO_PROFILE_DIR")
        or "pio_obs/profiles"
    )


def cmd_profile_serve(args) -> int:
    """Trigger an on-demand device capture on a RUNNING server (query,
    event, or fleet gateway — the gateway fans out to one replica):
    ``POST /profile/capture?ms=``. The bundle lands in the server's own
    profile store; inspect it with ``pio profile list/show`` against
    that directory."""
    import urllib.error
    import urllib.request

    target = args.url.rstrip("/") + f"/profile/capture?ms={args.ms}"
    req = urllib.request.Request(target, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            body = json.loads(resp.read().decode("utf-8", errors="replace"))
    except urllib.error.HTTPError as exc:
        detail = exc.read().decode("utf-8", errors="replace")[:400]
        if exc.code == 409:
            return _die(f"capture already in flight on {args.url}: {detail}")
        return _die(f"capture failed ({exc.code}): {detail}")
    except Exception as exc:  # noqa: BLE001 - network errors -> one line
        return _die(f"server unreachable at {args.url}: {exc}")
    print(json.dumps(body, indent=2, sort_keys=True))
    return 0


def cmd_profile_train(args) -> int:
    """Train under the device tracer: sets ``PIO_PROFILE_DIR`` (the
    compatibility gate `obs.profiler.maybe_profile_train` honors) and
    re-invokes ``pio train`` with the remaining arguments; the trace
    lands as a content-addressed bundle under the profile dir."""
    rest = list(args.train_args)
    if rest and rest[0] == "--":
        rest = rest[1:]
    os.environ["PIO_PROFILE_DIR"] = _profile_dir(args)
    return main(["train", *rest])


def cmd_profile_list(args) -> int:
    """Profile bundles (same content-addressed grammar as incident
    bundles; docs/observability.md §Profiling plane)."""
    from predictionio_tpu.obs.incidents import list_bundles

    directory = _profile_dir(args)
    refs = list_bundles(directory)
    if not refs:
        print(
            f"No profile bundles under {directory} "
            "(POST /profile/capture, `pio profile serve|train`, or "
            "profile-on-alert write them; --profile-dir points elsewhere)"
        )
        return 0
    print(f"Profiles: {directory}")
    print(f"{'Bundle':<30} | {'Trigger':<14} | Captured")
    import time as _time

    for ref in refs:
        when = _time.strftime(
            "%Y-%m-%d %H:%M:%S", _time.localtime(ref.captured_at)
        )
        print(f"{ref.bundle_id:<30} | {ref.trigger:<14} | {when}")
    return 0


def cmd_profile_show(args) -> int:
    from predictionio_tpu.obs.incidents import load_bundle

    directory = _profile_dir(args)
    try:
        bundle = load_bundle(directory, args.bundle)
    except (FileNotFoundError, ValueError) as exc:
        return _die(str(exc))
    if args.json:
        print(json.dumps(bundle, indent=2, sort_keys=True, default=repr))
        return 0
    manifest = bundle["manifest"]
    print(f"trigger   {manifest.get('trigger')}")
    print(f"captured  {manifest.get('capturedAt')}")
    print(f"sha256    {manifest.get('sha256')}")
    context = manifest.get("context") or {}
    if context:
        print("context   " + json.dumps(context, sort_keys=True))
    for name, part in sorted(bundle["parts"].items()):
        size = len(json.dumps(part))
        print(f"part      {name}.json ({size} bytes)")
    for name, text in sorted(bundle["texts"].items()):
        print(f"text      {name}.txt ({len(text)} bytes)")
    for entry in manifest.get("trace") or []:
        print(
            f"trace     {entry.get('name')} ({entry.get('bytes')} bytes, "
            f"sha256 {str(entry.get('sha256'))[:12]})"
        )
    return 0


def cmd_profile_export(args) -> int:
    from predictionio_tpu.obs.incidents import export_bundle

    try:
        dest = export_bundle(_profile_dir(args), args.bundle, args.dest)
    except (FileNotFoundError, ValueError, OSError) as exc:
        return _die(str(exc))
    print(f"Exported to {dest}")
    return 0


def cmd_status(args) -> int:
    """ref commands/Management.status + Storage.verifyAllDataObjects."""
    print(f"predictionio_tpu {predictionio_tpu.__version__}")
    try:
        storage = _storage()
    except Exception as exc:
        return _die(f"storage configuration invalid: {exc}")
    failures = storage.verify_all_data_objects()
    if failures:
        for f in failures:
            print(f"  [FAILED] {f}")
        return _die("storage verification failed")
    print("  storage: all data objects verified")
    # the device probe runs in a BOUNDED subprocess: a wedged TPU-tunnel
    # plugin hangs device init forever (observed in the wild), and `pio
    # status` must report that, not inherit it. 45s covers a healthy cold
    # tunnel's ~40s first contact.
    import subprocess

    pkg_root = os.path.dirname(os.path.dirname(predictionio_tpu.__file__))
    probe_env = {
        **os.environ,
        "PYTHONPATH": pkg_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    try:
        probe = subprocess.run(
            [
                sys.executable,
                "-c",
                # honor an explicit JAX_PLATFORMS=cpu even here: the probe
                # exists to DETECT a wedged plugin, not to hang on it when
                # the user asked for CPU
                "from predictionio_tpu.utils.platform import "
                "ensure_cpu_if_requested; ensure_cpu_if_requested(); "
                "import jax; print('PIO-JAX', jax.__version__, "
                "jax.device_count())",
            ],
            capture_output=True,
            timeout=45,
            text=True,
            env=probe_env,
        )
        # a plugin/sitecustomize may print banners around the probe line:
        # find OUR marker instead of assuming clean stdout
        marker = next(
            (
                ln.split()
                for ln in probe.stdout.splitlines()
                if ln.startswith("PIO-JAX ")
            ),
            None,
        )
        if probe.returncode == 0 and marker and len(marker) == 3:
            print(f"  jax {marker[1]}; devices: {marker[2]}")
        else:
            err = probe.stderr.strip().splitlines()
            print(f"  jax devices unavailable: {err[-1] if err else 'unknown'}")
    except subprocess.TimeoutExpired:
        print(
            "  jax devices unavailable: device init timed out after 45s "
            "(wedged accelerator tunnel?)"
        )
    except Exception as exc:  # noqa: BLE001 - status must never crash here
        print(f"  jax devices unavailable: {exc}")
    print("(sleeping)   <- your engine is ready to train")
    return 0


def _parse_bytes(text: str) -> int:
    """'16e9', '16000000000', '16GB', '16GiB' -> bytes."""
    t = text.strip().lower()
    for suffix, mult in (
        ("gib", 1 << 30), ("mib", 1 << 20), ("kib", 1 << 10),
        ("gb", 10**9), ("mb", 10**6), ("kb", 10**3), ("b", 1),
    ):
        if t.endswith(suffix):
            return int(float(t[: -len(suffix)]) * mult)
    return int(float(t))


def _doctor_roofline(args) -> int:
    """``pio doctor --roofline``: the device-free roofline — lower and
    compile every registered jit bucket family, read XLA's own
    ``cost_analysis()`` flops/bytes into arithmetic intensity and a
    per-model device cost per 1k queries (obs/costmodel). Runs on the
    CPU backend; exits nonzero only when NO family produced numbers."""
    from predictionio_tpu.obs import costmodel

    families = (
        [f.strip() for f in args.families.split(",") if f.strip()]
        if getattr(args, "families", None)
        else None
    )
    try:
        report = costmodel.analyze(
            families=families,
            device=args.device or costmodel.DEFAULT_DEVICE,
        )
    except ValueError as exc:
        return _die(str(exc))
    print(json.dumps(report, indent=2, sort_keys=True))
    if not report["families"]:
        return _die("no bucket family produced cost numbers", code=1)
    return 0


def cmd_doctor(args) -> int:
    """Preflight diagnostics. ``--capacity USERS ITEMS K`` runs the HBM
    capacity planner (obs/xray.estimate_factors): will this ALS train fit
    per-device HBM? ``--ann "clusters,nprobe"`` prices a serving-side ANN
    index for the same corpus next to the factor tables (the budget check
    then gates the sum). Exits nonzero when the estimate exceeds
    ``--hbm-bytes`` — ROADMAP item 1's memory target as a gate instead of
    an OOM. Without ``--capacity``: device inventory + live memory + any
    ANN indexes pinned in the registry."""
    from predictionio_tpu.obs import xray

    if getattr(args, "roofline", False):
        return _doctor_roofline(args)
    if getattr(args, "ann", None) and not args.capacity:
        return _die("--ann needs --capacity USERS ITEMS K (ITEMS and K size the index)")
    if args.capacity:
        users, items, k = (int(v) for v in args.capacity)
        est = xray.estimate_factors(
            users,
            items,
            k,
            dtype=args.dtype,
            mesh=args.mesh or None,
            nnz=args.nnz,
            gather_dtype=args.gather_dtype,
        )
        budget = _parse_bytes(args.hbm_bytes) if args.hbm_bytes else None
        need = est.per_device_bytes
        ann_est = None
        if getattr(args, "ann", None):
            try:
                clusters_s, _, nprobe_s = args.ann.partition(",")
                clusters, nprobe = int(clusters_s or 0), int(nprobe_s or 0)
            except ValueError:
                return _die(
                    f"--ann expects 'clusters,nprobe' (0 = auto), got {args.ann!r}"
                )
            ann_est = xray.estimate_ann(
                items,
                k,
                clusters,
                nprobe,
                quantize_int8=bool(getattr(args, "ann_int8", False)),
            )
            need += ann_est["perDeviceBytes"]
        out = {
            "capacity": est.to_json_dict(),
            "ann": ann_est,
            "perDeviceBytesTotal": need,
            "hbmBudgetBytes": budget,
            "fits": (need <= budget) if budget is not None else None,
        }
        print(json.dumps(out, indent=2))
        if budget is not None:
            gb = need / 1e9
            if need > budget:
                print(
                    f"EXCEEDS BUDGET: {gb:.2f} GB/device needed vs "
                    f"{budget / 1e9:.2f} GB budget — shard wider (--mesh), "
                    f"lower k, bf16 the tables"
                    + (
                        ", or --ann-int8 / fewer clusters for the index"
                        if ann_est
                        else ""
                    ),
                    file=sys.stderr,
                )
                return 1
            print(
                f"fits: {gb:.2f} GB/device of {budget / 1e9:.2f} GB budget "
                f"({100.0 * need / budget:.1f}%)"
            )
        return 0
    # inventory mode: what does this host actually have
    try:
        import jax

        devices = jax.local_devices()
        print(f"backend: {jax.default_backend()}  devices: {len(devices)}")
        per = xray.live_bytes_per_device()
        for d in devices:
            stats = getattr(d, "memory_stats", lambda: None)() or {}
            live = per.get(str(d), 0)
            line = f"  {d}  live {live} B"
            if stats:
                line += (
                    f"  in_use {stats.get('bytes_in_use', 0)}"
                    f"  peak {stats.get('peak_bytes_in_use', 0)}"
                    f"  limit {stats.get('bytes_limit', 0)}"
                )
            print(line)
    except Exception as exc:  # noqa: BLE001 - doctor reports, never crashes
        print(f"devices unavailable: {exc}")
    _doctor_ann_inventory(getattr(args, "registry_dir", None))
    return 0


def _doctor_ann_inventory(registry_dir: str | None) -> None:
    """List every ANN index pinned on a registry-stable version — the
    'what retrieval indexes are live' half of the inventory."""
    import os as _os

    registry_dir = registry_dir or _os.environ.get("PIO_REGISTRY_DIR")
    if not registry_dir or not _os.path.isdir(registry_dir):
        return
    try:
        from predictionio_tpu.registry import ArtifactStore

        store = ArtifactStore(registry_dir)
        lines = []
        for key in store.engines():
            state = store.state_by_key(key)
            if not state.stable:
                continue
            versions = {m.version: m for m in store.versions_by_key(key)}
            manifest = versions.get(state.stable)
            if manifest is None or not manifest.ann_index:
                continue
            a = manifest.ann_index
            lines.append(
                f"  {key} {state.stable}: {a.get('items', '?')} items, "
                f"{a.get('clusters', '?')} clusters x cap "
                f"{a.get('bucketCap', '?')}, nprobe {a.get('nprobe', '?')}, "
                f"{a.get('hbmBytes', 0)} B"
                + (" (int8)" if a.get("quantized") else "")
            )
        if lines:
            print("ann indexes (registry-pinned stable):")
            for line in lines:
                print(line)
    except Exception as exc:  # noqa: BLE001 - doctor reports, never crashes
        print(f"ann inventory unavailable: {exc}")


def cmd_import(args) -> int:
    from predictionio_tpu.tools.import_export import import_events

    try:
        n = import_events(args.input, args.app_name, args.channel)
    except (OSError, ValueError) as exc:
        # surface the underlying parse/storage error (file:line: cause), not
        # a bare nonzero exit — operators need to know WHICH line was bad
        return _die(f"import failed: {exc}")
    print(f"Imported {n} events.")
    return 0


def cmd_export(args) -> int:
    from predictionio_tpu.tools.import_export import export_events

    n = export_events(args.output, args.app_name, args.channel, format=args.format)
    print(f"Exported {n} events.")
    return 0


# ---------------------------------------------------------------------------
# model registry (docs/model_registry.md)
# ---------------------------------------------------------------------------


def _models_store(args):
    from predictionio_tpu.registry import ArtifactStore

    return ArtifactStore(getattr(args, "registry_dir", None) or None)


def _models_engine_id(args) -> str:
    if getattr(args, "engine_id", None):
        return args.engine_id
    from predictionio_tpu.workflow.engine_loader import load_manifest

    return load_manifest(args.engine_dir, args.variant).engine_id


def _http_json(url: str, method: str = "GET", payload=None, timeout: float = 10.0):
    import urllib.error
    import urllib.request

    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, method=method, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        body = exc.read().decode(errors="replace")
        try:
            message = json.loads(body).get("message", body)
        except ValueError:
            message = body
        raise RuntimeError(f"{method} {url} -> {exc.code}: {message}") from exc


def cmd_models_list(args) -> int:
    store = _models_store(args)
    engine_id = _models_engine_id(args)
    state = store.get_state(engine_id)
    versions = store.list_versions(engine_id)
    if not versions:
        print(
            f"No versions in registry {store.base_dir} for engine "
            f"{engine_id} (key {store.engine_key(engine_id)}). "
            "Train with PIO_REGISTRY_DIR set (or pio train --registry-dir)."
        )
        return 0
    print(f"Registry: {store.base_dir} (engine key {store.engine_key(engine_id)})")
    print(f"{'Version':<10} | {'Role':<10} | {'Created':<26} | {'Bytes':>9} | Instance")
    for m in versions:
        role = ""
        if m.version == state.stable:
            role = "stable"
        elif m.version == state.candidate:
            role = f"candidate ({state.mode} {state.fraction:g})"
        created = (m.created_at or "")[:26]
        print(f"{m.version:<10} | {role:<10} | {created:<26} | {m.blob_size:>9} | {m.instance_id}")
    return 0


def cmd_models_show(args) -> int:
    if args.url:
        data = _http_json(f"{args.url}/models")
        if not args.version:
            print(json.dumps(data, indent=2))
            return 0
        # a positional version narrows to THAT version (and errors when
        # the server doesn't know it) instead of dumping unrelated state
        out = {"version": args.version}
        for role in ("stable", "candidate"):
            lane = data.get(role)
            if lane and lane.get("version") == args.version:
                out["role"] = role
                out["live"] = lane
        registry_row = next(
            (
                v
                for v in (data.get("registry") or {}).get("versions", ())
                if v.get("version") == args.version
            ),
            None,
        )
        if registry_row is not None:
            out["registry"] = registry_row
        if "live" not in out and registry_row is None:
            return _die(
                f"version {args.version} is not known to the server at "
                f"{args.url}"
            )
        print(json.dumps(out, indent=2))
        return 0
    store = _models_store(args)
    engine_id = _models_engine_id(args)
    state = store.get_state(engine_id)
    version = args.version or state.stable
    if not version:
        return _die("no version given and no stable recorded; see `pio models list`")
    manifest = store.get_manifest(engine_id, version)
    if manifest is None:
        return _die(f"unknown version {version}; see `pio models list`")
    print(
        json.dumps(
            {"manifest": manifest.to_json_dict(), "rollout": state.to_json_dict()},
            indent=2,
        )
    )
    return 0


def cmd_models_promote(args) -> int:
    if args.url:
        # an explicit version is sent as a guard: the server refuses (409)
        # if it isn't the staged candidate, instead of promoting whatever
        # happens to be staged
        payload = {"version": args.version} if args.version else {}
        out = _http_json(f"{args.url}/models/promote", method="POST", payload=payload)
        print(f"Promoted {out.get('version')} (instance {out.get('instanceId')}).")
        return 0
    store = _models_store(args)
    engine_id = _models_engine_id(args)
    state = store.promote(engine_id, args.version or None)
    print(f"Promoted {state.stable} to stable (previous: {state.previous_stable or '-'}).")
    return 0


def cmd_models_rollback(args) -> int:
    if args.url:
        out = _http_json(f"{args.url}/models/rollback", method="POST", payload={})
        print(f"Rolled back candidate {out.get('version')}.")
        return 0
    store = _models_store(args)
    engine_id = _models_engine_id(args)
    state = store.rollback(engine_id, reason="manual (cli)")
    print(f"Rolled back; stable is {state.stable or '-'}.")
    return 0


def cmd_models_stage(args) -> int:
    """Stage a candidate on a RUNNING server (sticky canary or shadow)."""
    out = _http_json(
        f"{args.url}/models/candidate",
        method="POST",
        payload={
            "version": args.version,
            "mode": args.mode,
            "fraction": args.fraction,
        },
    )
    print(
        f"Staged {out.get('version')} as {out.get('mode')} candidate "
        f"(fraction {out.get('fraction')})."
    )
    return 0


def _profile_delta_lines(label_a, label_b, pa: dict, pb: dict) -> list[str]:
    """Human train-profile comparison: wall clock, device share, memory —
    "did this version get slower or bigger to train" at a glance."""

    def fmt_delta(va, vb, unit=""):
        if not isinstance(va, (int, float)) or not isinstance(vb, (int, float)):
            return f"{va} -> {vb}"
        pct = f" ({(vb - va) / va * 100.0:+.1f}%)" if va else ""
        return f"{va:g}{unit} -> {vb:g}{unit}{pct}"

    lines = [f"train_profile ({label_a} -> {label_b}):"]
    rows = (
        ("wall clock", "wallClockS", "s"),
        ("device time", "deviceS", "s"),
        ("steps", "steps", ""),
        ("rows/s", "rowsPerS", ""),
    )
    for title, key, unit in rows:
        va, vb = pa.get(key), pb.get(key)
        if va is not None or vb is not None:
            lines.append(f"  {title}: {fmt_delta(va, vb, unit)}")
    ma = (pa.get("memory") or {}).get("peakBytesPerDevice")
    mb = (pb.get("memory") or {}).get("peakBytesPerDevice")
    if ma is not None or mb is not None:
        lines.append(f"  peak bytes/device: {fmt_delta(ma, mb, ' B')}")
    return lines


def cmd_models_diff(args) -> int:
    store = _models_store(args)
    engine_id = _models_engine_id(args)
    a = store.get_manifest(engine_id, args.version_a)
    b = store.get_manifest(engine_id, args.version_b)
    if a is None or b is None:
        missing = args.version_a if a is None else args.version_b
        return _die(f"unknown version {missing}; see `pio models list`")
    da, db = a.to_json_dict(), b.to_json_dict()
    # the train profiles are compared as a wall/memory delta, not dumped
    # raw (a step timeline in a field diff is unreadable); strip the copy
    # embedded under data_span.stream for the same reason
    pa, pb = da.pop("train_profile", None) or {}, db.pop("train_profile", None) or {}
    for d in (da, db):
        stream = d.get("data_span", {}).get("stream")
        if isinstance(stream, dict):
            stream.pop("profile", None)
    same = True
    for key in sorted(set(da) | set(db)):
        va, vb = da.get(key), db.get(key)
        if va != vb:
            same = False
            print(f"{key}:")
            print(f"  - {args.version_a}: {va}")
            print(f"  + {args.version_b}: {vb}")
    if pa or pb:
        for line in _profile_delta_lines(args.version_a, args.version_b, pa, pb):
            print(line)
        if pa != pb:
            same = False
    if same:
        print(f"{args.version_a} and {args.version_b} are identical.")
    elif a.params_hash == b.params_hash:
        print("(same engine params; differs only in data/lineage)")
    return 0


# ---------------------------------------------------------------------------
# templates (ref commands/Template.scala — gallery replaced by bundled dirs)
# ---------------------------------------------------------------------------

BUNDLED_TEMPLATES = (
    "recommendation",
    "similarproduct",
    "classification",
    "ecommerce",
    "twotower",
    "sequential",
)


def cmd_template_list(args) -> int:
    base = os.path.dirname(
        os.path.abspath(sys.modules["predictionio_tpu"].__file__)
    )
    for name in BUNDLED_TEMPLATES:
        path = os.path.join(base, "models", name)
        marker = "" if os.path.isdir(path) else " (planned)"
        print(f"  {name}{marker}")
    return 0


def cmd_template_get(args) -> int:
    """Copy a bundled template's engine.json (+ optional scaffold) into a new
    engine dir the user can customize."""
    base = os.path.dirname(os.path.abspath(sys.modules["predictionio_tpu"].__file__))
    src = os.path.join(base, "models", args.name)
    if not os.path.isdir(src):
        return _die(f"unknown template {args.name}; see `template list`")
    dst = args.directory or args.name
    if os.path.exists(dst) and os.listdir(dst):
        return _die(f"directory {dst} exists and is not empty")
    os.makedirs(dst, exist_ok=True)
    shutil.copy(os.path.join(src, "engine.json"), os.path.join(dst, "engine.json"))
    with open(os.path.join(dst, "template.json"), "w") as f:
        json.dump({"pio": {"version": {"min": "0.1.0"}}}, f)
    print(f"Engine template {args.name} created at {dst}/")
    print("Edit engine.json (appName, algorithm params) and run `pio train`.")
    return 0


def cmd_run(args) -> int:
    """Run an arbitrary python main with the framework importable
    (ref `pio run` spark-submit of a custom main)."""
    import runpy

    sys.argv = [args.main] + (args.args or [])
    runpy.run_path(args.main, run_name="__main__")
    return 0


def cmd_upgrade(args) -> int:
    """Storage-format migration check (ref Console.scala 'upgrade' — the
    reference migrates 0.8.x HBase layouts; here every backend is verified
    and its content stamp reported so operators can confirm compatibility
    after a framework update)."""
    storage = _storage()
    errors = storage.verify_all_data_objects()
    if errors:
        for e in errors:
            print(f"[ERROR] {e}")
        return 1
    print("All storage repositories verified; data formats are current.")
    try:
        stamp = storage.get_p_events().store_identity()
        if stamp:
            print(f"Event store identity: {stamp}")
    except Exception:
        pass
    print("No migration necessary.")
    return 0


def cmd_lint(args) -> int:
    return run_lint(args)


def cmd_version(args) -> int:
    print(predictionio_tpu.__version__)
    return 0


def cmd_shell(args) -> int:
    from predictionio_tpu.tools.shell import run_shell

    run_shell()
    return 0


def _pidfile_dir() -> str:
    base = os.environ.get(
        "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".pio_store")
    )
    os.makedirs(base, exist_ok=True)
    return base


def cmd_start_all(args) -> int:
    """Start event server + admin server + dashboard as background processes
    (ref bin/pio-start-all)."""
    import subprocess

    pidfile = os.path.join(_pidfile_dir(), "pio-services.pid")
    if os.path.exists(pidfile):
        return _die(f"{pidfile} exists; run stop-all first")
    specs = [
        ("eventserver", ["eventserver", "--port", str(args.eventserver_port)]),
        ("adminserver", ["adminserver", "--port", str(args.adminserver_port)]),
        ("dashboard", ["dashboard", "--port", str(args.dashboard_port)]),
    ]
    pids = []
    for name, argv in specs:
        proc = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.tools.cli", *argv],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        pids.append(f"{name}:{proc.pid}")
        print(f"started {name} (pid {proc.pid})")
    with open(pidfile, "w") as f:
        f.write("\n".join(pids))
    return 0


def cmd_stop_all(args) -> int:
    """Stop services started by start-all (ref bin/pio-stop-all)."""
    import signal

    pidfile = os.path.join(_pidfile_dir(), "pio-services.pid")
    if not os.path.exists(pidfile):
        return _die("no pio-services.pid; nothing to stop")
    with open(pidfile) as f:
        entries = [l.strip() for l in f if l.strip()]
    for entry in entries:
        name, _, pid = entry.partition(":")
        try:
            os.kill(int(pid), signal.SIGTERM)
            print(f"stopped {name} (pid {pid})")
        except ProcessLookupError:
            print(f"{name} (pid {pid}) already gone")
    os.remove(pidfile)
    return 0


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio",
        description="TPU-native PredictionIO-class ML framework console",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version").set_defaults(fn=cmd_version)
    sub.add_parser(
        "upgrade", help="verify storage formats after a framework update"
    ).set_defaults(fn=cmd_upgrade)
    sub.add_parser("status").set_defaults(fn=cmd_status)
    sub.add_parser("shell").set_defaults(fn=cmd_shell)

    x = sub.add_parser("start-all")
    x.add_argument("--eventserver-port", type=int, default=7070)
    x.add_argument("--adminserver-port", type=int, default=7071)
    x.add_argument("--dashboard-port", type=int, default=9000)
    x.set_defaults(fn=cmd_start_all)
    sub.add_parser("stop-all").set_defaults(fn=cmd_stop_all)

    # app
    app = sub.add_parser("app").add_subparsers(dest="subcommand", required=True)
    x = app.add_parser("new")
    x.add_argument("name")
    x.add_argument("--id", type=int, default=0)
    x.add_argument("--description")
    x.add_argument("--access-key", default="")
    x.set_defaults(fn=cmd_app_new)
    app.add_parser("list").set_defaults(fn=cmd_app_list)
    x = app.add_parser("show")
    x.add_argument("name")
    x.set_defaults(fn=cmd_app_show)
    x = app.add_parser("delete")
    x.add_argument("name")
    x.add_argument("-f", "--force", action="store_true")
    x.set_defaults(fn=cmd_app_delete)
    x = app.add_parser("data-delete")
    x.add_argument("name")
    x.add_argument("--channel")
    x.add_argument("-f", "--force", action="store_true")
    x.set_defaults(fn=cmd_app_data_delete)
    x = app.add_parser("channel-new")
    x.add_argument("app_name")
    x.add_argument("channel")
    x.set_defaults(fn=cmd_channel_new)
    x = app.add_parser("channel-delete")
    x.add_argument("app_name")
    x.add_argument("channel")
    x.add_argument("-f", "--force", action="store_true")
    x.set_defaults(fn=cmd_channel_delete)

    # accesskey
    ak = sub.add_parser("accesskey").add_subparsers(dest="subcommand", required=True)
    x = ak.add_parser("new")
    x.add_argument("app_name")
    x.add_argument("--key", default="")
    x.add_argument("--event", action="append")
    x.set_defaults(fn=cmd_accesskey_new)
    x = ak.add_parser("list")
    x.add_argument("app_name", nargs="?")
    x.set_defaults(fn=cmd_accesskey_list)
    x = ak.add_parser("delete")
    x.add_argument("key")
    x.set_defaults(fn=cmd_accesskey_delete)

    # engine lifecycle
    def engine_args(x):
        x.add_argument("--engine-dir", default=".")
        x.add_argument("--variant")

    def stream_args(x, require_app: bool):
        """Speed-layer flags shared by `pio stream` and `pio train --follow`."""
        x.add_argument(
            "--app-name",
            required=require_app,
            default=None if require_app else "",
            help="app whose event store to tail",
        )
        x.add_argument("--channel", default="", help="channel name (optional)")
        x.add_argument(
            "--interval", type=float, default=5.0, help="seconds between cycles"
        )
        x.add_argument(
            "--batch-limit",
            type=int,
            default=500,
            help="events per drain micro-batch (the backpressure unit)",
        )
        x.add_argument(
            "--safety-lag",
            type=float,
            default=0.5,
            help="seconds the drain stays behind the wall clock, so a "
            "concurrently committing insert cannot land behind the "
            "cursor and be skipped (0 disables)",
        )
        x.add_argument(
            "--publish-min-events",
            type=int,
            default=1,
            help="publish a candidate once this many new events folded in",
        )
        x.add_argument(
            "--mode",
            choices=("canary", "shadow"),
            default="canary",
            help="rollout mode published candidates are staged with",
        )
        x.add_argument(
            "--fraction", type=float, default=0.1, help="canary fraction"
        )
        x.add_argument(
            "--from-beginning",
            action="store_true",
            help="a fresh cursor replays the whole store instead of "
            "starting at the head",
        )
        x.add_argument(
            "--cursor-dir", help="cursor state dir (default: $PIO_STREAM_DIR)"
        )
        x.add_argument(
            "--cycles",
            type=int,
            default=None,
            help="stop after N cycles (default: run until interrupted)",
        )
        x.add_argument(
            "--notify-url",
            help="POST staged candidates to this query server's "
            "/models/candidate instead of writing registry rollout state "
            "directly",
        )
        x.add_argument(
            "--metrics-port",
            type=int,
            default=0,
            help="serve the pipeline's pio_stream_* metrics at "
            "http://0.0.0.0:PORT/metrics (for `pio top`); 0 disables",
        )
        x.add_argument(
            "--obs-dir",
            help="observability plane dir: drift-guard breaches land on "
            "its telemetry ring (kind=drift — the lifecycle controller's "
            "retune sensor) and snapshot rate-limited incident bundles",
        )

    x = sub.add_parser("build")
    engine_args(x)
    x.set_defaults(fn=cmd_build)

    x = sub.add_parser("unregister")
    engine_args(x)
    x.set_defaults(fn=cmd_unregister)

    x = sub.add_parser("train")
    engine_args(x)
    x.add_argument("--batch", default="")
    x.add_argument("--skip-sanity-check", action="store_true")
    x.add_argument("--stop-after-read", action="store_true")
    x.add_argument("--stop-after-prepare", action="store_true")
    x.add_argument(
        "--num-hosts",
        type=int,
        default=1,
        help="launch N local worker processes joined via jax.distributed "
        "(ref Runner.runOnSpark)",
    )
    x.add_argument(
        "--hosts",
        default="",
        help="comma-separated remote hosts; one ssh-launched worker each",
    )
    x.add_argument(
        "--registry-dir",
        help="publish the trained model into this artifact registry "
        "(default: $PIO_REGISTRY_DIR when set, else no registry publish)",
    )
    x.add_argument(
        "--keep-versions",
        type=int,
        default=5,
        help="registry GC: keep this many versions (stable/candidate are "
        "always kept)",
    )
    x.add_argument(
        "--follow",
        action="store_true",
        help="after training, keep tailing the event store and publish "
        "registry candidates continuously (speed layer; requires "
        "--app-name — see docs/streaming.md)",
    )
    stream_args(x, require_app=False)
    x.set_defaults(fn=cmd_train)

    x = sub.add_parser(
        "stream",
        help="speed layer: tail the event store, fold events into the "
        "stable model, publish registry candidates (docs/streaming.md)",
    )
    engine_args(x)
    x.add_argument(
        "--registry-dir",
        help="artifact registry holding the stable model and receiving "
        "candidates (default: $PIO_REGISTRY_DIR)",
    )
    stream_args(x, require_app=True)
    x.set_defaults(fn=cmd_stream)

    x = sub.add_parser(
        "eval",
        help="hyperparameter search: parallel, resumable fold×params "
        "evaluation grid; winner publishes through the registry "
        "(docs/evaluation.md)",
    )
    x.add_argument("evaluation", help="dotted path to an Evaluation")
    x.add_argument("engine_params_generator", nargs="?")
    x.add_argument("--batch", default="")
    x.add_argument(
        "--workers",
        type=int,
        default=0,
        help="parallel cell worker processes (0 = score cells in-process; "
        "workers rebuild the evaluation from its dotted path)",
    )
    x.add_argument(
        "--folds",
        type=int,
        default=None,
        help="expected fold count (default: discovered from the data "
        "source's read_eval)",
    )
    x.add_argument(
        "--workdir",
        default=None,
        help="grid working directory holding the trial ledger; a stable "
        "--workdir is what makes --resume possible (default: a fresh "
        "temp dir per run)",
    )
    x.add_argument(
        "--resume",
        action="store_true",
        help="resume a killed run from --workdir's ledger: finished "
        "cells are never retrained",
    )
    x.add_argument(
        "--batch-size",
        type=int,
        default=512,
        help="mega-batch size for held-out scoring through "
        "Engine.dispatch_batch (default 512)",
    )
    x.add_argument(
        "--engine-dir",
        default=None,
        help="engine project directory — supplies the registry identity "
        "the winner publishes under (with --variant)",
    )
    x.add_argument("--variant", help="engine.json variant (with --engine-dir)")
    x.add_argument(
        "--registry-dir",
        help="artifact registry receiving the winning refit as a "
        "candidate (default: $PIO_REGISTRY_DIR)",
    )
    x.add_argument(
        "--publish",
        action="store_true",
        help="force winner publication (default: publish automatically "
        "when --engine-dir and a registry dir are both available)",
    )
    x.add_argument(
        "--no-publish",
        action="store_true",
        help="never publish the winner (scores and ledger only)",
    )
    x.add_argument(
        "--stage-mode",
        choices=["canary", "shadow"],
        default="canary",
        help="rollout mode the winner is staged under (default canary)",
    )
    x.add_argument(
        "--stage-fraction",
        type=float,
        default=0.1,
        help="canary fraction for the staged winner (default 0.1)",
    )
    x.add_argument(
        "--status-file",
        default=None,
        help="write throttled atomic progress snapshots here; "
        "`pio top --eval PATH` renders them live",
    )
    x.add_argument(
        "--nice",
        type=int,
        default=0,
        help="re-nice grid worker processes by this amount (background "
        "retunes yield the CPU to serving; 0 = inherit)",
    )
    x.add_argument(
        "--worker-class",
        choices=["", "cpu-fallback"],
        default="",
        help="fleet replica class the workers run as: cpu-fallback pins "
        "workers to JAX_PLATFORMS=cpu and bounds --workers so the grid "
        "never grabs the accelerator from serving",
    )
    x.add_argument(
        "--out", default=None, help="write the grid report JSON here"
    )
    x.set_defaults(fn=cmd_eval)

    x = sub.add_parser("deploy")
    engine_args(x)
    x.add_argument("--ip", default="0.0.0.0")
    x.add_argument("--port", type=int, default=8000)
    x.add_argument("--accesskey")
    x.add_argument("--feedback", action="store_true")
    x.add_argument("--event-server-url")
    x.add_argument("--feedback-access-key")
    x.add_argument("--ssl-certfile")
    x.add_argument("--ssl-keyfile")
    x.add_argument("--log-url", help="POST serving errors to this collector URL")
    x.add_argument("--log-prefix", help="prefix prepended to each remote log body")
    x.add_argument(
        "--request-timeout",
        type=float,
        default=10.0,
        help="per-request deadline in seconds for /queries.json "
        "(503 instead of hanging; <= 0 disables)",
    )
    x.add_argument(
        "--queue-high-water",
        type=int,
        default=256,
        help="shed load with 503 + Retry-After when this many queries are "
        "already queued (0 = unbounded)",
    )
    x.add_argument(
        "--breaker-threshold",
        type=int,
        default=3,
        help="consecutive deadline-blown device calls that open the "
        "dispatch circuit breaker",
    )
    x.add_argument(
        "--breaker-recovery",
        type=float,
        default=5.0,
        help="seconds an open dispatch breaker waits before probing again",
    )
    x.add_argument(
        "--registry-dir",
        help="serve the model registry's pinned stable version and expose "
        "the /models rollout surface (default: registry disabled)",
    )
    x.add_argument(
        "--sticky-key",
        default="user",
        help="query payload field whose hash pins a user to one model "
        "during a canary",
    )
    x.add_argument(
        "--candidate-breaker-threshold",
        type=int,
        default=3,
        help="consecutive candidate-lane failures that force an instant "
        "rollback",
    )
    x.add_argument(
        "--bake-window",
        type=float,
        default=60.0,
        help="seconds a candidate must bake before the promotion gates run",
    )
    x.add_argument(
        "--bake-min-requests",
        type=int,
        default=20,
        help="minimum canary queries (shadow: scored queries) before any "
        "promote/rollback verdict",
    )
    x.add_argument(
        "--no-auto-promote",
        action="store_true",
        help="gates report 'ready' instead of promoting; an operator "
        "promotes via `pio models promote --url ...`",
    )
    x.add_argument(
        "--bandit",
        choices=("epsilon", "thompson"),
        help="steer staged candidates with a contextual-bandit policy: "
        "arms are the stable/candidate lanes, reward is feedback events "
        "matched to served impressions by trace id, and the bake gate "
        "doubles as reward accounting (docs/bandit.md)",
    )
    x.add_argument(
        "--bandit-epsilon",
        type=float,
        default=0.1,
        help="explore share for the epsilon policy (doubles as the "
        "cold-start fraction for thompson)",
    )
    x.add_argument(
        "--bandit-min-pulls",
        type=int,
        default=20,
        help="per-arm impression floor before the reward posterior may "
        "promote or retire",
    )
    x.add_argument(
        "--bandit-app-name",
        help="app whose event stream carries the reward events (required "
        "with --bandit)",
    )
    x.add_argument(
        "--bandit-reward-event",
        help="comma-separated event names credited as rewards "
        "(default: reward)",
    )
    x.add_argument(
        "--result-cache-size",
        type=int,
        default=1024,
        help="version-keyed result cache entries (0 disables); hits "
        "answer before micro-batch admission (docs/PERF.md)",
    )
    x.add_argument(
        "--result-cache-ttl",
        type=float,
        default=10.0,
        help="result-cache entry TTL seconds — the staleness bound for "
        "serving components reading live state outside the model",
    )
    x.add_argument(
        "--fleet",
        type=int,
        default=0,
        metavar="N",
        help="deploy N supervised QueryServer worker processes (ports "
        "PORT+1..PORT+N) behind a gateway on PORT: least-loaded routing, "
        "/healthz ejection, crash restart, one-retry failover, federated "
        "/metrics (docs/fleet.md)",
    )
    x.add_argument(
        "--fleet-probe-interval",
        type=float,
        default=1.0,
        help="gateway /healthz probe cadence in seconds (bounds how fast "
        "a dead replica is ejected)",
    )
    x.add_argument(
        "--autoscale",
        action="store_true",
        help="size the fleet from the telemetry ring: scale out on "
        "fast-window SLO burn / sustained queue depth, scale in (graceful "
        "drain) on sustained idle; never resizes mid-bake; needs the "
        "flight recorder (--obs-dir) enabled (docs/fleet.md §Autoscaling)",
    )
    x.add_argument(
        "--fleet-min",
        type=int,
        default=None,
        metavar="N",
        help="autoscaler device-class floor (default 1)",
    )
    x.add_argument(
        "--fleet-max",
        type=int,
        default=None,
        metavar="N",
        help="autoscaler device-class ceiling (default 2x the --fleet "
        "boot size); wanting capacity past the whole envelope snapshots "
        "an autoscaler-saturated incident bundle",
    )
    x.add_argument(
        "--cpu-fallback-max",
        type=int,
        default=None,
        metavar="N",
        help="max cheap cpu-fallback replicas (JAX_PLATFORMS=cpu workers) "
        "added once the device envelope is exhausted; the gateway routes "
        "them overflow-first so spikes degrade to slower answers instead "
        "of sheds (default 0 = disabled)",
    )
    x.add_argument(
        "--autoscale-interval",
        type=float,
        default=None,
        help="autoscaler control-loop cadence in seconds (default 5)",
    )
    x.add_argument(
        "--hosts",
        default=None,
        metavar="SPEC",
        help="multi-host worker placement: comma list of "
        "[driver@]host:slots entries (drivers: local, ssh, container; "
        "e.g. 'local:4,ssh@gpu-2:8'); workers spread across the "
        "inventory and a dead host's capacity respawns on survivors "
        "(docs/fleet.md §Multi-host)",
    )
    x.add_argument(
        "--gateways",
        type=int,
        default=1,
        metavar="N",
        help="run N shared-nothing gateways on ports PORT..PORT+N-1 over "
        "the same replica set (put any TCP balancer in front); each peer "
        "serves its own /metrics, /traces/recent and /slo fan in across "
        "peers (default 1)",
    )
    x.add_argument(
        "--obs-dir",
        default="pio_obs",
        help="fleet flight-recorder directory: worker log tails, the "
        "durable telemetry ring (`pio top --history`), and incident "
        "bundles (`pio incidents list`); '' disables "
        "(docs/observability.md)",
    )
    x.add_argument(
        "--registry-sync-interval",
        type=float,
        default=None,
        help="poll the registry's state generation on this cadence and "
        "adopt stage/promote/rollback made by other processes (fleet "
        "workers default to 1.0; 0 disables; needs --registry-dir)",
    )
    x.add_argument(
        "--lifecycle",
        default=None,
        metavar="EVALUATION",
        help="run the self-driving lifecycle controller in the fleet "
        "parent: drift on the telemetry ring (or --lifecycle-cadence) "
        "triggers a background retune of this dotted Evaluation on "
        "nice'd cpu-fallback grid workers, the winner bakes through the "
        "rollout gates, promotes auto-warm the result cache; needs "
        "--registry-dir and --obs-dir (docs/lifecycle.md)",
    )
    x.add_argument(
        "--lifecycle-cadence",
        type=float,
        default=None,
        help="also retune every N seconds (default 0 = drift/manual only)",
    )
    x.add_argument(
        "--lifecycle-cooldown",
        type=float,
        default=None,
        help="seconds after an episode before auto triggers re-arm "
        "(default 600)",
    )
    x.add_argument(
        "--lifecycle-workers",
        type=int,
        default=None,
        help="grid worker processes for lifecycle retunes (default 2; "
        "always the cpu-fallback class)",
    )
    x.add_argument(
        "--lifecycle-nice",
        type=int,
        default=None,
        help="re-nice lifecycle grid workers (default 10)",
    )
    x.add_argument(
        "--lifecycle-warm-limit",
        type=int,
        default=None,
        help="max queries replayed per post-promote cache warm "
        "(default 256; 0 disables)",
    )
    x.add_argument(
        "--lifecycle-app",
        default=None,
        metavar="APP_NAME",
        help="app whose event store supplies warm-up queries (distinct "
        "users); unset disables cache warming",
    )
    x.add_argument(
        "--drain-grace",
        type=float,
        default=15.0,
        help="seconds a SIGTERM'd server waits for in-flight queries to "
        "answer after closing its listener (graceful drain)",
    )
    x.set_defaults(fn=cmd_deploy)

    x = sub.add_parser("undeploy")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=8000)
    x.add_argument("--ssl", action="store_true", help="server was deployed with TLS")
    x.set_defaults(fn=cmd_undeploy)

    x = sub.add_parser(
        "batchpredict",
        help="offline mega-batch prediction through the fused device "
        "kernels (docs/batch_predict.md)",
    )
    engine_args(x)
    x.add_argument(
        "--input",
        default=None,
        help="multi-line JSON query file, streamed (default "
        "batchpredict-input.json; mutually exclusive with --from-events)",
    )
    x.add_argument(
        "--output",
        default="batchpredict-output.json",
        help="line-aligned JSONL predictions, written atomically "
        "(tmp+rename); '' disables the file sink",
    )
    x.add_argument(
        "--from-events",
        action="store_true",
        help="stream DISTINCT users straight off the app's event store "
        "(find_after order, bounded pages) instead of a query file",
    )
    x.add_argument(
        "--app-name",
        default="",
        help="app for --from-events/--to-events (default: the engine "
        "variant's datasource appName)",
    )
    x.add_argument("--channel", default="", help="channel name (optional)")
    x.add_argument(
        "--query-num",
        type=int,
        default=10,
        help="top-k per synthesized --from-events query (default 10)",
    )
    x.add_argument(
        "--batch",
        type=int,
        default=512,
        help="mega-batch size; pow2 keeps the compiled-bucket universe "
        "at one program (default 512)",
    )
    x.add_argument(
        "--to-events",
        action="store_true",
        help="also write scored results back into the event store "
        "(batchpredict.result events, retry/breaker-protected)",
    )
    x.add_argument(
        "--limit",
        type=int,
        default=0,
        help="cap the number of queries processed (0 = all)",
    )
    x.add_argument(
        "--status-file",
        default=None,
        help="write throttled atomic progress snapshots here; "
        "`pio top --batchpredict PATH` renders them live",
    )
    x.set_defaults(fn=cmd_batchpredict)

    # servers
    x = sub.add_parser("eventserver")
    x.add_argument("--ip", default="0.0.0.0")
    x.add_argument("--port", type=int, default=7070)
    x.add_argument("--stats", action="store_true")
    x.add_argument("--ssl-certfile")
    x.add_argument("--ssl-keyfile")
    x.add_argument(
        "--storage-retries",
        type=int,
        default=3,
        help="attempts per storage call for transient failures (<= 1 disables)",
    )
    x.add_argument(
        "--breaker-threshold",
        type=int,
        default=5,
        help="consecutive storage failures that open the circuit breaker "
        "(requests then answer 503 'storage unavailable')",
    )
    x.add_argument(
        "--breaker-recovery",
        type=float,
        default=5.0,
        help="seconds an open storage breaker waits before probing again",
    )
    x.set_defaults(fn=cmd_eventserver)

    x = sub.add_parser("adminserver")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=7071)
    x.add_argument(
        "--registry-dir",
        help="model registry base dir served at /cmd/models "
        "(default: $PIO_REGISTRY_DIR, else $PIO_FS_BASEDIR/registry)",
    )
    x.set_defaults(fn=cmd_adminserver)

    x = sub.add_parser("dashboard")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=9000)
    x.add_argument(
        "--metrics-url",
        action="append",
        help="a server base URL whose /metrics the dashboard shows as "
        "breaker/queue/latency panels (repeatable; e.g. "
        "http://localhost:8000)",
    )
    x.set_defaults(fn=cmd_dashboard)

    x = sub.add_parser(
        "top",
        help="live terminal summary of a running server's /metrics "
        "(qps, p95, shed rate, breaker states, recompile count)",
    )
    x.add_argument(
        "--url",
        default=_TOP_DEFAULT_URL,
        help="server base URL (QueryServer or EventServer)",
    )
    x.add_argument("--interval", type=float, default=2.0)
    x.add_argument(
        "-n",
        "--iterations",
        type=int,
        default=None,
        help="stop after N refreshes (default: run until Ctrl-C)",
    )
    x.add_argument(
        "--once",
        action="store_true",
        help="print one snapshot and exit (rates need two samples and "
        "show as '-')",
    )
    x.add_argument(
        "--json",
        action="store_true",
        help="machine-readable output: one JSON snapshot per line instead "
        "of the terminal screen (for CI and fleet tooling)",
    )
    x.add_argument(
        "--metrics-url",
        action="append",
        help="poll this endpoint (repeatable); an explicitly-set --url "
        "(or --fleet gateway) is polled too, first in each refresh — an "
        "untouched default --url is not. Fleet dashboards scrape replicas "
        "directly alongside the gateway's federated view; with --json, "
        "one object per endpoint per refresh",
    )
    x.add_argument(
        "--fleet",
        action="store_true",
        help="fleet mode: point --url at a `pio deploy --fleet` gateway; "
        "the per-replica fleet line renders from its federated /metrics",
    )
    x.add_argument(
        "--history",
        action="store_true",
        help="render the fleet telemetry ring's queue-depth/burn/health "
        "series (one shot): from the gateway's /telemetry/window, or "
        "straight off the on-disk ring via --obs-dir when the gateway "
        "is down (the ring survives the process)",
    )
    x.add_argument(
        "--history-window",
        type=float,
        default=600.0,
        metavar="S",
        help="trailing seconds of telemetry to render (default 600)",
    )
    x.add_argument(
        "--obs-dir",
        default=None,
        help="read the telemetry ring from this fleet obs directory "
        "instead of over HTTP (pairs with --history)",
    )
    x.add_argument(
        "--batchpredict",
        default=None,
        metavar="STATUS_FILE",
        help="render the progress line of an offline `pio batchpredict` "
        "run from its --status-file (live while the run is active, "
        "final totals after)",
    )
    x.add_argument(
        "--eval",
        default=None,
        metavar="STATUS_FILE",
        help="render the live grid line of a `pio eval` run from its "
        "--status-file: cells done/total, running workers, best score "
        "so far, ETA",
    )
    x.add_argument(
        "--lifecycle",
        default=None,
        metavar="STATE_FILE",
        help="render the lifecycle controller's episode line from its "
        "durable state file (<state-dir>/lifecycle.json): state, "
        "trigger, grid progress, candidate baking, last outcome",
    )
    x.add_argument(
        "--hotspots",
        action="store_true",
        help="append the host-sampler hotspots block (top-of-stack "
        "frames per thread role + sampler overhead %%) from the "
        "server's /profile/stacks; an endpoint without the profiling "
        "plane degrades to one 'unreachable' line",
    )
    x.set_defaults(fn=cmd_top)

    inc = sub.add_parser(
        "incidents",
        help="inspect incident bundles captured by the fleet flight "
        "recorder (worker crash, breaker trip, SLO alert; "
        "docs/observability.md)",
    ).add_subparsers(dest="subcommand", required=True)
    x = inc.add_parser("list", help="bundles oldest first")
    x.add_argument(
        "--obs-dir",
        default="pio_obs",
        help="fleet observability directory (`pio deploy --fleet --obs-dir`)",
    )
    x.set_defaults(fn=cmd_incidents_list)
    x = inc.add_parser(
        "show", help="manifest, parts, and the stderr tail of one bundle"
    )
    x.add_argument("bundle", help="bundle id (unique prefix accepted)")
    x.add_argument("--obs-dir", default="pio_obs")
    x.add_argument("--json", action="store_true", help="full bundle as JSON")
    x.add_argument(
        "--tail-lines",
        type=int,
        default=20,
        help="stderr-tail lines to print (default 20)",
    )
    x.set_defaults(fn=cmd_incidents_show)
    x = inc.add_parser("export", help="copy one bundle somewhere shippable")
    x.add_argument("bundle", help="bundle id (unique prefix accepted)")
    x.add_argument("dest", help="destination directory")
    x.add_argument("--obs-dir", default="pio_obs")
    x.set_defaults(fn=cmd_incidents_export)

    lc = sub.add_parser(
        "lifecycle",
        help="the self-driving model lifecycle: drift → retune → bake → "
        "promote → warm, zero human commands (docs/lifecycle.md)",
    ).add_subparsers(dest="subcommand", required=True)

    def lifecycle_dir_args(x):
        x.add_argument(
            "--obs-dir",
            default="pio_obs",
            help="fleet observability directory (the controller's state "
            "lives under <obs-dir>/lifecycle by default)",
        )
        x.add_argument(
            "--state-dir",
            default=None,
            help="controller state directory override (default "
            "<obs-dir>/lifecycle)",
        )

    x = lc.add_parser(
        "run",
        help="run the controller against an already-deployed server "
        "(`pio deploy --fleet N --lifecycle` embeds the same loop)",
    )
    x.add_argument("evaluation", help="dotted path to the retune Evaluation")
    x.add_argument("--engine-dir", default=".")
    x.add_argument("--variant")
    x.add_argument(
        "--registry-dir",
        help="artifact registry the loop stages/promotes through "
        "(default: $PIO_REGISTRY_DIR)",
    )
    lifecycle_dir_args(x)
    x.add_argument(
        "--cadence",
        type=float,
        default=0.0,
        help="scheduled retune every N seconds (0 = drift/manual only)",
    )
    x.add_argument(
        "--drift-window",
        type=float,
        default=600.0,
        help="trailing seconds of ring drift records that count as a "
        "live signal (default 600)",
    )
    x.add_argument(
        "--min-drift-records",
        type=int,
        default=1,
        help="drift records inside the window needed to trigger "
        "(default 1 — each breach already suppressed a publish)",
    )
    x.add_argument(
        "--cooldown",
        type=float,
        default=600.0,
        help="seconds after an episode before drift/cadence can "
        "retrigger (manual `pio lifecycle trigger` bypasses it)",
    )
    x.add_argument(
        "--tune-timeout",
        type=float,
        default=7200.0,
        help="abandon a grid run older than this (its ledger still "
        "speeds up the next episode)",
    )
    x.add_argument(
        "--bake-timeout",
        type=float,
        default=3600.0,
        help="unstage a candidate no server resolves within this",
    )
    x.add_argument(
        "--tick-interval", type=float, default=2.0, help="control-loop cadence"
    )
    x.add_argument(
        "--workers",
        type=int,
        default=2,
        help="grid worker processes (cpu-fallback class: JAX_PLATFORMS "
        "pinned to cpu, count bounded)",
    )
    x.add_argument(
        "--nice",
        type=int,
        default=10,
        help="re-nice grid workers (background retunes yield to serving)",
    )
    x.add_argument("--folds", type=int, default=None)
    x.add_argument(
        "--workdir",
        default=None,
        help="grid workdir root, one run-NNNN per episode (default "
        "<state-dir>/grid); stable across restarts = crash resume",
    )
    x.add_argument(
        "--stage-mode", choices=["canary", "shadow"], default="canary"
    )
    x.add_argument("--stage-fraction", type=float, default=0.1)
    x.add_argument(
        "--serve-url",
        default=None,
        help="server/gateway base URL; promoted models warm their result "
        "cache by replaying queries here (with --app-name)",
    )
    x.add_argument(
        "--app-name",
        default=None,
        help="app whose event store supplies warm-up queries "
        "(distinct users, the batchpredict --from-events source)",
    )
    x.add_argument(
        "--warm-limit",
        type=int,
        default=256,
        help="max queries replayed per post-promote cache warm "
        "(0 disables warming)",
    )
    x.set_defaults(fn=cmd_lifecycle_run)

    x = lc.add_parser(
        "status", help="episode state from the controller's durable file"
    )
    lifecycle_dir_args(x)
    x.add_argument("--json", action="store_true")
    x.set_defaults(fn=cmd_lifecycle_status)

    x = lc.add_parser(
        "trigger",
        help="queue one manual retune (bypasses cooldown, never an "
        "in-flight episode or a live bake)",
    )
    lifecycle_dir_args(x)
    x.set_defaults(fn=cmd_lifecycle_trigger)

    x = lc.add_parser(
        "pause",
        help="stop automatic triggers (in-flight episodes finish; "
        "manual trigger still works)",
    )
    lifecycle_dir_args(x)
    x.set_defaults(fn=cmd_lifecycle_pause)

    x = lc.add_parser("resume", help="re-enable automatic triggers")
    lifecycle_dir_args(x)
    x.set_defaults(fn=cmd_lifecycle_pause)

    prof = sub.add_parser(
        "profile",
        help="the profiling plane: on-demand device captures against a "
        "live server, device-traced training, and content-addressed "
        "profile bundle inspection (docs/observability.md §Profiling "
        "plane)",
    ).add_subparsers(dest="subcommand", required=True)

    def profile_dir_arg(x):
        x.add_argument(
            "--profile-dir",
            default=None,
            help="profile bundle directory (default $PIO_PROFILE_DIR, "
            "else pio_obs/profiles — the server default)",
        )

    x = prof.add_parser(
        "serve",
        help="POST /profile/capture?ms= on a running server (or a fleet "
        "gateway, which fans out to one replica)",
    )
    x.add_argument("--url", default=_TOP_DEFAULT_URL)
    x.add_argument(
        "--ms",
        type=int,
        default=500,
        help="device-trace duration (clamped server-side to its max; "
        "0 = host-only bundle, no device trace)",
    )
    x.add_argument("--timeout", type=float, default=30.0)
    x.set_defaults(fn=cmd_profile_serve)
    x = prof.add_parser(
        "train",
        help="run `pio train ...` under the device tracer; the trace "
        "lands as a content-addressed bundle under --profile-dir",
    )
    profile_dir_arg(x)
    x.add_argument(
        "train_args",
        nargs=argparse.REMAINDER,
        help="arguments forwarded to `pio train` (prefix with -- )",
    )
    x.set_defaults(fn=cmd_profile_train)
    x = prof.add_parser("list", help="bundles oldest first")
    profile_dir_arg(x)
    x.set_defaults(fn=cmd_profile_list)
    x = prof.add_parser(
        "show", help="manifest, parts, and trace inventory of one bundle"
    )
    x.add_argument("bundle", help="bundle id (unique prefix accepted)")
    profile_dir_arg(x)
    x.add_argument("--json", action="store_true", help="full bundle as JSON")
    x.set_defaults(fn=cmd_profile_show)
    x = prof.add_parser("export", help="copy one bundle somewhere shippable")
    x.add_argument("bundle", help="bundle id (unique prefix accepted)")
    x.add_argument("dest", help="destination directory")
    profile_dir_arg(x)
    x.set_defaults(fn=cmd_profile_export)

    x = sub.add_parser(
        "doctor",
        help="preflight diagnostics: HBM capacity planning "
        "(--capacity USERS ITEMS K) and device/memory inventory",
    )
    x.add_argument(
        "--capacity",
        nargs=3,
        metavar=("USERS", "ITEMS", "K"),
        help="predict per-device bytes for an ALS train of this shape",
    )
    x.add_argument("--dtype", choices=["f32", "bf16"], default="f32")
    x.add_argument(
        "--gather-dtype",
        choices=["f32", "bf16"],
        default="f32",
        help="solver gather dtype (bf16 adds half-size table copies)",
    )
    x.add_argument(
        "--mesh",
        help="mesh axis sizes, e.g. data=8,model=2 (explicit sizes only)",
    )
    x.add_argument("--nnz", type=int, help="rating count (adds wire bytes)")
    x.add_argument(
        "--ann",
        metavar="CLUSTERS,NPROBE",
        help="price an ANN retrieval index (ITEMS items, dim K) next to "
        "the factor tables: 'clusters,nprobe' (0,0 = auto sizing); the "
        "budget check then covers factors + index (docs/ann.md)",
    )
    x.add_argument(
        "--ann-int8",
        action="store_true",
        help="price the int8-quantized index layout",
    )
    x.add_argument(
        "--hbm-bytes",
        help="per-device HBM budget (accepts 16e9 / 16GB / 16GiB); "
        "exit 1 when the estimate exceeds it",
    )
    x.add_argument(
        "--registry-dir",
        help="registry to inventory pinned ANN indexes from "
        "(default $PIO_REGISTRY_DIR)",
    )
    x.add_argument(
        "--roofline",
        action="store_true",
        help="device-free roofline: compile the registered jit bucket "
        "families and report cost_analysis flops/bytes, arithmetic "
        "intensity, and device cost per 1k queries (docs/PERF.md)",
    )
    x.add_argument(
        "--families",
        help="comma list of bucket families for --roofline "
        "(default: all of topk,ann,als,twotower)",
    )
    x.add_argument(
        "--device",
        default=None,
        help="device spec the roofline prices against "
        "(tpu-v4/tpu-v5e/tpu-v5p/cpu-host; default tpu-v4)",
    )
    x.set_defaults(fn=cmd_doctor)

    # data
    x = sub.add_parser("import")
    x.add_argument("--appname", dest="app_name", required=True)
    x.add_argument("--input", required=True)
    x.add_argument("--channel")
    x.set_defaults(fn=cmd_import)

    x = sub.add_parser("export")
    x.add_argument("--appname", dest="app_name", required=True)
    x.add_argument("--output", required=True)
    x.add_argument("--channel")
    x.add_argument("--format", default="json", choices=["json", "parquet", "npz"])
    x.set_defaults(fn=cmd_export)

    # model registry
    mdl = sub.add_parser(
        "models",
        help="model registry: versioned artifacts, canary/shadow rollout, "
        "promote/rollback (docs/model_registry.md)",
    ).add_subparsers(dest="subcommand", required=True)

    def models_args(x):
        x.add_argument("--engine-dir", default=".")
        x.add_argument("--variant")
        x.add_argument(
            "--engine-id",
            help="registry engine id (skips resolving it from --engine-dir)",
        )
        x.add_argument(
            "--registry-dir",
            help="artifact registry base dir (default: $PIO_REGISTRY_DIR, "
            "else $PIO_FS_BASEDIR/registry)",
        )

    x = mdl.add_parser("list")
    models_args(x)
    x.set_defaults(fn=cmd_models_list)
    x = mdl.add_parser("show")
    models_args(x)
    x.add_argument("version", nargs="?", help="default: the stable version")
    x.add_argument("--url", help="show a RUNNING server's /models instead")
    x.set_defaults(fn=cmd_models_show)
    x = mdl.add_parser("promote")
    models_args(x)
    x.add_argument("version", nargs="?", help="default: the staged candidate")
    x.add_argument("--url", help="promote on a RUNNING server (lanes swap live)")
    x.set_defaults(fn=cmd_models_promote)
    x = mdl.add_parser("rollback")
    models_args(x)
    x.add_argument("--url", help="roll back on a RUNNING server")
    x.set_defaults(fn=cmd_models_rollback)
    x = mdl.add_parser("stage")
    models_args(x)
    x.add_argument("version")
    x.add_argument("--url", required=True, help="running server base URL")
    x.add_argument("--mode", choices=["canary", "shadow"], default="canary")
    x.add_argument("--fraction", type=float, default=0.1)
    x.set_defaults(fn=cmd_models_stage)
    x = mdl.add_parser("diff")
    models_args(x)
    x.add_argument("version_a")
    x.add_argument("version_b")
    x.set_defaults(fn=cmd_models_diff)

    # templates
    tpl = sub.add_parser("template").add_subparsers(dest="subcommand", required=True)
    tpl.add_parser("list").set_defaults(fn=cmd_template_list)
    x = tpl.add_parser("get")
    x.add_argument("name")
    x.add_argument("directory", nargs="?")
    x.set_defaults(fn=cmd_template_get)

    # static analysis
    x = sub.add_parser(
        "lint",
        help="TPU-aware static analysis: tracer safety, recompile hazards, "
        "host-sync stalls, concurrency, storage contracts",
    )
    add_lint_arguments(x)
    x.set_defaults(fn=cmd_lint)

    # run
    x = sub.add_parser("run")
    x.add_argument("main")
    x.add_argument("args", nargs="*")
    x.set_defaults(fn=cmd_run)

    return p


def main(argv: list[str] | None = None) -> int:
    from predictionio_tpu.utils.platform import ensure_cpu_if_requested

    ensure_cpu_if_requested()
    args = build_parser().parse_args(argv)
    # remember the EXACT argv this invocation parsed (None = process argv);
    # the multi-host launcher re-execs it in the workers
    args._invocation_argv = list(argv) if argv is not None else None
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="[%(levelname)s] [%(name)s] %(message)s",
    )
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130
    except Exception as exc:
        if args.verbose:
            raise
        return _die(str(exc))


if __name__ == "__main__":
    sys.exit(main())
