"""Interactive shell with preloaded stores (ref ``bin/pio-shell`` +
``python/pypio/shell.py``: a REPL with PEventStore/CleanupFunctions bound)."""

from __future__ import annotations

BANNER = """predictionio_tpu shell
Preloaded: storage, p_event_store, l_event_store, Event, DataMap, jax, jnp
Example: list(p_event_store.find("MyApp1", limit=5))
"""


def run_shell() -> None:
    # an explicit JAX_PLATFORMS=cpu shell must never touch the TPU plugin
    # (whose registration can hang on a wedged tunnel) — same guard as the
    # CLI entry
    from predictionio_tpu.utils.platform import ensure_cpu_if_requested

    ensure_cpu_if_requested()
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.store.event_store import LEventStore, PEventStore
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.workflow.cleanup import CleanupFunctions

    storage = Storage.instance()
    namespace = {
        "storage": storage,
        "p_event_store": PEventStore(storage),
        "l_event_store": LEventStore(storage),
        "Event": Event,
        "DataMap": DataMap,
        "CleanupFunctions": CleanupFunctions,
        "jax": jax,
        "jnp": jnp,
    }
    print(BANNER)
    try:
        from IPython import start_ipython

        start_ipython(argv=["--no-banner"], user_ns=namespace)
    except ImportError:
        import code

        code.interact(banner="", local=namespace)
    finally:
        CleanupFunctions.run()
