"""The Event model and validation rules.

Reference parity: ``data/.../storage/Event.scala`` (fields :42-60, validation
:112-166) and the REST wire format in ``EventJson4sSupport.scala:46-108``
(required event/entityType/entityId; optional eventId, targetEntityType/Id,
properties, eventTime ISO8601 defaulting to now-UTC, prId; ``tags`` and
``creationTime`` exist on the model but are disabled on the API).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Any, Mapping

from predictionio_tpu.data.datamap import DataMap

UTC = _dt.timezone.utc


def now_utc() -> _dt.datetime:
    return _dt.datetime.now(tz=UTC)


def ensure_aware(t: _dt.datetime | None) -> _dt.datetime | None:
    """Interpret naive datetimes as UTC (filters from user code may be naive;
    stored event times are always aware)."""
    if t is not None and t.tzinfo is None:
        return t.replace(tzinfo=UTC)
    return t


def parse_event_time(value: str) -> _dt.datetime:
    """Parse an ISO8601 timestamp; must carry a timezone (ref wire contract)."""
    # Python's fromisoformat only handles the 'Z' suffix from 3.11 on, but
    # the wire format (and format_event_time) emit it; normalize for 3.10.
    if value.endswith(("Z", "z")):
        value = value[:-1] + "+00:00"
    t = _dt.datetime.fromisoformat(value)
    if t.tzinfo is None:
        raise ValueError(f"eventTime {value!r} must include a timezone offset")
    return t


def format_event_time(t: _dt.datetime) -> str:
    """ISO8601 with milliseconds, matching the reference's joda output."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=UTC)
    s = t.isoformat(timespec="milliseconds")
    return s.replace("+00:00", "Z")


@dataclasses.dataclass(frozen=True)
class Event:
    """One immutable event record (ref Event.scala:42-60)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: str | None = None
    target_entity_id: str | None = None
    properties: DataMap = dataclasses.field(default_factory=DataMap)
    event_time: _dt.datetime = dataclasses.field(default_factory=now_utc)
    event_id: str | None = None
    tags: tuple[str, ...] = ()
    pr_id: str | None = None
    creation_time: _dt.datetime = dataclasses.field(default_factory=now_utc)

    def __post_init__(self):
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        if self.event_time.tzinfo is None:
            object.__setattr__(self, "event_time", self.event_time.replace(tzinfo=UTC))
        if self.creation_time.tzinfo is None:
            object.__setattr__(
                self, "creation_time", self.creation_time.replace(tzinfo=UTC)
            )

    # -- wire format --------------------------------------------------------
    def to_json_dict(self, with_creation_time: bool = False) -> dict[str, Any]:
        d: dict[str, Any] = {
            "eventId": self.event_id,
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
        }
        if self.target_entity_type is not None:
            d["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            d["targetEntityId"] = self.target_entity_id
        d["properties"] = self.properties.fields
        d["eventTime"] = format_event_time(self.event_time)
        if self.pr_id is not None:
            d["prId"] = self.pr_id
        if with_creation_time:
            d["creationTime"] = format_event_time(self.creation_time)
        return d

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "Event":
        """Decode the REST payload. Raises ValueError/KeyError on contract
        violations mirroring EventJson4sSupport read rules."""
        for field in ("event", "entityType", "entityId"):
            if field not in d or not isinstance(d[field], str):
                raise ValueError(f"field {field} is required and must be a string")
        # optional string fields must still BE strings: a numeric
        # targetEntityId would be accepted (bool(7) passes validate), then
        # persisted as a JSON number that the two scan paths decode
        # differently (python interns the int, the native scanner drops it)
        for field in ("targetEntityType", "targetEntityId", "eventId", "prId"):
            if d.get(field) is not None and not isinstance(d[field], str):
                raise ValueError(f"field {field} must be a string")
        props = d.get("properties")
        if props is None:
            props = {}
        if not isinstance(props, Mapping):
            raise ValueError("properties must be a JSON object")
        raw_time = d.get("eventTime")
        event_time = parse_event_time(raw_time) if raw_time else now_utc()
        e = Event(
            event=d["event"],
            entity_type=d["entityType"],
            entity_id=d["entityId"],
            target_entity_type=d.get("targetEntityType"),
            target_entity_id=d.get("targetEntityId"),
            properties=DataMap(props),
            event_time=event_time,
            event_id=d.get("eventId"),
            pr_id=d.get("prId"),
        )
        EventValidation.validate(e)
        return e


class EventValidation:
    """Validation rules for events (ref Event.scala:112-166)."""

    DEFAULT_TZ = UTC
    SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})
    BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})
    BUILTIN_PROPERTIES: frozenset[str] = frozenset()

    @classmethod
    def is_reserved_prefix(cls, name: str) -> bool:
        return name.startswith("$") or name.startswith("pio_")

    @classmethod
    def is_special_event(cls, name: str) -> bool:
        return name in cls.SPECIAL_EVENTS

    @classmethod
    def is_builtin_entity_type(cls, name: str) -> bool:
        return name in cls.BUILTIN_ENTITY_TYPES

    @classmethod
    def validate(cls, e: Event) -> None:
        def require(cond: bool, msg: str) -> None:
            if not cond:
                raise ValueError(msg)

        require(bool(e.event), "event must not be empty.")
        require(bool(e.entity_type), "entityType must not be empty string.")
        require(bool(e.entity_id), "entityId must not be empty string.")
        require(
            e.target_entity_type is None or bool(e.target_entity_type),
            "targetEntityType must not be empty string",
        )
        require(
            e.target_entity_id is None or bool(e.target_entity_id),
            "targetEntityId must not be empty string.",
        )
        require(
            (e.target_entity_type is None) == (e.target_entity_id is None),
            "targetEntityType and targetEntityId must be specified together.",
        )
        require(
            not (e.event == "$unset" and e.properties.is_empty()),
            "properties cannot be empty for $unset event",
        )
        require(
            not cls.is_reserved_prefix(e.event) or cls.is_special_event(e.event),
            f"{e.event} is not a supported reserved event name.",
        )
        require(
            not cls.is_special_event(e.event)
            or (e.target_entity_type is None and e.target_entity_id is None),
            f"Reserved event {e.event} cannot have targetEntity",
        )
        require(
            not cls.is_reserved_prefix(e.entity_type)
            or cls.is_builtin_entity_type(e.entity_type),
            f"The entityType {e.entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.",
        )
        require(
            e.target_entity_type is None
            or not cls.is_reserved_prefix(e.target_entity_type)
            or cls.is_builtin_entity_type(e.target_entity_type),
            f"The targetEntityType {e.target_entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.",
        )
        cls.validate_properties(e)

    @classmethod
    def validate_properties(cls, e: Event) -> None:
        for k in e.properties.keyset():
            if cls.is_reserved_prefix(k) and k not in cls.BUILTIN_PROPERTIES:
                raise ValueError(
                    f"The property {k} is not allowed. "
                    "'pio_' is a reserved name prefix."
                )
