"""BiMap — immutable bidirectional mapping, used to index entity IDs into
contiguous integer ranges for TPU embedding/factor tables.

Reference parity: ``data/.../storage/BiMap.scala:1-266`` (``stringInt``/
``stringLong`` constructors, ``inverse``, ``contains``, ``getOrElse``,
``take``, ``toMap``). Where the reference builds from Spark RDDs, this builds
from any iterable (host-side) — the resulting dense int range is exactly what
device-side gather/scatter wants.
"""

from __future__ import annotations

from typing import Generic, Hashable, Iterable, Iterator, Mapping, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class BiMap(Generic[K, V]):
    __slots__ = ("_forward", "_backward")

    def __init__(self, forward: Mapping[K, V], _backward: Mapping[V, K] | None = None):
        self._forward: dict[K, V] = dict(forward)
        if _backward is None:
            backward: dict[V, K] = {v: k for k, v in self._forward.items()}
            if len(backward) != len(self._forward):
                raise ValueError("BiMap values must be unique")
            self._backward = backward
        else:
            self._backward = dict(_backward)

    # -- constructors (ref BiMap.scala stringInt/stringLong) ----------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Assign each distinct key a dense index 0..n-1 in first-seen order."""
        forward: dict[str, int] = {}
        for k in keys:
            if k not in forward:
                forward[k] = len(forward)
        return BiMap(forward)

    string_long = string_int  # Python ints are unbounded

    # -- API ----------------------------------------------------------------
    def __call__(self, key: K) -> V:
        return self._forward[key]

    def __getitem__(self, key: K) -> V:
        return self._forward[key]

    def __contains__(self, key: object) -> bool:
        return key in self._forward

    def __len__(self) -> int:
        return len(self._forward)

    def __iter__(self) -> Iterator[K]:
        return iter(self._forward)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BiMap) and self._forward == other._forward

    def get(self, key: K, default: V | None = None) -> V | None:
        return self._forward.get(key, default)

    def get_or_else(self, key: K, default: V) -> V:
        return self._forward.get(key, default)

    def contains(self, key: K) -> bool:
        return key in self._forward

    def inverse(self) -> "BiMap[V, K]":
        return BiMap(self._backward, self._forward)

    def take(self, n: int) -> "BiMap[K, V]":
        head = dict(list(self._forward.items())[:n])
        return BiMap(head)

    def to_map(self) -> dict[K, V]:
        return dict(self._forward)

    def __repr__(self) -> str:
        return f"BiMap({len(self._forward)} entries)"
