"""Sharded columnar snapshot cache of the event table.

The reference hides event-scan throughput inside Spark's partitioned input
formats (``storage/jdbc/.../JDBCPEvents.scala:91-121`` JdbcRDD time-range
partitions, ``storage/hbase/.../HBPEvents.scala:63-95`` TableInputFormat
region splits): every ``pio train`` re-scans the SQL/HBase store in parallel.
On TPU the equivalent bottleneck is host-side: re-walking a row store and
re-dictionary-encoding 20M events per train run wastes minutes before the
first device step.

This module materialises the result of ``PEvents.to_columnar`` once, as N
row-block shards of dense numpy columns (``.npz``), keyed by a content stamp
of the underlying store. Subsequent trains with the same filters memory-load
the shards (near-disk-bandwidth) instead of re-scanning. Multi-host jobs pick
disjoint shard subsets deterministically (``shards_for_host``), mirroring the
reference's deterministic partition->executor assignment.

Invalidation: the cache key includes ``PEvents.version_stamp`` (cheap
count/max-rowid per backend). Any write to the app's events changes the stamp
and the next read rebuilds. Stale snapshot directories are garbage-collected
lazily (keep the newest ``keep`` per app/filter signature).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import uuid
from pathlib import Path
from typing import Any, Sequence

import numpy as np

from predictionio_tpu.data.storage.base import ColumnarEvents

_META = "meta.json"


def _key(payload: dict[str, Any]) -> str:
    blob = json.dumps(payload, sort_keys=True, default=str).encode()
    return hashlib.sha1(blob).hexdigest()[:20]


def shards_for_host(n_shards: int, host_index: int, host_count: int) -> list[int]:
    """Deterministic host -> shard-subset assignment (round robin)."""
    if host_count <= 0:
        raise ValueError("host_count must be positive")
    return [s for s in range(n_shards) if s % host_count == host_index]


# canonical_order lives beside ColumnarEvents (it is a property of the
# encoding, used by parallel-scan drivers as well as this cache); re-exported
# here for existing importers
from predictionio_tpu.data.storage.base import canonical_order  # noqa: E402,F401


def _shard_count_for(n_rows: int, n_shards: int) -> int:
    return max(1, min(n_shards, n_rows) if n_rows else 1)


def _shard_bounds(n_rows: int, n_shards: int) -> np.ndarray:
    return np.linspace(0, n_rows, n_shards + 1, dtype=np.int64)


def take_blocks(
    cols: ColumnarEvents, shard_ids: Sequence[int], n_shards: int = 8
) -> ColumnarEvents:
    """Select the row blocks that shards ``shard_ids`` of an ``n_shards``-way
    block partition would contain (same math as the shard files)."""
    n = len(cols)
    bounds = _shard_bounds(n, _shard_count_for(n, n_shards))
    idx = (
        np.concatenate(
            [np.arange(bounds[s], bounds[s + 1]) for s in shard_ids]
        ).astype(np.int64)
        if shard_ids
        else np.zeros((0,), np.int64)
    )
    take = idx.tolist()
    return ColumnarEvents(
        event_ids=[cols.event_ids[i] for i in take],
        event_names=[cols.event_names[i] for i in take],
        entity_ids=cols.entity_ids[idx],
        target_ids=cols.target_ids[idx],
        event_codes=cols.event_codes[idx],
        timestamps=cols.timestamps[idx],
        ratings=cols.ratings[idx],
        entity_vocab=cols.entity_vocab,
        target_vocab=cols.target_vocab,
        event_vocab=cols.event_vocab,
    )


def take_host_blocks(
    cols: ColumnarEvents, host_index: int, host_count: int, n_shards: int = 8
) -> ColumnarEvents:
    """This host's deterministic disjoint block subset (canonicalize first
    for order-nondeterministic drivers — see ``canonical_order``)."""
    count = _shard_count_for(len(cols), n_shards)
    return take_blocks(cols, shards_for_host(count, host_index, host_count), n_shards)


@dataclasses.dataclass
class SnapshotCache:
    """Columnar snapshot store rooted at ``root`` (one subdir per key)."""

    root: str | os.PathLike
    n_shards: int = 8
    keep: int = 2  # stale generations retained per signature before GC

    def __post_init__(self):
        self.root = Path(self.root)
        self.root.mkdir(parents=True, exist_ok=True)

    # -- public API ---------------------------------------------------------

    def columnar(
        self,
        p_events,
        app_id: int,
        channel_id: int | None = None,
        *,
        event_names: Sequence[str] | None = None,
        rating_key: str = "rating",
        host_index: int = 0,
        host_count: int = 1,
        refresh: bool = False,
        **find_kwargs: Any,
    ) -> ColumnarEvents:
        """Cached equivalent of ``p_events.to_columnar(...)``.

        Returns only this host's shard subset when ``host_count > 1``.
        """
        signature = {
            "app_id": app_id,
            "channel_id": channel_id,
            "event_names": sorted(event_names) if event_names else None,
            "rating_key": rating_key,
            "find": {k: str(v) for k, v in sorted(find_kwargs.items())},
            # distinct stores sharing one snapshot root must neither alias
            # on equal stamps nor GC each other's generations
            "store": getattr(p_events, "store_identity", lambda: None)(),
        }
        stamp = p_events.version_stamp(app_id, channel_id)
        key = _key({**signature, "stamp": stamp})
        d = self.root / key
        if refresh or stamp is None or not (d / _META).exists():
            # a caller-supplied frozen vocab IS the canonical encoding; only
            # scan-encounter-order vocabs need the deterministic remap
            cols = canonical_order(
                p_events.to_columnar(
                    app_id,
                    channel_id,
                    event_names=event_names,
                    rating_key=rating_key,
                    **find_kwargs,
                ),
                frozen_entity_vocab=find_kwargs.get("entity_vocab") is not None,
                frozen_target_vocab=find_kwargs.get("target_vocab") is not None,
            )
            if stamp is not None:
                self._write(d, cols, signature)
                self._gc(signature, keep_key=key)
            if host_count > 1:
                # Same block partition as the shard files, so a host that
                # misses (build pass) and a host that hits (shard read) see
                # disjoint, jointly-complete row sets. canonical_order above
                # makes this hold even for drivers whose scan order is
                # nondeterministic (ES parallel sliced scroll).
                shard_ids = shards_for_host(
                    self._shard_count(len(cols)), host_index, host_count
                )
                return self._take_blocks(cols, shard_ids)
            return cols
        shard_ids = shards_for_host(self._meta(d)["n_shards"], host_index, host_count)
        return self._read(d, shard_ids)

    # -- internals ----------------------------------------------------------

    def _meta(self, d: Path) -> dict:
        return json.loads((d / _META).read_text())

    def _shard_count(self, n_rows: int) -> int:
        return _shard_count_for(n_rows, self.n_shards)

    def _bounds(self, n_rows: int, n_shards: int) -> np.ndarray:
        return _shard_bounds(n_rows, n_shards)

    def _write(self, d: Path, cols: ColumnarEvents, signature: dict) -> None:
        # unique temp dir per writer: concurrent builders on a shared
        # snapshot root must not clobber each other's in-progress output
        tmp = d.parent / f".{d.name}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
        tmp.mkdir(parents=True)
        n = len(cols)
        n_shards = self._shard_count(n)
        bounds = self._bounds(n, n_shards)
        for s in range(n_shards):
            lo, hi = int(bounds[s]), int(bounds[s + 1])
            np.savez_compressed(
                tmp / f"shard_{s:05d}.npz",
                event_ids=np.asarray(cols.event_ids[lo:hi]),
                event_names=np.asarray(cols.event_names[lo:hi]),
                entity_ids=cols.entity_ids[lo:hi],
                target_ids=cols.target_ids[lo:hi],
                event_codes=cols.event_codes[lo:hi],
                timestamps=cols.timestamps[lo:hi],
                ratings=cols.ratings[lo:hi],
            )
        (tmp / _META).write_text(
            json.dumps(
                {
                    "n_rows": n,
                    "n_shards": n_shards,
                    "signature": signature,
                    "entity_vocab": cols.entity_vocab,
                    "target_vocab": cols.target_vocab,
                    "event_vocab": cols.event_vocab,
                }
            )
        )
        if d.exists():
            shutil.rmtree(d)
        try:
            tmp.rename(d)
        except OSError:
            # a concurrent builder renamed its identical snapshot first
            shutil.rmtree(tmp, ignore_errors=True)

    def _read(self, d: Path, shard_ids: Sequence[int]) -> ColumnarEvents:
        meta = self._meta(d)
        parts = [np.load(d / f"shard_{s:05d}.npz", allow_pickle=False) for s in shard_ids]

        def cat(name, dtype=None):
            if not parts:
                return np.zeros((0,), dtype or np.int32)
            arr = np.concatenate([p[name] for p in parts])
            return arr.astype(dtype) if dtype else arr

        return ColumnarEvents(
            event_ids=list(cat("event_ids").tolist()) if parts else [],
            event_names=list(cat("event_names").tolist()) if parts else [],
            entity_ids=cat("entity_ids", np.int32),
            target_ids=cat("target_ids", np.int32),
            event_codes=cat("event_codes", np.int32),
            timestamps=cat("timestamps", np.float64),
            ratings=cat("ratings", np.float32),
            entity_vocab=meta["entity_vocab"],
            target_vocab=meta["target_vocab"],
            event_vocab=meta["event_vocab"],
        )

    def _take_blocks(
        self, cols: ColumnarEvents, shard_ids: Sequence[int]
    ) -> ColumnarEvents:
        """Select the row blocks that shards ``shard_ids`` would contain."""
        return take_blocks(cols, shard_ids, self.n_shards)

    def _gc(self, signature: dict, keep_key: str) -> None:
        """Drop all-but-newest snapshot dirs sharing ``signature``."""
        matches = []
        for child in self.root.iterdir():
            meta_path = child / _META
            if not meta_path.exists() or child.name == keep_key:
                continue
            try:
                meta = json.loads(meta_path.read_text())
            except (OSError, json.JSONDecodeError):
                continue
            if meta.get("signature") == signature:
                matches.append((child.stat().st_mtime, child))
        matches.sort(reverse=True)
        for _, child in matches[max(0, self.keep - 1):]:
            shutil.rmtree(child, ignore_errors=True)
