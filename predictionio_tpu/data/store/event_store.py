"""LEventStore / PEventStore — what engine code calls to read events.

Reference parity: ``data/.../store/LEventStore.scala:33-143`` (blocking
row-level reads by app *name*, used at predict time by e-commerce-style
algorithms), ``PEventStore.scala:35-119`` (bulk reads for training),
``Common.scala`` (name->id resolution with channel validation).

The P store's ``to_columnar`` is the TPU on-ramp: one bulk scan,
dictionary-encoded to dense int32/float32 numpy columns ready for
``jax.device_put`` / sharded ingest (see ``predictionio_tpu.parallel.ingest``).
"""

from __future__ import annotations

import datetime as _dt
from typing import Iterator, Sequence

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import ColumnarEvents
from predictionio_tpu.data.storage.registry import Storage, StorageError


def resolve_app(
    storage: Storage, app_name: str, channel_name: str | None = None
) -> tuple[int, int | None]:
    """appName -> (appId, channelId) (ref Common.appNameToId)."""
    app = storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise StorageError(f"App {app_name!r} does not exist.")
    if channel_name is None:
        return app.id, None
    channels = storage.get_meta_data_channels().get_by_app_id(app.id)
    for c in channels:
        if c.name == channel_name:
            return app.id, c.id
    raise StorageError(
        f"Channel {channel_name!r} does not exist for app {app_name!r}."
    )


class LEventStore:
    """Blocking row-level reads, safe to call on the serving hot path."""

    def __init__(self, storage: Storage | None = None):
        self._storage = storage or Storage.instance()

    def find(
        self,
        app_name: str,
        channel_name: str | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        app_id, channel_id = resolve_app(self._storage, app_name, channel_name)
        return self._storage.get_l_events().find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=latest,
        )

    def find_by_entity(
        self,
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        limit: int | None = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        """ref LEventStore.findByEntity — newest-first by default."""
        return self.find(
            app_name=app_name,
            channel_name=channel_name,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            latest=latest,
        )


class PEventStore:
    """Bulk reads for training; mirror of ``PEventStore.scala``."""

    def __init__(self, storage: Storage | None = None):
        self._storage = storage or Storage.instance()

    def find(
        self,
        app_name: str,
        channel_name: str | None = None,
        **kwargs,
    ) -> Iterator[Event]:
        app_id, channel_id = resolve_app(self._storage, app_name, channel_name)
        return self._storage.get_p_events().find(
            app_id=app_id, channel_id=channel_id, **kwargs
        )

    def aggregate_properties(
        self,
        app_name: str,
        entity_type: str,
        channel_name: str | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        required: Sequence[str] | None = None,
    ) -> dict[str, PropertyMap]:
        app_id, channel_id = resolve_app(self._storage, app_name, channel_name)
        return self._storage.get_p_events().aggregate_properties(
            app_id=app_id,
            channel_id=channel_id,
            entity_type=entity_type,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )

    def to_columnar(
        self,
        app_name: str,
        channel_name: str | None = None,
        **kwargs,
    ) -> ColumnarEvents:
        app_id, channel_id = resolve_app(self._storage, app_name, channel_name)
        return self._storage.get_p_events().to_columnar(
            app_id=app_id, channel_id=channel_id, **kwargs
        )

    def to_columnar_cached(
        self,
        app_name: str,
        channel_name: str | None = None,
        snapshot_dir: str | None = None,
        host_index: int = 0,
        host_count: int = 1,
        refresh: bool = False,
        **kwargs,
    ) -> ColumnarEvents:
        """``to_columnar`` through the sharded snapshot cache
        (``data/store/snapshot.py``) — the replacement for the reference's
        partitioned storage scans (``JDBCPEvents.scala:91-121``): train runs
        hit the columnar shards, not the row store, unless events changed.

        ``snapshot_dir`` defaults to ``$PIO_SNAPSHOT_DIR``, else
        ``$PIO_FS_BASEDIR/snapshots``, else ``~/.pio_store/snapshots``.
        Multi-host callers pass their ``host_index``/``host_count`` for a
        deterministic disjoint shard set. Set ``PIO_SNAPSHOT_DISABLE=1`` to
        force every train back to the row store.
        """
        import os

        from predictionio_tpu.data.store.snapshot import (
            SnapshotCache,
            canonical_order,
            take_host_blocks,
        )

        if os.environ.get("PIO_SNAPSHOT_DISABLE", "").lower() in ("1", "true", "yes", "on"):
            cols = self.to_columnar(app_name, channel_name, **kwargs)
            if host_count > 1:
                # the bypass must keep the multi-host contract: each host
                # still gets its disjoint block subset of the SAME canonical
                # row order AND the same canonical dictionary encoding (each
                # host built its own vocab in scan-encounter order here),
                # exactly as the cached path computes them
                cols = take_host_blocks(
                    canonical_order(
                        cols,
                        frozen_entity_vocab=kwargs.get("entity_vocab") is not None,
                        frozen_target_vocab=kwargs.get("target_vocab") is not None,
                    ),
                    host_index,
                    host_count,
                )
            return cols
        base = os.environ.get("PIO_FS_BASEDIR")
        snapshot_dir = (
            snapshot_dir
            or os.environ.get("PIO_SNAPSHOT_DIR")
            or (os.path.join(base, "snapshots") if base else None)
            or os.path.join(os.path.expanduser("~"), ".pio_store", "snapshots")
        )
        app_id, channel_id = resolve_app(self._storage, app_name, channel_name)
        cache = SnapshotCache(snapshot_dir)
        return cache.columnar(
            self._storage.get_p_events(),
            app_id,
            channel_id,
            host_index=host_index,
            host_count=host_count,
            refresh=refresh,
            **kwargs,
        )
