"""Engine-facing event stores (app-name addressed).

Reference parity: ``data/.../store/LEventStore.scala``, ``PEventStore.scala``,
``Common.scala`` (appName -> appId / channelName -> channelId resolution).
"""

from predictionio_tpu.data.store.event_store import LEventStore, PEventStore

__all__ = ["LEventStore", "PEventStore"]
