"""Event model, property maps, storage SPI and engine-facing stores.

Reference parity: ``data/src/main/scala/org/apache/predictionio/data`` —
``storage/Event.scala``, ``storage/DataMap.scala``, ``storage/Storage.scala``,
``store/LEventStore.scala``, ``store/PEventStore.scala``, ``api/EventServer.scala``.
"""

from predictionio_tpu.data.datamap import DataMap, PropertyMap
from predictionio_tpu.data.event import Event, EventValidation
from predictionio_tpu.data.bimap import BiMap

__all__ = ["DataMap", "PropertyMap", "Event", "EventValidation", "BiMap"]
