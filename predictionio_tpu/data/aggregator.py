"""Property-replay aggregation: fold $set/$unset/$delete event streams into
per-entity PropertyMaps.

Reference parity: ``data/.../storage/LEventAggregator.scala:41-147`` —
sort by eventTime ascending; ``$set`` merges new keys over old, ``$unset``
removes listed keys, ``$delete`` resets the accumulator; entities whose final
accumulator is empty/None are dropped; firstUpdated/lastUpdated = min/max
eventTime over the three special events only (other events are ignored
entirely). The RDD variant ``PEventAggregator.scala`` has identical fold
semantics; here one vectorizable host-side pass covers both.
"""

from __future__ import annotations

import datetime as _dt
from typing import Iterable

from predictionio_tpu.data.datamap import DataMap, PropertyMap
from predictionio_tpu.data.event import Event

SPECIAL_EVENTS = ("$set", "$unset", "$delete")


class _Acc:
    __slots__ = ("dm", "first", "last")

    def __init__(self):
        self.dm: DataMap | None = None
        self.first: _dt.datetime | None = None
        self.last: _dt.datetime | None = None

    def fold(self, e: Event) -> None:
        if e.event == "$set":
            self.dm = e.properties if self.dm is None else self.dm.union(e.properties)
        elif e.event == "$unset":
            if self.dm is not None:
                self.dm = self.dm.diff(e.properties.keyset())
        elif e.event == "$delete":
            self.dm = None
        else:
            return  # non-special events do not touch properties or timestamps
        self.first = e.event_time if self.first is None else min(self.first, e.event_time)
        self.last = e.event_time if self.last is None else max(self.last, e.event_time)

    def result(self) -> PropertyMap | None:
        if self.dm is None:
            return None
        assert self.first is not None and self.last is not None
        return PropertyMap(self.dm.fields, self.first, self.last)


def aggregate_properties(events: Iterable[Event]) -> dict[str, PropertyMap]:
    """Group by entityId, replay in eventTime order, drop deleted entities."""
    by_entity: dict[str, list[Event]] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: dict[str, PropertyMap] = {}
    for entity_id, es in by_entity.items():
        acc = _Acc()
        for e in sorted(es, key=lambda e: e.event_time):
            acc.fold(e)
        pm = acc.result()
        if pm is not None:
            out[entity_id] = pm
    return out


def aggregate_properties_single(events: Iterable[Event]) -> PropertyMap | None:
    """Replay one entity's events (ref aggregatePropertiesSingle)."""
    acc = _Acc()
    for e in sorted(events, key=lambda e: e.event_time):
        acc.fold(e)
    return acc.result()
