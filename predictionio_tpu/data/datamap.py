"""DataMap / PropertyMap — typed JSON property bags attached to events.

Reference parity: ``data/.../storage/DataMap.scala`` (typed getters, ``++``
merge / ``--`` diff, required-field errors) and ``PropertyMap.scala``
(firstUpdated / lastUpdated timestamps from property-replay aggregation).
"""

from __future__ import annotations

import datetime as _dt
import json
from typing import Any, Iterable, Iterator, Mapping


class DataMapError(KeyError):
    """Raised when a required field is missing or null (ref DataMap.scala:52-58)."""


class DataMap(Mapping[str, Any]):
    """An immutable mapping of property names to JSON values.

    Unlike a plain dict it distinguishes "missing" from "present but null"
    the way the reference does: ``get`` raises on missing, ``get_opt``
    returns None for missing or null.
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Mapping[str, Any] | None = None):
        self._fields: dict[str, Any] = dict(fields) if fields else {}

    # -- Mapping protocol ---------------------------------------------------
    def __getitem__(self, name: str) -> Any:
        return self._fields[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, name: object) -> bool:
        return name in self._fields

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:  # stable enough for memo keys
        return hash(json.dumps(self._fields, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    # -- reference API ------------------------------------------------------
    @property
    def fields(self) -> dict[str, Any]:
        return dict(self._fields)

    def require(self, name: str) -> None:
        if name not in self._fields:
            raise DataMapError(f"The field {name} is required.")

    def contains(self, name: str) -> bool:
        return name in self._fields

    def get(self, name: str, default: Any = ...) -> Any:
        """Required getter: raises DataMapError when missing or null,
        unless an explicit ``default`` is supplied (dict.get compatibility)."""
        if name not in self._fields:
            if default is not ...:
                return default
            raise DataMapError(f"The field {name} is required.")
        value = self._fields[name]
        if value is None:
            if default is not ...:
                return default
            raise DataMapError(f"The required field {name} cannot be null.")
        return value

    def get_opt(self, name: str) -> Any | None:
        return self._fields.get(name)

    def get_or_else(self, name: str, default: Any) -> Any:
        value = self._fields.get(name)
        return default if value is None else value

    def get_list(self, name: str) -> list[Any]:
        value = self.get(name)
        if not isinstance(value, list):
            raise DataMapError(f"The field {name} is not an array.")
        return value

    def get_string(self, name: str) -> str:
        return str(self.get(name))

    def get_double(self, name: str) -> float:
        return float(self.get(name))

    def get_int(self, name: str) -> int:
        return int(self.get(name))

    def keyset(self) -> set[str]:
        return set(self._fields)

    def union(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        """``++`` in the reference: right-hand side wins on key conflicts."""
        merged = dict(self._fields)
        merged.update(dict(other))
        return DataMap(merged)

    def diff(self, keys: Iterable[str]) -> "DataMap":
        """``--`` in the reference: remove the listed keys."""
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def is_empty(self) -> bool:
        return not self._fields

    def to_json(self) -> str:
        return json.dumps(self._fields, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "DataMap":
        obj = json.loads(s) if s else {}
        if not isinstance(obj, dict):
            raise ValueError("DataMap JSON must be an object")
        return DataMap(obj)


EMPTY_DATAMAP = DataMap()


class PropertyMap(DataMap):
    """A DataMap produced by $set/$unset/$delete replay, carrying the first
    and last update times of the special events that built it
    (ref PropertyMap.scala:28-45).
    """

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Mapping[str, Any] | None,
        first_updated: _dt.datetime,
        last_updated: _dt.datetime,
    ):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self.fields!r}, first_updated={self.first_updated}, "
            f"last_updated={self.last_updated})"
        )

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self.fields == other.fields
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        return super().__eq__(other)

    __hash__ = DataMap.__hash__
