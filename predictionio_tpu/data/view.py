"""Batch views over the event store (deprecated API surface kept for parity).

Reference parity: ``data/src/main/scala/org/apache/predictionio/data/view/``
— ``DataView.scala`` (cached DataFrame of converted events), ``LBatchView.scala``
(``EventSeq`` filter/aggregate helpers, deprecated since 0.9.2 in favour of
``LEvents``/``LEventStore``) and ``PBatchView.scala`` (RDD flavour of the
same).

The TPU-native rendering of ``DataView.create`` is a *columnar* cache: the
conversion function maps each ``Event`` to a flat record (tuple/dataclass/
dict); the records are transposed into dense numpy columns and cached as an
``.npz`` under ``$PIO_FS_BASEDIR/view`` keyed by a content hash of
(time window, version, schema) — the same invalidation contract as the
reference's MurmurHash-named parquet file (``DataView.scala:83-104``). A
cache hit never touches the row store; columns feed ``jnp.asarray`` directly.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import hashlib
import json
import os
import re
import warnings
from typing import Any, Callable, Iterable, Mapping, Sequence

import numpy as np

from predictionio_tpu.data.datamap import DataMap

# cache filename tails: <marker><sha1-16>.npz — anchored so one view's
# prune can never touch another view whose name extends this one's prefix
_VIEW_STAMPED_RE = re.compile(r"stamp-[0-9a-f]{16}\.npz")
_VIEW_LEGACY_RE = re.compile(r"[0-9a-f]{16}\.npz")
from predictionio_tpu.data.event import Event

UTC = _dt.timezone.utc

_DEPRECATION = (
    "the batch-view API is deprecated (ref LBatchView.scala @deprecated "
    "0.9.2); use LEventStore / PEventStore instead"
)


# ---------------------------------------------------------------------------
# EventSeq — LBatchView.scala:25-180 (filter + ordered aggregation helpers)
# ---------------------------------------------------------------------------


class EventSeq:
    """A list of events with the deprecated filter/aggregate helpers
    (ref ``LBatchView.scala`` ``EventSeq`` / ``ViewPredicates`` /
    ``ViewAggregators``)."""

    def __init__(self, events: Iterable[Event]):
        warnings.warn(_DEPRECATION, DeprecationWarning, stacklevel=2)
        self.events: list[Event] = list(events)

    def __iter__(self):
        return iter(self.events)

    def __len__(self) -> int:
        return len(self.events)

    def filter(
        self,
        event: str | None = None,
        entity_type: str | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
    ) -> "EventSeq":
        """Predicate filter; note the reference's start-time predicate is
        *strictly after* start (``LBatchView.scala`` ``getStartTimePredicate``
        excludes equality), unlike LEvents' inclusive ``startTime``."""
        out = self.events
        if event is not None:
            out = [e for e in out if e.event == event]
        if entity_type is not None:
            out = [e for e in out if e.entity_type == entity_type]
        if start_time is not None:
            out = [e for e in out if e.event_time > start_time]
        if until_time is not None:
            out = [e for e in out if e.event_time < until_time]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            return EventSeq(out)

    def aggregate_by_entity_ordered(
        self,
        init: Any,
        op: Callable[[Any, Event], Any],
        predicate: Callable[[Event], bool] | None = None,
    ) -> dict[str, Any]:
        """Group by entity_id, sort each group by event_time ascending, fold
        ``op`` from ``init`` (ref ``LBatchView.scala``
        ``aggregateByEntityOrdered``)."""
        groups: dict[str, list[Event]] = {}
        for e in self.events:
            if predicate is None or predicate(e):
                groups.setdefault(e.entity_id, []).append(e)
        return {
            eid: _fold(sorted(es, key=lambda e: e.event_time), init, op)
            for eid, es in groups.items()
        }


def _fold(events: Sequence[Event], init: Any, op: Callable[[Any, Event], Any]):
    acc = init
    for e in events:
        acc = op(acc, e)
    return acc


def datamap_aggregator() -> Callable[[DataMap | None, Event], DataMap | None]:
    """The $set/$unset/$delete fold used by the deprecated views
    (ref ``ViewAggregators.getDataMapAggregator``). Prefer
    ``data.aggregator`` for the full PropertyMap replay."""

    def agg(acc: DataMap | None, e: Event) -> DataMap | None:
        if e.event == "$set":
            return e.properties if acc is None else acc.union(e.properties)
        if e.event == "$unset":
            return None if acc is None else acc.diff(e.properties.keyset())
        if e.event == "$delete":
            return None
        return acc

    return agg


# ---------------------------------------------------------------------------
# DataView — DataView.scala:41-113 (cached converted-event table)
# ---------------------------------------------------------------------------


def _record_to_dict(rec: Any) -> Mapping[str, Any]:
    if dataclasses.is_dataclass(rec) and not isinstance(rec, type):
        return dataclasses.asdict(rec)
    if isinstance(rec, Mapping):
        return rec
    if hasattr(rec, "_asdict"):  # namedtuple
        return rec._asdict()
    if isinstance(rec, (tuple, list)):
        return {f"c{i}": v for i, v in enumerate(rec)}
    raise TypeError(
        f"conversion function must return a dataclass/dict/namedtuple/tuple, got {type(rec)!r}"
    )


def _columnarise(dicts: list[Mapping[str, Any]]) -> dict[str, np.ndarray]:
    if not dicts:
        return {}
    cols: dict[str, list[Any]] = {k: [] for k in dicts[0]}
    for d in dicts:
        if d.keys() != cols.keys():
            raise ValueError("conversion function returned inconsistent fields")
        for k, v in d.items():
            cols[k].append(v)
    out: dict[str, np.ndarray] = {}
    for k, vs in cols.items():
        arr = np.asarray(vs)
        if arr.dtype == object:  # mixed / string-ish -> unicode
            arr = np.asarray([str(v) for v in vs])
        out[k] = arr
    return out


def create(
    app_name: str,
    conversion_function: Callable[[Event], Any | None],
    channel_name: str | None = None,
    start_time: _dt.datetime | None = None,
    until_time: _dt.datetime | None = None,
    name: str = "",
    version: str = "",
    base_dir: str | None = None,
    storage=None,
) -> dict[str, np.ndarray]:
    """Columnar view of ``conversion_function`` applied to an app's events,
    cached under ``<base_dir>/view`` (ref ``DataView.create``,
    ``DataView.scala:41-113``). Events mapped to ``None`` are dropped.

    Cache key: (window, version, conversion-function qualname) — the
    reference keys on (window, version, case-class serialVersionUID), i.e. an
    identity of the conversion output that does not require scanning. Bump
    ``version`` whenever the conversion function's *logic* changes.
    """
    base = base_dir or os.environ.get(
        "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".pio_store")
    )
    begin = start_time or _dt.datetime(1970, 1, 1, tzinfo=UTC)

    from predictionio_tpu.data.store.event_store import PEventStore, resolve_app

    store = PEventStore(storage)
    stamp_keyed = until_time is None
    cacheable = True
    if stamp_keyed:
        # "everything so far": key on the store's VERSION STAMP, not
        # wall-clock "now" — a now-keyed digest can never hit, so every
        # call rescanned the row store and left another npz behind
        app_id, channel_id = resolve_app(
            store._storage, app_name, channel_name
        )
        stamp = store._storage.get_p_events().version_stamp(app_id, channel_id)
        # a backend that cannot stamp cheaply returns None (base-class
        # default); keying on the constant 'stamp:None' would serve the
        # first npz forever while events accumulate — mirror snapshot.py
        # and bypass the cache instead
        cacheable = stamp is not None
        end_key = f"stamp:{stamp}"
    else:
        end_key = str(until_time)

    fn_uid = getattr(conversion_function, "__module__", "") + "." + getattr(
        conversion_function, "__qualname__", repr(conversion_function)
    )
    key_blob = json.dumps(
        [str(begin), end_key, version, fn_uid, channel_name], sort_keys=True
    ).encode()
    digest = hashlib.sha1(key_blob).hexdigest()[:16]
    view_dir = os.path.join(base, "view")
    os.makedirs(view_dir, exist_ok=True)
    prefix = f"{name or 'view'}-{app_name}-"
    # stamp-keyed entries carry a marker so the prune below can tell them
    # apart from explicit-until_time entries ("t-"), which are immutable
    # and valid forever (pruning those thrashed workloads alternating >4
    # windows); marking BOTH kinds lets pre-marker legacy files — which
    # can never be hit again under this naming — be swept instead of
    # orphaned
    marker = "stamp-" if stamp_keyed else "t-"
    path = os.path.join(view_dir, f"{prefix}{marker}{digest}.npz")

    if cacheable and os.path.exists(path):
        with np.load(path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}

    converted = []
    for e in store.find(
        app_name,
        channel_name=channel_name,
        start_time=start_time,
        until_time=until_time,
    ):
        rec = conversion_function(e)
        if rec is not None:
            converted.append(_record_to_dict(rec))

    cols = _columnarise(converted)
    if not cacheable:
        return cols
    tmp = path + ".tmp.npz"
    np.savez(tmp[:-4], **cols)
    os.replace(tmp, path)
    # bound the cache: only STAMP-keyed digests go stale as events arrive;
    # keep the newest few per (name, app) and drop the rest.
    # Explicit-until_time entries (no marker) are immutable and stay. Stat
    # per-file under try: a concurrent create() (multi-host workers share
    # the dir) may unlink an entry between listdir and the stat — that must
    # not fail a build whose own output was already written successfully.
    # Only files whose tail is EXACTLY <marker><16-hex digest>.npz belong
    # to this (name, app): plain startswith(prefix) also matched other
    # views whose name/app merely extends this prefix ('als-prod-' is a
    # string prefix of 'als-prod-eu-...'), and the legacy sweep would have
    # deleted their valid files (code-review r5).
    aged: list[tuple[float, str]] = []
    for f in os.listdir(view_dir):
        if not (f.startswith(prefix) and f.endswith(".npz")):
            continue
        rest = f[len(prefix):]
        p = os.path.join(view_dir, f)
        if _VIEW_STAMPED_RE.fullmatch(rest):
            try:
                aged.append((os.path.getmtime(p), p))
            except OSError:
                continue  # already gone
        elif _VIEW_LEGACY_RE.fullmatch(rest):
            # pre-marker legacy entry: unreachable under the marker naming
            # (never hit again), so delete rather than orphan
            try:
                os.unlink(p)
            except OSError:
                pass
        # anything else (incl. explicit-window "t-" entries and other
        # views' files) is left untouched
    for _, old in sorted(aged, reverse=True)[4:]:
        try:
            os.unlink(old)
        except OSError:
            pass
    return cols
