"""Event-server ingestion statistics, re-based on the metrics registry.

Reference parity: ``data/.../api/Stats.scala:18-82`` + ``StatsActor.scala:35-77``
— per-app counters keyed by HTTP status code and by
(entityType, targetEntityType, event), kept for the current hour and for the
server lifetime, surfaced at ``/stats.json``.

The lifetime store is now a pair of :class:`~predictionio_tpu.obs.metrics`
counters (``pio_events_ingested_total`` / ``pio_events_by_type_total``) in
the event server's registry, so the same numbers a Prometheus scrape of
``/metrics`` sees also back the legacy ``/stats.json`` JSON — one source
of truth instead of two bookkeeping paths. Hourly windows are derived by
snapshotting counter values at hour boundaries and reporting the diff;
the response shape (``currentHour`` / ``longLive`` / ``prevHour``) is
byte-compatible with the pre-registry collector.
"""

from __future__ import annotations

import datetime as _dt
import threading
from typing import Any

from predictionio_tpu.data.event import UTC, Event, format_event_time
from predictionio_tpu.obs.metrics import Counter, MetricsRegistry

# counters store label values as strings; None target_entity_type maps to ""
_NONE_TARGET = ""


def _snapshot_counter(counter: Counter) -> dict[tuple[str, ...], float]:
    return dict(counter.collect())


def _diff(
    current: dict[tuple[str, ...], float], base: dict[tuple[str, ...], float]
) -> dict[tuple[str, ...], float]:
    out: dict[tuple[str, ...], float] = {}
    for key, value in current.items():
        delta = value - base.get(key, 0.0)
        if delta > 0:
            out[key] = delta
    return out


class StatsCollector:
    """Hourly + lifetime ingestion stats on top of the metrics registry
    (ref StatsActor hour-bucketing)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or MetricsRegistry()
        # labels: (app_id, status)
        self._status = self.registry.counter(
            "pio_events_ingested_total",
            "events accepted by the collection API, by app and HTTP status",
            labelnames=("app_id", "status"),
        )
        # labels: (app_id, entity_type, target_entity_type, event)
        self._ete = self.registry.counter(
            "pio_events_by_type_total",
            "events accepted by the collection API, by app and "
            "(entityType, targetEntityType, event)",
            labelnames=("app_id", "entity_type", "target_entity_type", "event"),
        )
        now = _dt.datetime.now(tz=UTC)
        self._lock = threading.Lock()
        self._start_time = now
        self._hour_start = self._floor_hour(now)
        # counter values at the start of the current hourly window
        self._hour_base_status: dict[tuple[str, ...], float] = {}
        self._hour_base_ete: dict[tuple[str, ...], float] = {}
        # (start, end, status_diff, ete_diff) of the completed previous hour
        self._prev_hour: (
            tuple[
                _dt.datetime,
                _dt.datetime,
                dict[tuple[str, ...], float],
                dict[tuple[str, ...], float],
            ]
            | None
        ) = None

    @staticmethod
    def _floor_hour(t: _dt.datetime) -> _dt.datetime:
        return t.replace(minute=0, second=0, microsecond=0)

    def _roll(self, now: _dt.datetime) -> None:
        """Close the hourly window when the wall clock crosses an hour
        boundary: the finished window becomes ``prevHour`` (as a diff of
        counter snapshots) and the new window re-bases."""
        hour = self._floor_hour(now)
        if hour <= self._hour_start:
            return
        status_now = _snapshot_counter(self._status)
        ete_now = _snapshot_counter(self._ete)
        self._prev_hour = (
            self._hour_start,
            hour,
            _diff(status_now, self._hour_base_status),
            _diff(ete_now, self._hour_base_ete),
        )
        self._hour_base_status = status_now
        self._hour_base_ete = ete_now
        self._hour_start = hour

    def bookkeeping(self, app_id: int, status_code: int, event: Event) -> None:
        # both increments happen under the collector lock so an hour-roll
        # snapshot can never observe one counter updated and not the other
        # (statusCode vs basic totals must always agree per window)
        with self._lock:
            self._roll(_dt.datetime.now(tz=UTC))
            self._status.inc(app_id=str(app_id), status=str(status_code))
            # deliberate bounded cardinality: event shapes come from the
            # app's schema (a handful of event names/entity types per app,
            # not per-request ids) — the documented /metrics caveat in
            # docs/observability.md
            # pio-lint: disable=obs-label-cardinality -- event shapes bounded by app schema, documented caveat
            self._ete.inc(
                app_id=str(app_id),
                entity_type=event.entity_type,
                target_entity_type=event.target_entity_type or _NONE_TARGET,
                event=event.event,
            )

    @staticmethod
    def _window_json(
        app_id: int,
        start: _dt.datetime,
        end: _dt.datetime | None,
        status: dict[tuple[str, ...], float],
        ete: dict[tuple[str, ...], float],
    ) -> dict[str, Any]:
        aid = str(app_id)
        basic = [
            {
                "entityType": k[1],
                "targetEntityType": k[2] if k[2] != _NONE_TARGET else None,
                "event": k[3],
                "count": int(v),
            }
            for k, v in sorted(
                ete.items(), key=lambda item: (item[0][1], item[0][2], item[0][3])
            )
            if k[0] == aid
        ]
        status_codes = [
            {"status": int(k[1]), "count": int(v)}
            for k, v in sorted(
                status.items(), key=lambda item: int(item[0][1])
            )
            if k[0] == aid
        ]
        return {
            "startTime": format_event_time(start),
            "endTime": format_event_time(end) if end else None,
            "basic": basic,
            "statusCode": status_codes,
        }

    def get_stats(self, app_id: int) -> dict[str, Any]:
        with self._lock:
            self._roll(_dt.datetime.now(tz=UTC))
            status_now = _snapshot_counter(self._status)
            ete_now = _snapshot_counter(self._ete)
            out = {
                "currentHour": self._window_json(
                    app_id,
                    self._hour_start,
                    None,
                    _diff(status_now, self._hour_base_status),
                    _diff(ete_now, self._hour_base_ete),
                ),
                "longLive": self._window_json(
                    app_id, self._start_time, None, status_now, ete_now
                ),
            }
            if self._prev_hour is not None:
                start, end, status_diff, ete_diff = self._prev_hour
                out["prevHour"] = self._window_json(
                    app_id, start, end, status_diff, ete_diff
                )
            return out
