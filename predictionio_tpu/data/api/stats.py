"""Event-server ingestion statistics.

Reference parity: ``data/.../api/Stats.scala:18-82`` + ``StatsActor.scala:35-77``
— per-app counters keyed by HTTP status code and by
(entityType, targetEntityType, event), kept for the current hour and for the
server lifetime, surfaced at ``/stats.json``.
"""

from __future__ import annotations

import datetime as _dt
import threading
from collections import Counter
from typing import Any

from predictionio_tpu.data.event import UTC, Event, format_event_time


class Stats:
    """One counting window (ref Stats.scala)."""

    def __init__(self, start_time: _dt.datetime):
        self.start_time = start_time
        self.end_time: _dt.datetime | None = None
        self.status_code_count: Counter[tuple[int, int]] = Counter()
        self.ete_count: Counter[tuple[int, tuple[str, str | None, str]]] = Counter()

    def cutoff(self, end_time: _dt.datetime) -> None:
        self.end_time = end_time

    def update(self, app_id: int, status_code: int, event: Event) -> None:
        self.status_code_count[(app_id, status_code)] += 1
        key = (event.entity_type, event.target_entity_type, event.event)
        self.ete_count[(app_id, key)] += 1

    def snapshot(self, app_id: int) -> dict[str, Any]:
        return {
            "startTime": format_event_time(self.start_time),
            "endTime": format_event_time(self.end_time) if self.end_time else None,
            "basic": [
                {
                    "entityType": k[0],
                    "targetEntityType": k[1],
                    "event": k[2],
                    "count": v,
                }
                for (aid, k), v in sorted(
                    self.ete_count.items(),
                    key=lambda item: (item[0][0], item[0][1][0], item[0][1][1] or "", item[0][1][2]),
                )
                if aid == app_id
            ],
            "statusCode": [
                {"status": code, "count": v}
                for (aid, code), v in sorted(self.status_code_count.items())
                if aid == app_id
            ],
        }


class StatsCollector:
    """Hourly + lifetime windows (ref StatsActor hour-bucketing)."""

    def __init__(self):
        now = _dt.datetime.now(tz=UTC)
        self._lock = threading.Lock()
        self.long_live = Stats(now)
        self.hourly = Stats(self._floor_hour(now))
        self.prev_hourly: Stats | None = None

    @staticmethod
    def _floor_hour(t: _dt.datetime) -> _dt.datetime:
        return t.replace(minute=0, second=0, microsecond=0)

    def _roll(self, now: _dt.datetime) -> None:
        hour = self._floor_hour(now)
        if hour > self.hourly.start_time:
            self.hourly.cutoff(hour)
            self.prev_hourly = self.hourly
            self.hourly = Stats(hour)

    def bookkeeping(self, app_id: int, status_code: int, event: Event) -> None:
        with self._lock:
            self._roll(_dt.datetime.now(tz=UTC))
            self.long_live.update(app_id, status_code, event)
            self.hourly.update(app_id, status_code, event)

    def get_stats(self, app_id: int) -> dict[str, Any]:
        with self._lock:
            self._roll(_dt.datetime.now(tz=UTC))
            out = {
                "currentHour": self.hourly.snapshot(app_id),
                "longLive": self.long_live.snapshot(app_id),
            }
            if self.prev_hourly is not None:
                out["prevHour"] = self.prev_hourly.snapshot(app_id)
            return out
