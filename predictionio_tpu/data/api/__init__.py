"""REST event-collection API (ref ``data/.../api/EventServer.scala``)."""
