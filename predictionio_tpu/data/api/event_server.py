"""REST event-collection server (aiohttp).

Reference parity: ``data/.../api/EventServer.scala:54-663``. Route surface:

  GET  /                       -> {"status": "alive"}
  POST /events.json            -> 201 {"eventId": ...} (single event)
  GET  /events.json            -> filtered query (default limit 20)
  GET  /events/<id>.json       -> one event
  DELETE /events/<id>.json     -> {"message": "Found"} | 404
  POST /batch/events.json      -> per-event status array, <= 50 events
  GET  /stats.json             -> ingestion stats (requires --stats)
  GET  /metrics                -> Prometheus text exposition (obs registry)
  GET  /traces/recent          -> recent request spans (ring buffer)
  GET  /plugins.json           -> plugin inventory
  GET  /plugins/<type>/<name>/...  -> plugin REST surface
  POST /webhooks/<name>.json   -> JSON connector ingestion
  GET  /webhooks/<name>.json   -> connector presence check
  POST /webhooks/<name>        -> form connector ingestion

Auth (ref :92-130): ``accessKey`` query param, or HTTP Basic where the
username is the access key; per-key allowed-event enforcement; optional
``channel`` query param must name an existing channel of the key's app.

The reference's Akka actor concurrency maps to asyncio: storage calls run in
a thread pool via ``loop.run_in_executor`` so a slow backend never blocks the
event loop (the analog of Spray's detached futures).
"""

from __future__ import annotations

import asyncio
import base64
import contextvars
import dataclasses
import logging
import os
import time
from typing import Any

from aiohttp import web

from predictionio_tpu.data.api.plugins import EventInfo, EventServerPluginContext
from predictionio_tpu.data.api.stats import StatsCollector
from predictionio_tpu.data.event import Event, parse_event_time
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.data.storage.traced import trace_dao
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.profiler import (
    ProfileBusyError,
    ProfileSession,
    ProfileStore,
)
from predictionio_tpu.obs.sampler import HostSampler
from predictionio_tpu.obs.tracing import (
    TRACE_HEADER,
    Tracer,
    get_tracer,
    mint_trace_id,
    reset_trace_id,
    set_trace_id,
)
from predictionio_tpu.obs.slo import SLOEngine, counter_ratio_source
from predictionio_tpu.obs.web import (
    BreakerInstruments,
    metrics_response,
    slo_response,
    traces_response,
)
from predictionio_tpu.resilience import (
    OPEN,
    CircuitBreaker,
    CircuitOpenError,
    ResiliencePolicy,
    RetryBudget,
    RetryPolicy,
)
from predictionio_tpu.data.webhooks import (
    ConnectorException,
    connector_to_event,
    form_connector,
    json_connector,
)

logger = logging.getLogger(__name__)

MAX_EVENTS_PER_BATCH_REQUEST = 50  # ref EventServer.scala:70

# canonical routes that ARE the collection API — the availability SLO
# rates these and only these. Health checks, scrapes, and trace reads go
# through the same counting middleware; folding them into the
# denominator would let monitoring traffic mask a 100% ingestion outage.
COLLECTION_ENDPOINTS = frozenset(
    {
        "/events.json",
        "/events/{event_id}.json",
        "/batch/events.json",
        "/webhooks/{name}.json",
        "/webhooks/{name}",
    }
)


@dataclasses.dataclass
class EventServerConfig:
    ip: str = "0.0.0.0"
    port: int = 7070
    plugins: str = "plugins"
    stats: bool = False
    # TLS (ref common/SSLConfiguration.scala — the reference's keystore
    # config covers the event server too): PEM cert + key paths
    ssl_certfile: str | None = None
    ssl_keyfile: str | None = None
    # -- resilience (see docs/resilience.md) --------------------------------
    # transient storage failures retry with exponential backoff before the
    # request fails; <= 1 disables retries
    storage_retries: int = 3
    storage_backoff_s: float = 0.05
    # this many consecutive storage failures trip the breaker: requests
    # then answer 503 "storage unavailable" + Retry-After instantly instead
    # of burying a struggling backend under more timed-out work
    breaker_threshold: int = 5
    breaker_recovery_s: float = 5.0
    # ingestion availability SLO (docs/observability.md): non-5xx fraction
    # of collection-API answers, evaluated as multi-window burn rates on
    # /slo and the pio_slo_* gauges
    slo_availability_objective: float = 0.999
    # -- profiling plane (docs/observability.md §Profiling plane) ----------
    # the event server carries the same POST /profile/capture + GET
    # /profile/stacks surface as the query server: ingest stalls profile
    # the same way serving stalls do
    profile_dir: str = "pio_obs/profiles"
    profile_max_bundles: int = 20
    profile_default_ms: int = 500
    profile_max_ms: int = 10_000
    sampler_period_s: float = 0.05

    def ssl_context(self):
        from predictionio_tpu.utils.tls import server_ssl_context

        return server_ssl_context(self.ssl_certfile, self.ssl_keyfile)


class BlockedEvent(Exception):
    """An input-blocker plugin rejected the event."""


@dataclasses.dataclass
class AuthData:
    app_id: int
    channel_id: int | None
    events: tuple[str, ...]

    def allows(self, event_name: str) -> bool:
        return not self.events or event_name in self.events


def _json_error(status: int, message: str) -> web.Response:
    return web.json_response({"message": message}, status=status)


class EventServer:
    def __init__(
        self,
        storage: Storage | None = None,
        config: EventServerConfig | None = None,
        plugin_context: EventServerPluginContext | None = None,
        tracer: Tracer | None = None,
    ):
        self.storage = storage or Storage.instance()
        self.config = config or EventServerConfig()
        # DAO calls record `storage.<dao>.<method>` spans carrying the
        # ingress trace id (see docs/observability.md)
        self.tracer = tracer or get_tracer()
        self.levents = trace_dao(
            self.storage.get_l_events(), "l_events", tracer=self.tracer
        )
        self.access_keys = trace_dao(
            self.storage.get_meta_data_access_keys(),
            "access_keys",
            tracer=self.tracer,
        )
        self.channels = trace_dao(
            self.storage.get_meta_data_channels(), "channels", tracer=self.tracer
        )
        self.metrics = MetricsRegistry()
        self.stats = StatsCollector(registry=self.metrics)
        self.plugin_context = plugin_context or EventServerPluginContext()
        self._runner: web.AppRunner | None = None
        self._m_requests = self.metrics.counter(
            "pio_requests_total",
            "HTTP requests served, by route and status",
            labelnames=("endpoint", "status"),
        )
        self._m_latency = self.metrics.histogram(
            "pio_request_seconds",
            "HTTP request wall time, by route",
            labelnames=("endpoint",),
        )
        self._m_retries = self.metrics.counter(
            "pio_storage_retries_total",
            "storage calls replayed by the retry policy",
        )
        self._breaker_instruments = BreakerInstruments(self.metrics)
        # every storage touch goes through this policy: transient failures
        # retry with backoff (bounded by a per-process budget), persistent
        # failure trips the breaker and requests answer 503 "storage
        # unavailable" instead of burying the backend (see docs/resilience.md)
        self.storage_policy = ResiliencePolicy(
            retry=RetryPolicy(
                max_attempts=max(1, self.config.storage_retries),
                backoff_base_s=self.config.storage_backoff_s,
                budget=RetryBudget(),
                on_retry=lambda exc: self._m_retries.inc(),
            ),
            breaker=self._breaker_instruments.watch(
                CircuitBreaker(
                    name="eventdata",
                    failure_threshold=self.config.breaker_threshold,
                    recovery_timeout_s=self.config.breaker_recovery_s,
                )
            ),
        )
        self.metrics.register_collector(self._breaker_instruments.collect)
        # the ingestion availability objective, burning against the same
        # request counter the envelope middleware maintains (one source of
        # truth — see obs/slo.py)
        self.slo = SLOEngine(self.metrics)
        self.slo.add(
            "availability",
            "collection API answered without a 5xx",
            self.config.slo_availability_objective,
            counter_ratio_source(
                self._m_requests,
                bad=lambda l: l.get("status", "").startswith("5"),
                match=lambda l: l.get("endpoint") in COLLECTION_ENDPOINTS,
            ),
        )
        self.metrics.register_collector(self.slo.collect)
        # profiling plane (obs/profiler + obs/sampler): the ingest tier's
        # host threads (event loop + executor pool) sample into the same
        # folded-stack format the query server exports
        self.sampler = HostSampler(
            period_s=self.config.sampler_period_s
            if self.config.sampler_period_s > 0
            else 0.05,
            metrics=self.metrics,
        )
        self.profiler = ProfileSession(
            ProfileStore(
                self.config.profile_dir, self.config.profile_max_bundles
            ),
            default_ms=self.config.profile_default_ms,
            max_ms=self.config.profile_max_ms,
            context_fn=lambda: {"server": "event", "port": self.config.port},
            metrics=self.metrics,
        )

    def _capture_profile(self, ms: int | None) -> str:
        # executor-thread side: trace sleep + bundle file writes stay off
        # the event loop
        return self.profiler.capture(
            ms=ms, trigger="manual", parts={"stacks": self.sampler.snapshot()}
        )

    async def handle_profile_capture(self, request: web.Request) -> web.Response:
        raw_ms = request.query.get("ms")
        try:
            ms = int(raw_ms) if raw_ms is not None else None
        except ValueError:
            return _json_error(400, "ms must be an integer")
        try:
            path = await asyncio.get_running_loop().run_in_executor(
                None, self._capture_profile, ms
            )
        except ProfileBusyError:
            return _json_error(409, "a profile capture is already in flight")
        except Exception as exc:  # noqa: BLE001 - surface, don't 500-blank
            logger.exception("profile capture failed")
            return _json_error(500, f"capture failed: {exc}")
        return web.json_response(
            {
                "bundle": os.path.basename(path),
                "path": path,
                "durationMs": self.profiler.clamp_ms(ms),
            }
        )

    async def handle_profile_stacks(self, request: web.Request) -> web.Response:
        if request.query.get("format") == "json":
            body = self.sampler.snapshot()
            body["hotspots"] = self.sampler.hotspots()
            return web.json_response(body)
        return web.Response(
            text=self.sampler.folded(), content_type="text/plain"
        )

    @staticmethod
    def _route_label(request: web.Request) -> str:
        """Canonical route pattern (``/events/{event_id}.json``), not the
        raw path — raw paths would blow up metric label cardinality."""
        try:
            resource = request.match_info.route.resource
            if resource is not None and resource.canonical:
                return resource.canonical
        except Exception:
            pass
        return "unmatched"

    # ------------------------------------------------------------------ auth
    async def _authenticate(self, request: web.Request) -> AuthData | web.Response:
        access_key = request.query.get("accessKey")
        channel_name = request.query.get("channel")
        if access_key is None:
            auth_header = request.headers.get("Authorization", "")
            if auth_header.startswith("Basic "):
                try:
                    decoded = base64.b64decode(auth_header[6:]).decode()
                    access_key = decoded.strip().split(":")[0]
                except Exception:
                    return _json_error(401, "Invalid accessKey.")
            else:
                return _json_error(401, "Missing accessKey.")
        key = await self._storage(self.access_keys.get, access_key)
        if key is None:
            return _json_error(401, "Invalid accessKey.")
        channel_id = None
        if channel_name is not None:
            channels = await self._storage(self.channels.get_by_app_id, key.appid)
            channel_map = {c.name: c.id for c in channels}
            if channel_name not in channel_map:
                return _json_error(401, f"Invalid channel '{channel_name}'.")
            channel_id = channel_map[channel_name]
        return AuthData(key.appid, channel_id, tuple(key.events))

    async def _run(self, fn, *args):
        """Executor hop (plugin REST and other non-storage work). The
        caller's contextvars (trace id) are copied onto the worker thread —
        ``run_in_executor`` alone would drop them and storage spans would
        mint orphan trace ids instead of joining the request's trace."""
        ctx = contextvars.copy_context()
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: ctx.run(fn, *args)
        )

    async def _storage(self, fn, *args):
        """Executor hop through the storage resilience policy: transient
        failures retry with backoff, a tripped breaker raises
        ``CircuitOpenError`` (mapped to 503 by the middleware/handlers).
        Context (trace id) rides along, same as ``_run``."""
        ctx = contextvars.copy_context()
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: ctx.run(self.storage_policy.call, fn, *args)
        )

    @staticmethod
    def _storage_unavailable(exc: CircuitOpenError) -> web.Response:
        return web.json_response(
            {"message": f"storage unavailable: {exc}"},
            status=503,
            headers={"Retry-After": str(max(1, round(exc.retry_after_s)))},
        )

    def _bookkeep(self, app_id: int, status: int, event: Event) -> None:
        # always-on: the registry counters behind /metrics must see every
        # event (an increment costs nothing). The --stats flag only gates
        # SERVING the legacy /stats.json view (see handle_stats).
        self.stats.bookkeeping(app_id, status, event)

    def _insert_one(self, auth: AuthData, event: Event) -> tuple[int, dict[str, Any]]:
        """Shared blocker -> insert -> sniffer path. Runs in executor.

        Raises BlockedEvent when an input blocker rejects (-> 403); any other
        exception is a storage failure (-> 500)."""
        info = EventInfo(auth.app_id, auth.channel_id, event)
        # blockers run OUTSIDE the storage policy: a rejection is a client
        # error, and must neither be retried nor counted against the breaker
        for blocker in self.plugin_context.input_blockers.values():
            try:
                blocker.process(info, self.plugin_context)
            except Exception as exc:
                raise BlockedEvent(str(exc)) from exc
        event_id = self.storage_policy.call(
            self.levents.insert, event, auth.app_id, auth.channel_id
        )
        for sniffer in self.plugin_context.input_sniffers.values():
            try:
                sniffer.process(info, self.plugin_context)
            except Exception:  # sniffers must never fail the request
                logger.exception("input sniffer failed")
        return 201, {"eventId": event_id}

    # ---------------------------------------------------------------- routes
    async def handle_root(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "alive"})

    async def handle_healthz(self, request: web.Request) -> web.Response:
        """Readiness (distinct from `/` liveness): reports the storage
        breaker so a load balancer can drain this replica while its backend
        is unavailable instead of feeding it traffic destined for 503s."""
        snap = self.storage_policy.snapshot()
        ready = snap["breaker"]["state"] != OPEN
        return web.json_response(
            {"ready": ready, **snap}, status=200 if ready else 503
        )

    async def handle_post_event(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        try:
            payload = await request.json()
            event = Event.from_json_dict(payload)
        except Exception as exc:
            return _json_error(400, str(exc))
        if not auth.allows(event.event):
            return _json_error(403, f"{event.event} events are not allowed")
        try:
            status, body = await self._run(self._insert_one, auth, event)
        except BlockedEvent as exc:
            return _json_error(403, str(exc))
        except CircuitOpenError as exc:
            return self._storage_unavailable(exc)
        except Exception as exc:
            logger.exception("event insert failed")
            return _json_error(500, str(exc))
        self._bookkeep(auth.app_id, status, event)
        return web.json_response(body, status=status)

    async def handle_get_events(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        q = request.query
        try:
            reversed_ = q.get("reversed", "false").lower() == "true"
            if reversed_ and not (q.get("entityType") and q.get("entityId")):
                raise ValueError(
                    "the parameter reversed can only be used with both entityType "
                    "and entityId specified."
                )
            start_time = parse_event_time(q["startTime"]) if "startTime" in q else None
            until_time = parse_event_time(q["untilTime"]) if "untilTime" in q else None
            limit = int(q.get("limit", 20))
            kwargs: dict[str, Any] = dict(
                app_id=auth.app_id,
                channel_id=auth.channel_id,
                start_time=start_time,
                until_time=until_time,
                entity_type=q.get("entityType"),
                entity_id=q.get("entityId"),
                event_names=[q["event"]] if "event" in q else None,
                limit=limit,
                reversed=reversed_,
            )
            if "targetEntityType" in q:
                kwargs["target_entity_type"] = q["targetEntityType"]
            if "targetEntityId" in q:
                kwargs["target_entity_id"] = q["targetEntityId"]
        except Exception as exc:
            return _json_error(400, str(exc))  # parameter errors only
        try:
            events = list(
                await self._storage(lambda: list(self.levents.find(**kwargs)))
            )
        except CircuitOpenError as exc:
            return self._storage_unavailable(exc)
        except Exception as exc:
            # a storage failure is a server-side outage (500), never a 400:
            # load balancers and clients must see it as retryable
            logger.exception("event find failed")
            return _json_error(500, str(exc))
        if not events:
            return _json_error(404, "Not Found")
        return web.json_response([e.to_json_dict() for e in events])

    async def handle_get_event(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        event_id = request.match_info["event_id"]
        event = await self._storage(
            self.levents.get, event_id, auth.app_id, auth.channel_id
        )
        if event is None:
            return _json_error(404, "Not Found")
        return web.json_response(event.to_json_dict())

    async def handle_delete_event(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        event_id = request.match_info["event_id"]
        found = await self._storage(
            self.levents.delete, event_id, auth.app_id, auth.channel_id
        )
        if not found:
            return _json_error(404, "Not Found")
        return web.json_response({"message": "Found"})

    async def handle_batch_events(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        try:
            payload = await request.json()
            if not isinstance(payload, list):
                raise ValueError("batch request body must be a JSON array")
        except Exception as exc:
            return _json_error(400, str(exc))
        if len(payload) > MAX_EVENTS_PER_BATCH_REQUEST:
            return _json_error(
                400,
                "Batch request must have less than or equal to "
                f"{MAX_EVENTS_PER_BATCH_REQUEST} events",
            )
        # decode + allowed-event checks inline (cheap, no storage); then ONE
        # executor hop processes every insert — the per-event loop used to
        # pay 50 run_in_executor round-trips per batch request. Per-event
        # semantics are unchanged: same status array order, per-event error
        # isolation, blockers/sniffers per event, bookkeeping on 201 only.
        results: list[dict[str, Any] | None] = []
        to_insert: list[tuple[int, Event]] = []  # (result slot, event)
        for item in payload:
            try:
                event = Event.from_json_dict(item)
            except Exception as exc:
                results.append({"status": 400, "message": str(exc)})
                continue
            if not auth.allows(event.event):
                results.append(
                    {"status": 403, "message": f"{event.event} events are not allowed"}
                )
                continue
            results.append(None)
            to_insert.append((len(results) - 1, event))

        def insert_all() -> list[tuple[int, Event, int, dict[str, Any]]]:
            out = []
            for slot, event in to_insert:
                try:
                    status, body = self._insert_one(auth, event)
                except BlockedEvent as exc:
                    status, body = 403, {"message": str(exc)}
                except CircuitOpenError as exc:
                    status, body = 503, {"message": f"storage unavailable: {exc}"}
                except Exception as exc:
                    status, body = 500, {"message": str(exc)}
                out.append((slot, event, status, body))
            return out

        if to_insert:
            for slot, event, status, body in await self._run(insert_all):
                results[slot] = {"status": status, **body}
                if status == 201:
                    self._bookkeep(auth.app_id, status, event)
        return web.json_response(results)

    async def handle_stats(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        if not self.config.stats:
            return _json_error(
                404, "To see stats, launch Event Server with --stats argument."
            )
        return web.json_response(self.stats.get_stats(auth.app_id))

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition of the full registry (request
        latency/status, ingestion counters, retry/breaker state). Unlike
        ``/stats.json`` this is unauthenticated by convention — scrapers
        don't carry app access keys — and always on. OpenMetrics
        negotiation (Accept header or ``?exemplars=1``) adds per-bucket
        trace-id exemplars."""
        return metrics_response(self.metrics, request)

    async def handle_slo(self, request: web.Request) -> web.Response:
        return slo_response(self.slo)

    async def handle_traces_recent(self, request: web.Request) -> web.Response:
        return traces_response(self.tracer, request)

    async def handle_plugins_json(self, request: web.Request) -> web.Response:
        return web.json_response(self.plugin_context.to_json_dict())

    async def handle_plugin_rest(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        tail = request.match_info["tail"].split("/")
        if len(tail) < 2:
            return _json_error(404, "Not Found")
        plugin_type, plugin_name, *args = tail
        registry = (
            self.plugin_context.input_blockers
            if plugin_type == "inputblocker"
            else self.plugin_context.input_sniffers
        )
        plugin = registry.get(plugin_name)
        if plugin is None:
            return _json_error(404, f"Unknown plugin {plugin_name}")
        result = await self._run(
            plugin.handle_rest, auth.app_id, auth.channel_id, args
        )
        return web.json_response(result)

    async def handle_webhook_json(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        name = request.match_info["name"]
        connector = json_connector(name)
        if connector is None:
            return _json_error(404, f"webhooks connection for {name} is not supported.")
        if request.method == "GET":
            return web.json_response({"message": f"webhooks {name} connected."})
        try:
            payload = await request.json()
            event = connector_to_event(connector, payload)
        except (ConnectorException, ValueError) as exc:
            return _json_error(400, str(exc))
        return await self._ingest_webhook_event(auth, event)

    async def _ingest_webhook_event(
        self, auth: AuthData, event: Event
    ) -> web.Response:
        """Shared webhook tail: same allowed-events + error contract as
        POST /events.json (stricter than the reference, which skipped the
        per-key event check on webhook routes)."""
        if not auth.allows(event.event):
            return _json_error(403, f"{event.event} events are not allowed")
        try:
            status, body = await self._run(self._insert_one, auth, event)
        except BlockedEvent as exc:
            return _json_error(403, str(exc))
        except CircuitOpenError as exc:
            return self._storage_unavailable(exc)
        except Exception as exc:
            logger.exception("webhook event insert failed")
            return _json_error(500, str(exc))
        self._bookkeep(auth.app_id, status, event)
        return web.json_response(body, status=status)

    async def handle_webhook_form(self, request: web.Request) -> web.Response:
        auth = await self._authenticate(request)
        if isinstance(auth, web.Response):
            return auth
        name = request.match_info["name"]
        connector = form_connector(name)
        if connector is None:
            return _json_error(404, f"webhooks connection for {name} is not supported.")
        if request.method == "GET":
            return web.json_response({"message": f"webhooks {name} connected."})
        form = dict(await request.post())
        try:
            event = connector_to_event(connector, form)
        except (ConnectorException, ValueError) as exc:
            return _json_error(400, str(exc))
        return await self._ingest_webhook_event(auth, event)

    # ------------------------------------------------------------------- app
    def make_app(self) -> web.Application:
        @web.middleware
        async def observability(request: web.Request, handler):
            # trace ingress: accept the caller's X-Pio-Trace-Id or mint
            # one; every span below (storage DAO calls included, via the
            # contextvar copied into executor hops) joins this trace. The
            # id is echoed on the response so clients can correlate.
            trace_id = request.headers.get(TRACE_HEADER) or mint_trace_id()
            token = set_trace_id(trace_id)
            endpoint = self._route_label(request)
            status = 500  # an escaping exception is a 500 to the client
            t0 = time.perf_counter()
            try:
                with self.tracer.span(
                    "http.event",
                    kind="ingress",
                    endpoint=endpoint,
                    method=request.method,
                ) as sp:
                    resp = await handler(request)
                    status = resp.status
                    sp.tags["status"] = status
            except web.HTTPException as exc:
                status = exc.status
                raise
            finally:
                reset_trace_id(token)
                self._m_requests.inc(endpoint=endpoint, status=str(status))
                self._m_latency.observe(
                    time.perf_counter() - t0, endpoint=endpoint
                )
            resp.headers[TRACE_HEADER] = trace_id
            return resp

        @web.middleware
        async def storage_resilience(request: web.Request, handler):
            # backstop for paths without their own mapping (auth lookups,
            # single-event get/delete): an open breaker is a 503 with
            # Retry-After, never a 500 stack trace
            try:
                return await handler(request)
            except CircuitOpenError as exc:
                return self._storage_unavailable(exc)

        # observability outermost: the resilience 503s must be counted too
        app = web.Application(middlewares=[observability, storage_resilience])
        app.add_routes(
            [
                web.get("/", self.handle_root),
                web.get("/healthz", self.handle_healthz),
                web.get("/metrics", self.handle_metrics),
                web.get("/slo", self.handle_slo),
                web.get("/traces/recent", self.handle_traces_recent),
                web.post("/profile/capture", self.handle_profile_capture),
                web.get("/profile/stacks", self.handle_profile_stacks),
                web.post("/events.json", self.handle_post_event),
                web.get("/events.json", self.handle_get_events),
                web.get("/events/{event_id}.json", self.handle_get_event),
                web.delete("/events/{event_id}.json", self.handle_delete_event),
                web.post("/batch/events.json", self.handle_batch_events),
                web.get("/stats.json", self.handle_stats),
                web.get("/plugins.json", self.handle_plugins_json),
                web.get("/plugins/{tail:.+}", self.handle_plugin_rest),
                web.post("/webhooks/{name}.json", self.handle_webhook_json),
                web.get("/webhooks/{name}.json", self.handle_webhook_json),
                web.post("/webhooks/{name}", self.handle_webhook_form),
                web.get("/webhooks/{name}", self.handle_webhook_form),
            ]
        )
        return app

    async def start(self) -> None:
        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        site = web.TCPSite(
            self._runner,
            self.config.ip,
            self.config.port,
            ssl_context=self.config.ssl_context(),
        )
        await site.start()
        if self.config.sampler_period_s > 0:
            self.sampler.start()
        logger.info(
            "Event server started on %s:%d", self.config.ip, self.config.port
        )

    async def stop(self) -> None:
        self.sampler.stop()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


def create_event_server(
    config: EventServerConfig | None = None, storage: Storage | None = None
) -> EventServer:
    return EventServer(storage=storage, config=config)


def run_event_server(config: EventServerConfig | None = None) -> None:
    """Blocking entry point (ref EventServer.createEventServer + actor boot)."""
    server = create_event_server(config)
    web.run_app(
        server.make_app(),
        host=server.config.ip,
        port=server.config.port,
        ssl_context=server.config.ssl_context(),
        print=None,
    )
