"""Event-server plugin SPI.

Reference parity: ``data/.../api/EventServerPlugin.scala:34`` — two plugin
kinds: input *blockers* run synchronously in the request path and may raise to
reject an event; input *sniffers* observe asynchronously. Plugins register via
``register_plugin`` (the Python analog of JVM ``ServiceLoader`` discovery) or
via entry-point style setup in engine code.
"""

from __future__ import annotations

import abc
from typing import Any

from predictionio_tpu.data.event import Event

INPUT_BLOCKER = "inputblocker"
INPUT_SNIFFER = "inputsniffer"


class EventInfo:
    __slots__ = ("app_id", "channel_id", "event")

    def __init__(self, app_id: int, channel_id: int | None, event: Event):
        self.app_id = app_id
        self.channel_id = channel_id
        self.event = event


class EventServerPlugin(abc.ABC):
    plugin_name: str = ""
    plugin_description: str = ""
    plugin_type: str = INPUT_SNIFFER

    def start(self, context: "EventServerPluginContext") -> None:
        pass

    @abc.abstractmethod
    def process(self, event_info: EventInfo, context: "EventServerPluginContext") -> None:
        """Blockers raise to reject; sniffers observe."""

    def handle_rest(
        self, app_id: int, channel_id: int | None, args: list[str]
    ) -> Any:
        """Serve GET /plugins/<type>/<name>/... (ref handleREST)."""
        return {"message": "handleREST is not implemented."}


class EventServerPluginContext:
    """Holds the live plugin registry for one server instance."""

    def __init__(self, plugins: list[EventServerPlugin] | None = None):
        self.input_blockers: dict[str, EventServerPlugin] = {}
        self.input_sniffers: dict[str, EventServerPlugin] = {}
        # None = global registry; an EXPLICIT empty list means a
        # plugin-free server (a falsy-list fallback would let globally
        # registered blockers reject events the caller opted out of)
        for p in list(_REGISTRY) if plugins is None else plugins:
            if p.plugin_type == INPUT_BLOCKER:
                self.input_blockers[p.plugin_name] = p
            else:
                self.input_sniffers[p.plugin_name] = p

    def to_json_dict(self) -> dict[str, Any]:
        def describe(ps: dict[str, EventServerPlugin]) -> dict[str, Any]:
            return {
                n: {
                    "name": p.plugin_name,
                    "description": p.plugin_description,
                    "class": type(p).__module__ + "." + type(p).__qualname__,
                }
                for n, p in ps.items()
            }

        return {
            "plugins": {
                "inputblockers": describe(self.input_blockers),
                "inputsniffers": describe(self.input_sniffers),
            }
        }


_REGISTRY: list[EventServerPlugin] = []


def register_plugin(plugin: EventServerPlugin) -> None:
    _REGISTRY.append(plugin)


def clear_plugins() -> None:
    _REGISTRY.clear()
