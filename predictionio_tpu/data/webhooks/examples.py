"""Example connectors for custom-webhook development.

Reference parity: ``data/.../webhooks/examplejson/ExampleJsonConnector.scala``
and ``exampleform/ExampleFormConnector.scala``.
"""

from __future__ import annotations

from typing import Any, Mapping

from predictionio_tpu.data.webhooks import (
    ConnectorException,
    FormConnector,
    JsonConnector,
)


class ExampleJsonConnector(JsonConnector):
    """Expects {"type": "userAction"|"userActionItem", ...} payloads."""

    def to_event_json(self, data: Mapping[str, Any]) -> dict[str, Any]:
        msg_type = data.get("type")
        try:
            if msg_type == "userAction":
                out = {
                    "event": "userAction",
                    "entityType": "user",
                    "entityId": data["userId"],
                    "properties": data.get("properties", {}),
                }
            elif msg_type == "userActionItem":
                out = {
                    "event": data["action"],
                    "entityType": "user",
                    "entityId": data["userId"],
                    "targetEntityType": "item",
                    "targetEntityId": data["itemId"],
                    "properties": data.get("properties", {}),
                }
            else:
                raise ConnectorException(
                    f"Cannot convert unknown type {msg_type} to event JSON."
                )
        except KeyError as exc:
            raise ConnectorException(f"The field {exc} is required.") from exc
        if data.get("timestamp"):
            out["eventTime"] = data["timestamp"]
        return out


class ExampleFormConnector(FormConnector):
    """Expects type=userAction form payloads."""

    def to_event_json(self, data: Mapping[str, str]) -> dict[str, Any]:
        if data.get("type") != "userAction":
            raise ConnectorException(
                f"Cannot convert unknown type {data.get('type')} to event JSON."
            )
        try:
            out: dict[str, Any] = {
                "event": "userAction",
                "entityType": "user",
                "entityId": data["userId"],
                "properties": {
                    k: v for k, v in data.items() if k not in ("type", "userId", "timestamp")
                },
            }
        except KeyError as exc:
            raise ConnectorException(f"The field {exc} is required.") from exc
        if data.get("timestamp"):
            out["eventTime"] = data["timestamp"]
        return out
