"""Webhooks framework: adapt third-party payloads into the Event JSON contract.

Reference parity: ``data/.../webhooks/JsonConnector.scala`` /
``FormConnector.scala`` / ``ConnectorUtil.scala`` — a connector maps one
incoming JSON object (or form-field map) to event JSON, which then flows
through the standard insert path.
"""

from __future__ import annotations

import abc
from typing import Any, Mapping

from predictionio_tpu.data.event import Event


class ConnectorException(Exception):
    """Raised when a payload cannot be converted (-> HTTP 400)."""


class JsonConnector(abc.ABC):
    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, Any]) -> dict[str, Any]:
        """Map a third-party JSON object to event JSON."""


class FormConnector(abc.ABC):
    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, str]) -> dict[str, Any]:
        """Map submitted form fields to event JSON."""


def connector_to_event(connector: JsonConnector | FormConnector, data) -> Event:
    """ref ConnectorUtil.toEvent: convert then validate via the normal
    Event wire decoder."""
    return Event.from_json_dict(connector.to_event_json(data))


_JSON_CONNECTORS: dict[str, JsonConnector] = {}
_FORM_CONNECTORS: dict[str, FormConnector] = {}


def register_json_connector(name: str, connector: JsonConnector) -> None:
    _JSON_CONNECTORS[name] = connector


def register_form_connector(name: str, connector: FormConnector) -> None:
    _FORM_CONNECTORS[name] = connector


def json_connector(name: str) -> JsonConnector | None:
    _ensure_builtin()
    return _JSON_CONNECTORS.get(name)


def form_connector(name: str) -> FormConnector | None:
    _ensure_builtin()
    return _FORM_CONNECTORS.get(name)


_loaded = False


def _ensure_builtin() -> None:
    """Register the shipped connectors (ref WebhooksConnectors.scala)."""
    global _loaded
    if _loaded:
        return
    _loaded = True
    from predictionio_tpu.data.webhooks import examples, mailchimp, segmentio

    register_json_connector("segmentio", segmentio.SegmentIOConnector())
    register_form_connector("mailchimp", mailchimp.MailChimpConnector())
    register_json_connector("examplejson", examples.ExampleJsonConnector())
    register_form_connector("exampleform", examples.ExampleFormConnector())
