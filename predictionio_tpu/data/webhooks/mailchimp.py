"""MailChimp webhook (form) connector.

Reference parity: ``data/.../webhooks/mailchimp/MailChimpConnector.scala`` —
handles subscribe / unsubscribe / profile / upemail / cleaned / campaign form
payloads; ``fired_at`` is ``yyyy-MM-dd HH:mm:ss`` in UTC, converted to
ISO8601.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Mapping

from predictionio_tpu.data.event import UTC, format_event_time
from predictionio_tpu.data.webhooks import ConnectorException, FormConnector


def _fired_at(data: Mapping[str, str]) -> str:
    raw = data.get("fired_at")
    if not raw:
        raise ConnectorException("The field 'fired_at' is required.")
    try:
        t = _dt.datetime.strptime(raw, "%Y-%m-%d %H:%M:%S").replace(tzinfo=UTC)
    except ValueError as exc:
        raise ConnectorException(f"Cannot parse fired_at {raw!r}") from exc
    return format_event_time(t)


def _req(data: Mapping[str, str], key: str) -> str:
    if key not in data:
        raise ConnectorException(f"The field '{key}' is required for MailChimp data.")
    return data[key]


class MailChimpConnector(FormConnector):
    def to_event_json(self, data: Mapping[str, str]) -> dict[str, Any]:
        msg_type = data.get("type")
        if msg_type is None:
            raise ConnectorException("The field 'type' is required for MailChimp data.")
        handler = {
            "subscribe": self._subscribe,
            "unsubscribe": self._unsubscribe,
            "profile": self._profile,
            "upemail": self._upemail,
            "cleaned": self._cleaned,
            "campaign": self._campaign,
        }.get(msg_type)
        if handler is None:
            raise ConnectorException(
                f"Cannot convert unknown MailChimp data type {msg_type} to event JSON"
            )
        return handler(data)

    @staticmethod
    def _merges(data: Mapping[str, str]) -> dict[str, Any]:
        merges = {
            "EMAIL": data.get("data[merges][EMAIL]"),
            "FNAME": data.get("data[merges][FNAME]"),
            "LNAME": data.get("data[merges][LNAME]"),
        }
        if "data[merges][INTERESTS]" in data:
            merges["INTERESTS"] = data["data[merges][INTERESTS]"]
        return {k: v for k, v in merges.items() if v is not None}

    def _subscribe(self, data: Mapping[str, str]) -> dict[str, Any]:
        return {
            "event": "subscribe",
            "entityType": "user",
            "entityId": _req(data, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _req(data, "data[list_id]"),
            "eventTime": _fired_at(data),
            "properties": {
                "email": data.get("data[email]"),
                "email_type": data.get("data[email_type]"),
                "merges": self._merges(data),
                "ip_opt": data.get("data[ip_opt]"),
                "ip_signup": data.get("data[ip_signup]"),
            },
        }

    def _unsubscribe(self, data: Mapping[str, str]) -> dict[str, Any]:
        return {
            "event": "unsubscribe",
            "entityType": "user",
            "entityId": _req(data, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _req(data, "data[list_id]"),
            "eventTime": _fired_at(data),
            "properties": {
                "action": data.get("data[action]"),
                "reason": data.get("data[reason]"),
                "email": data.get("data[email]"),
                "email_type": data.get("data[email_type]"),
                "merges": self._merges(data),
                "campaign_id": data.get("data[campaign_id]"),
                "ip_opt": data.get("data[ip_opt]"),
            },
        }

    def _profile(self, data: Mapping[str, str]) -> dict[str, Any]:
        return {
            "event": "profile",
            "entityType": "user",
            "entityId": _req(data, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _req(data, "data[list_id]"),
            "eventTime": _fired_at(data),
            "properties": {
                "email": data.get("data[email]"),
                "email_type": data.get("data[email_type]"),
                "merges": self._merges(data),
                "ip_opt": data.get("data[ip_opt]"),
            },
        }

    def _upemail(self, data: Mapping[str, str]) -> dict[str, Any]:
        return {
            "event": "upemail",
            "entityType": "list",
            "entityId": _req(data, "data[list_id]"),
            "eventTime": _fired_at(data),
            "properties": {
                "new_id": data.get("data[new_id]"),
                "new_email": data.get("data[new_email]"),
                "old_email": data.get("data[old_email]"),
            },
        }

    def _cleaned(self, data: Mapping[str, str]) -> dict[str, Any]:
        return {
            "event": "cleaned",
            "entityType": "list",
            "entityId": _req(data, "data[list_id]"),
            "eventTime": _fired_at(data),
            "properties": {
                "campaign_id": data.get("data[campaign_id]"),
                "reason": data.get("data[reason]"),
                "email": data.get("data[email]"),
            },
        }

    def _campaign(self, data: Mapping[str, str]) -> dict[str, Any]:
        return {
            "event": "campaign",
            "entityType": "campaign",
            "entityId": _req(data, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _req(data, "data[list_id]"),
            "eventTime": _fired_at(data),
            "properties": {
                "subject": data.get("data[subject]"),
                "status": data.get("data[status]"),
                "reason": data.get("data[reason]"),
            },
        }
