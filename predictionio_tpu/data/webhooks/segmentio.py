"""Segment.io webhook connector.

Reference parity: ``data/.../webhooks/segmentio/SegmentIOConnector.scala`` —
supports the spec v2 message types identify / track / alias / page / screen /
group; entity is always the user (``userId`` falling back to
``anonymousId``); per-type payload fields land in ``properties`` with the
optional ``context`` object merged alongside.
"""

from __future__ import annotations

from typing import Any, Mapping

from predictionio_tpu.data.webhooks import ConnectorException, JsonConnector


class SegmentIOConnector(JsonConnector):
    TYPES = ("identify", "track", "alias", "page", "screen", "group")

    def to_event_json(self, data: Mapping[str, Any]) -> dict[str, Any]:
        if "version" not in data:
            raise ConnectorException("Failed to get segment.io API version.")
        msg_type = data.get("type")
        if msg_type not in self.TYPES:
            raise ConnectorException(
                f"Cannot convert unknown type {msg_type} to event JSON."
            )
        user_id = data.get("userId") or data.get("anonymousId")
        if not user_id:
            raise ConnectorException(
                "there was no `userId` or `anonymousId` in the common fields."
            )

        if msg_type == "identify":
            props: dict[str, Any] = {"traits": data.get("traits")}
        elif msg_type == "track":
            props = {
                "properties": data.get("properties"),
                "event": data.get("event"),
            }
        elif msg_type == "alias":
            props = {"previous_id": data.get("previousId") or data.get("previous_id")}
        elif msg_type in ("page", "screen"):
            props = {
                "name": data.get("name"),
                "properties": data.get("properties"),
            }
        else:  # group
            props = {
                "group_id": data.get("groupId") or data.get("group_id"),
                "traits": data.get("traits"),
            }
        if data.get("context") is not None:
            props["context"] = data["context"]
        props = {k: v for k, v in props.items() if v is not None}

        out: dict[str, Any] = {
            "event": msg_type,
            "entityType": "user",
            "entityId": str(user_id),
            "properties": props,
        }
        if data.get("timestamp"):
            out["eventTime"] = data["timestamp"]
        return out
