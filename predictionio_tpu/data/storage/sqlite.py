"""SQLite storage backend — the single-host development default.

Plays the role of the reference's JDBC driver
(``storage/jdbc/.../JDBCLEvents.scala`` / ``JDBCPEvents.scala`` /
``JDBCApps.scala`` etc., 2,051 LoC of scalikejdbc): a full implementation of
every DAO on one embedded SQL database. The event-column layout mirrors the
reference's JDBC DDL (``JDBCLEvents.scala:54-68``) — id, event, entityType,
entityId, targetEntityType, targetEntityId, properties JSON, eventTime +
zone, tags, prId, creationTime + zone — with timestamps stored as UTC epoch
micros plus the original offset, which preserves the wire-format round trip.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import os
import sqlite3
import threading
import uuid

import numpy as np
from typing import Iterable, Iterator, Sequence

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import UTC, Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS event_versions (
  tbl TEXT PRIMARY KEY,
  version INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS apps (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL UNIQUE,
  description TEXT
);
CREATE TABLE IF NOT EXISTS accesskeys (
  accesskey TEXT PRIMARY KEY,
  appid INTEGER NOT NULL,
  events TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS channels (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL,
  appid INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS engineinstances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  startTime INTEGER NOT NULL,
  endTime INTEGER NOT NULL,
  engineId TEXT NOT NULL,
  engineVersion TEXT NOT NULL,
  engineVariant TEXT NOT NULL,
  engineFactory TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  sparkConf TEXT NOT NULL DEFAULT '{}',
  dataSourceParams TEXT NOT NULL DEFAULT '{}',
  preparatorParams TEXT NOT NULL DEFAULT '{}',
  algorithmsParams TEXT NOT NULL DEFAULT '[]',
  servingParams TEXT NOT NULL DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS evaluationinstances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  startTime INTEGER NOT NULL,
  endTime INTEGER NOT NULL,
  evaluationClass TEXT NOT NULL DEFAULT '',
  engineParamsGeneratorClass TEXT NOT NULL DEFAULT '',
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  sparkConf TEXT NOT NULL DEFAULT '{}',
  evaluatorResults TEXT NOT NULL DEFAULT '',
  evaluatorResultsHTML TEXT NOT NULL DEFAULT '',
  evaluatorResultsJSON TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS models (
  id TEXT PRIMARY KEY,
  models BLOB NOT NULL
);
"""

_EVENT_TABLE_DDL = """
CREATE TABLE IF NOT EXISTS {table} (
  id TEXT PRIMARY KEY,
  event TEXT NOT NULL,
  entityType TEXT NOT NULL,
  entityId TEXT NOT NULL,
  targetEntityType TEXT,
  targetEntityId TEXT,
  properties TEXT,
  eventTime INTEGER NOT NULL,
  eventTimeZone TEXT NOT NULL,
  tags TEXT,
  prId TEXT,
  creationTime INTEGER NOT NULL,
  creationTimeZone TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS {table}_time ON {table} (eventTime);
CREATE INDEX IF NOT EXISTS {table}_entity ON {table} (entityType, entityId);
CREATE INDEX IF NOT EXISTS {table}_ctime ON {table} (creationTime, id);
"""


def _micros(t: _dt.datetime) -> int:
    if t.tzinfo is None:  # naive filters/timestamps are interpreted as UTC
        t = t.replace(tzinfo=_dt.timezone.utc)
    return int(t.timestamp() * 1_000_000)


def _from_micros(us: int, offset: str) -> _dt.datetime:
    t = _dt.datetime.fromtimestamp(us / 1_000_000, tz=UTC)
    if offset and offset != "Z":
        hh, _, mm = offset.lstrip("+-").partition(":")
        delta = _dt.timedelta(hours=int(hh), minutes=int(mm or 0))
        if offset.startswith("-"):
            delta = -delta
        t = t.astimezone(_dt.timezone(delta))
    return t


def _offset_of(t: _dt.datetime) -> str:
    off = t.utcoffset() or _dt.timedelta(0)
    if not off:
        return "Z"
    total = int(off.total_seconds())
    sign = "+" if total >= 0 else "-"
    total = abs(total)
    return f"{sign}{total // 3600:02d}:{(total % 3600) // 60:02d}"


def _event_table(app_id: int, channel_id: int | None) -> str:
    return f"events_{app_id}" if channel_id is None else f"events_{app_id}_{channel_id}"


def _is_missing_table(exc: sqlite3.OperationalError) -> bool:
    """Only 'no such table' means 'no events yet'; other operational errors
    (locked, I/O) must propagate instead of reading as empty data."""
    return "no such table" in str(exc)


# atomic per-table write counter bump, run inside data-write transactions
_BUMP_SQL = (
    "INSERT INTO event_versions (tbl, version) VALUES (?, 1) "
    "ON CONFLICT(tbl) DO UPDATE SET version = version + 1"
)


class SQLiteStorageClient:
    """Backend entry point (type name: ``sqlite``). Config key ``path``
    selects the database file; ``:memory:`` works for tests but is
    per-connection, so a shared connection is used throughout."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self.path = self.config.get("PATH") or self.config.get("path") or ":memory:"
        # snapshot-cache stamp disambiguator: two databases sharing one
        # snapshot root must not alias on equal (version, count); an
        # in-memory db is additionally unique per client instance
        if self.path == ":memory:":
            self.store_identity = f"sqlite:{uuid.uuid4().hex[:12]}"
        else:
            self.store_identity = f"sqlite:{os.path.abspath(self.path)}"
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._lock = threading.RLock()
        self._initialized_event_tables: set[str] = set()
        with self._lock, self._conn:
            self._conn.executescript(_SCHEMA)

    # -- connection helpers -------------------------------------------------
    def execute(self, sql: str, params: Sequence = ()) -> sqlite3.Cursor:
        with self._lock, self._conn:
            return self._conn.execute(sql, params)

    def query(self, sql: str, params: Sequence = ()) -> list[tuple]:
        with self._lock:
            return self._conn.execute(sql, params).fetchall()

    def close(self) -> None:
        self._conn.close()

    def bump_event_version(self, table: str) -> None:
        """Monotonic write counter per event table — the snapshot-cache
        stamp. Rowid/count/max-time are NOT sufficient (sqlite reuses a
        freed max rowid, so delete+reinsert could leave them unchanged)."""
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT INTO event_versions (tbl, version) VALUES (?, 1) "
                "ON CONFLICT(tbl) DO UPDATE SET version = version + 1",
                (table,),
            )

    def event_version(self, table: str) -> int:
        rows = self.query(
            "SELECT version FROM event_versions WHERE tbl = ?", (table,)
        )
        return rows[0][0] if rows else 0

    # DAO accessors used by registry reflection
    def l_events(self) -> "SQLiteLEvents":
        return SQLiteLEvents(self)

    def p_events(self) -> "SQLitePEvents":
        return SQLitePEvents(self)

    def apps(self) -> "SQLiteApps":
        return SQLiteApps(self)

    def access_keys(self) -> "SQLiteAccessKeys":
        return SQLiteAccessKeys(self)

    def channels(self) -> "SQLiteChannels":
        return SQLiteChannels(self)

    def engine_instances(self) -> "SQLiteEngineInstances":
        return SQLiteEngineInstances(self)

    def evaluation_instances(self) -> "SQLiteEvaluationInstances":
        return SQLiteEvaluationInstances(self)

    def models(self) -> "SQLiteModels":
        return SQLiteModels(self)


def _event_where(
    *,
    start_time=None,
    until_time=None,
    entity_type=None,
    entity_id=None,
    event_names=None,
    target_entity_type=...,
    target_entity_id=...,
) -> tuple[str, list]:
    """WHERE clause + params for the 9-filter event contract (shared by
    ``find`` and the raw-column columnar scan)."""
    clauses, params = [], []
    if start_time is not None:
        clauses.append("eventTime >= ?")
        params.append(_micros(start_time))
    if until_time is not None:
        clauses.append("eventTime < ?")
        params.append(_micros(until_time))
    if entity_type is not None:
        clauses.append("entityType = ?")
        params.append(entity_type)
    if entity_id is not None:
        clauses.append("entityId = ?")
        params.append(entity_id)
    if event_names is not None:
        placeholders = ",".join("?" for _ in event_names)
        clauses.append(f"event IN ({placeholders})")
        params.extend(event_names)
    if target_entity_type is not ...:
        if target_entity_type is None:
            clauses.append("targetEntityType IS NULL")
        else:
            clauses.append("targetEntityType = ?")
            params.append(target_entity_type)
    if target_entity_id is not ...:
        if target_entity_id is None:
            clauses.append("targetEntityId IS NULL")
        else:
            clauses.append("targetEntityId = ?")
            params.append(target_entity_id)
    return (f" WHERE {' AND '.join(clauses)}" if clauses else ""), params


class SQLiteLEvents(base.LEvents):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        table = _event_table(app_id, channel_id)
        if table in self._c._initialized_event_tables:
            return True
        with self._c._lock, self._c._conn:
            self._c._conn.executescript(_EVENT_TABLE_DDL.format(table=table))
            self._c._initialized_event_tables.add(table)
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        table = _event_table(app_id, channel_id)
        self._c.execute(f"DROP TABLE IF EXISTS {table}")
        self._c._initialized_event_tables.discard(table)
        self._c.bump_event_version(table)
        return True

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        self.init(app_id, channel_id)
        table = _event_table(app_id, channel_id)
        ids, rows = [], []
        for event in events:
            event_id = event.event_id or uuid.uuid4().hex
            ids.append(event_id)
            rows.append(
                (
                    event_id,
                    event.event,
                    event.entity_type,
                    event.entity_id,
                    event.target_entity_type,
                    event.target_entity_id,
                    event.properties.to_json(),
                    _micros(event.event_time),
                    _offset_of(event.event_time),
                    json.dumps(list(event.tags)),
                    event.pr_id,
                    _micros(event.creation_time),
                    _offset_of(event.creation_time),
                )
            )
        with self._c._lock, self._c._conn:
            self._c._conn.executemany(
                f"INSERT OR REPLACE INTO {table} VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)",
                rows,
            )
            # stamp bump in the same transaction: a crash can never commit
            # data without invalidating cached snapshots
            self._c._conn.execute(_BUMP_SQL, (table,))
        return ids

    @staticmethod
    def _row_to_event(row: tuple) -> Event:
        (
            event_id,
            name,
            entity_type,
            entity_id,
            tet,
            tei,
            properties,
            event_time,
            event_tz,
            tags,
            pr_id,
            creation_time,
            creation_tz,
        ) = row
        return Event(
            event=name,
            entity_type=entity_type,
            entity_id=entity_id,
            target_entity_type=tet,
            target_entity_id=tei,
            properties=DataMap.from_json(properties or "{}"),
            event_time=_from_micros(event_time, event_tz),
            event_id=event_id,
            tags=tuple(json.loads(tags or "[]")),
            pr_id=pr_id,
            creation_time=_from_micros(creation_time, creation_tz),
        )

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        table = _event_table(app_id, channel_id)
        try:
            rows = self._c.query(f"SELECT * FROM {table} WHERE id = ?", (event_id,))
        except sqlite3.OperationalError as exc:
            if _is_missing_table(exc):  # app has no events yet
                return None
            raise
        return self._row_to_event(rows[0]) if rows else None

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        table = _event_table(app_id, channel_id)
        try:
            with self._c._lock, self._c._conn:
                cur = self._c._conn.execute(
                    f"DELETE FROM {table} WHERE id = ?", (event_id,)
                )
                if cur.rowcount > 0:  # stamp bump rides the delete txn
                    self._c._conn.execute(_BUMP_SQL, (table,))
        except sqlite3.OperationalError as exc:
            if _is_missing_table(exc):
                return False
            raise
        return cur.rowcount > 0

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        table = _event_table(app_id, channel_id)
        where, params = _event_where(
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )
        order = "DESC" if reversed else "ASC"
        sql = f"SELECT * FROM {table}{where} ORDER BY eventTime {order}"
        if limit is not None and limit >= 0:
            sql += f" LIMIT {int(limit)}"
        try:
            rows = self._c.query(sql, params)
        except sqlite3.OperationalError as exc:
            if _is_missing_table(exc):  # table not yet created = no events
                return iter(())
            raise
        return (self._row_to_event(r) for r in rows)

    def find_after(
        self,
        app_id: int,
        channel_id: int | None = None,
        cursor: tuple[int, str] | None = None,
        limit: int = 100,
    ) -> list[Event]:
        """Indexed tail read on ``(creationTime, id)`` — the ordering
        contract of ``base.event_seq_key`` executed server-side. The id
        column is ASCII hex, so SQL text comparison and python string
        comparison agree on the tiebreak."""
        limit = base.check_tail_limit(limit)
        table = _event_table(app_id, channel_id)
        where, params = "", []
        if cursor is not None:
            where = " WHERE creationTime > ? OR (creationTime = ? AND id > ?)"
            params = [int(cursor[0]), int(cursor[0]), str(cursor[1])]
        sql = (
            f"SELECT * FROM {table}{where} "
            f"ORDER BY creationTime, id LIMIT {limit}"
        )
        try:
            rows = self._c.query(sql, params)
        except sqlite3.OperationalError as exc:
            if _is_missing_table(exc):
                return []
            raise
        return [self._row_to_event(r) for r in rows]

    def seq_head(
        self, app_id: int, channel_id: int | None = None
    ) -> tuple[int, str] | None:
        table = _event_table(app_id, channel_id)
        try:
            rows = self._c.query(
                f"SELECT creationTime, id FROM {table} "
                "ORDER BY creationTime DESC, id DESC LIMIT 1"
            )
        except sqlite3.OperationalError as exc:
            if _is_missing_table(exc):
                return None
            raise
        return (int(rows[0][0]), str(rows[0][1])) if rows else None


class SQLitePEvents(base.PEvents):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client
        self._l = SQLiteLEvents(client)

    def find(self, app_id: int, channel_id: int | None = None, **kw) -> Iterator[Event]:
        return self._l.find(app_id, channel_id, **kw)

    _COLUMNAR_FAST_KW = frozenset(
        (
            "event_names", "rating_key", "entity_vocab", "target_vocab",
            "start_time", "until_time", "entity_type", "entity_id",
            "target_entity_type", "target_entity_id",
        )
    )

    def to_columnar(self, app_id: int, channel_id: int | None = None, **kw):
        """Raw-column columnar scan: selects only the five encoded columns
        and lets sqlite's ``json_extract`` pull the rating out of the
        properties JSON in C. The generic path builds an Event + DataMap +
        two tz-aware datetimes per row just to throw them away — measured
        ~5x slower at the snapshot-ingest bench's 200k rows. Output is
        identical (same vocab encounter order, same codes/timestamps);
        unsupported kwargs fall back to the generic encoder."""
        rating_key = kw.get("rating_key", "rating")
        if (
            "events" in kw
            or set(kw) - self._COLUMNAR_FAST_KW
            # JSON-path metacharacters would need escaping; rare keys take
            # the generic path instead of risking a wrong path expression
            or not rating_key.replace("_", "").isalnum()
        ):
            return super().to_columnar(app_id, channel_id, **kw)
        table = _event_table(app_id, channel_id)
        where, params = _event_where(
            start_time=kw.get("start_time"),
            until_time=kw.get("until_time"),
            entity_type=kw.get("entity_type"),
            entity_id=kw.get("entity_id"),
            event_names=kw.get("event_names"),
            target_entity_type=kw.get("target_entity_type", ...),
            target_entity_id=kw.get("target_entity_id", ...),
        )
        sql = (
            f"SELECT id, event, entityId, targetEntityId, eventTime, "
            f"json_extract(properties, ?) FROM {table}{where} "
            f"ORDER BY eventTime ASC"
        )
        try:
            rows = self._c.query(sql, [f"$.{rating_key}", *params])
        except sqlite3.OperationalError as exc:
            if _is_missing_table(exc):
                rows = []
            else:
                raise
        entity_vocab = kw.get("entity_vocab")
        target_vocab = kw.get("target_vocab")
        ent_index: dict[str, int] = (
            {v: i for i, v in enumerate(entity_vocab)} if entity_vocab else {}
        )
        tgt_index: dict[str, int] = (
            {v: i for i, v in enumerate(target_vocab)} if target_vocab else {}
        )
        frozen_ent = entity_vocab is not None
        frozen_tgt = target_vocab is not None
        ev_index: dict[str, int] = {}
        n = len(rows)
        event_ids: list[str] = [""] * n
        names: list[str] = [""] * n
        ent_col = np.empty(n, np.int32)
        tgt_col = np.empty(n, np.int32)
        ev_col = np.empty(n, np.int32)
        ts_col = np.empty(n, np.float64)
        rating_col = np.empty(n, np.float32)
        for i, (eid, name, ent, tgt, micros, rating) in enumerate(rows):
            event_ids[i] = eid or ""
            names[i] = name
            if frozen_ent:
                ent_col[i] = ent_index.get(ent, -1)
            else:
                ent_col[i] = ent_index.setdefault(ent, len(ent_index))
            if tgt is None:
                tgt_col[i] = -1
            elif frozen_tgt:
                tgt_col[i] = tgt_index.get(tgt, -1)
            else:
                tgt_col[i] = tgt_index.setdefault(tgt, len(tgt_index))
            ev_col[i] = ev_index.setdefault(name, len(ev_index))
            # micros/1e6 == Event.event_time.timestamp() (tz-independent)
            ts_col[i] = micros / 1e6
            # json_extract: numbers arrive as int/float (bool as 0/1, like
            # DataMap's isinstance(int) rule); TEXT/NULL/objects -> NaN
            rating_col[i] = (
                float(rating) if isinstance(rating, (int, float)) else float("nan")
            )
        return base.ColumnarEvents(
            event_ids=event_ids,
            event_names=names,
            entity_ids=ent_col,
            target_ids=tgt_col,
            event_codes=ev_col,
            timestamps=ts_col,
            ratings=rating_col,
            entity_vocab=list(entity_vocab) if frozen_ent else list(ent_index),
            target_vocab=list(target_vocab) if frozen_tgt else list(tgt_index),
            event_vocab=list(ev_index),
        )

    def write(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> None:
        self._l.insert_batch(list(events), app_id, channel_id)

    def delete(
        self, event_ids: Iterable[str], app_id: int, channel_id: int | None = None
    ) -> None:
        ids = list(event_ids)
        if not ids:
            return
        table = _event_table(app_id, channel_id)
        # chunked DELETE ... IN + the version bump in one transaction
        # (not one txn per id, and no data-without-stamp crash window)
        try:
            with self._c._lock, self._c._conn:
                for chunk_start in range(0, len(ids), 500):
                    chunk = ids[chunk_start : chunk_start + 500]
                    placeholders = ",".join("?" for _ in chunk)
                    self._c._conn.execute(
                        f"DELETE FROM {table} WHERE id IN ({placeholders})", chunk
                    )
                self._c._conn.execute(_BUMP_SQL, (table,))
        except sqlite3.OperationalError as exc:
            if _is_missing_table(exc):
                return
            raise

    def version_stamp(self, app_id: int, channel_id: int | None = None) -> str | None:
        table = _event_table(app_id, channel_id)
        version = self._c.event_version(table)
        try:
            rows = self._c.query(f"SELECT COUNT(*) FROM {table}")
            count = rows[0][0]
        except sqlite3.OperationalError as exc:
            if not _is_missing_table(exc):
                raise
            count = 0
        return f"v{version}:{count}"

    def store_identity(self) -> str | None:
        return self._c.store_identity


class SQLiteApps(base.Apps):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, app: App) -> int | None:
        try:
            if app.id:
                self._c.execute(
                    "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                    (app.id, app.name, app.description),
                )
                return app.id
            cur = self._c.execute(
                "INSERT INTO apps (name, description) VALUES (?,?)",
                (app.name, app.description),
            )
            return cur.lastrowid
        except sqlite3.IntegrityError:
            return None

    def get(self, app_id: int) -> App | None:
        rows = self._c.query("SELECT id, name, description FROM apps WHERE id=?", (app_id,))
        return App(*rows[0]) if rows else None

    def get_by_name(self, name: str) -> App | None:
        rows = self._c.query(
            "SELECT id, name, description FROM apps WHERE name=?", (name,)
        )
        return App(*rows[0]) if rows else None

    def get_all(self) -> list[App]:
        return [App(*r) for r in self._c.query("SELECT id, name, description FROM apps ORDER BY id")]

    def update(self, app: App) -> None:
        self._c.execute(
            "UPDATE apps SET name=?, description=? WHERE id=?",
            (app.name, app.description, app.id),
        )

    def delete(self, app_id: int) -> None:
        self._c.execute("DELETE FROM apps WHERE id=?", (app_id,))


class SQLiteAccessKeys(base.AccessKeys):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, k: AccessKey) -> str | None:
        key = k.key or base.generate_access_key()
        try:
            self._c.execute(
                "INSERT INTO accesskeys (accesskey, appid, events) VALUES (?,?,?)",
                (key, k.appid, json.dumps(list(k.events))),
            )
            return key
        except sqlite3.IntegrityError:
            return None

    @staticmethod
    def _row(r: tuple) -> AccessKey:
        # JSON list; event names may contain any non-reserved characters
        raw = r[2] or "[]"
        events = json.loads(raw) if raw.startswith("[") else [e for e in raw.split(",") if e]
        return AccessKey(r[0], r[1], tuple(events))

    def get(self, key: str) -> AccessKey | None:
        rows = self._c.query("SELECT * FROM accesskeys WHERE accesskey=?", (key,))
        return self._row(rows[0]) if rows else None

    def get_all(self) -> list[AccessKey]:
        return [self._row(r) for r in self._c.query("SELECT * FROM accesskeys")]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [
            self._row(r)
            for r in self._c.query("SELECT * FROM accesskeys WHERE appid=?", (app_id,))
        ]

    def update(self, k: AccessKey) -> None:
        self._c.execute(
            "UPDATE accesskeys SET appid=?, events=? WHERE accesskey=?",
            (k.appid, json.dumps(list(k.events)), k.key),
        )

    def delete(self, key: str) -> None:
        self._c.execute("DELETE FROM accesskeys WHERE accesskey=?", (key,))


class SQLiteChannels(base.Channels):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        try:
            if channel.id:
                self._c.execute(
                    "INSERT INTO channels (id, name, appid) VALUES (?,?,?)",
                    (channel.id, channel.name, channel.appid),
                )
                return channel.id
            cur = self._c.execute(
                "INSERT INTO channels (name, appid) VALUES (?,?)",
                (channel.name, channel.appid),
            )
            return cur.lastrowid
        except sqlite3.IntegrityError:
            return None

    def get(self, channel_id: int) -> Channel | None:
        rows = self._c.query(
            "SELECT id, name, appid FROM channels WHERE id=?", (channel_id,)
        )
        return Channel(*rows[0]) if rows else None

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [
            Channel(*r)
            for r in self._c.query(
                "SELECT id, name, appid FROM channels WHERE appid=?", (app_id,)
            )
        ]

    def delete(self, channel_id: int) -> None:
        self._c.execute("DELETE FROM channels WHERE id=?", (channel_id,))


_EI_COLS = (
    "id, status, startTime, endTime, engineId, engineVersion, engineVariant, "
    "engineFactory, batch, env, sparkConf, dataSourceParams, preparatorParams, "
    "algorithmsParams, servingParams"
)


class SQLiteEngineInstances(base.EngineInstances):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, i: EngineInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        i.id = iid
        self._c.execute(
            f"INSERT OR REPLACE INTO engineinstances ({_EI_COLS}) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                iid,
                i.status,
                _micros(i.start_time),
                _micros(i.end_time),
                i.engine_id,
                i.engine_version,
                i.engine_variant,
                i.engine_factory,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.data_source_params,
                i.preparator_params,
                i.algorithms_params,
                i.serving_params,
            ),
        )
        return iid

    @staticmethod
    def _row(r: tuple) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=_from_micros(r[2], "Z"),
            end_time=_from_micros(r[3], "Z"),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8],
            env=json.loads(r[9]),
            spark_conf=json.loads(r[10]),
            data_source_params=r[11],
            preparator_params=r[12],
            algorithms_params=r[13],
            serving_params=r[14],
        )

    def get(self, instance_id: str) -> EngineInstance | None:
        rows = self._c.query(
            f"SELECT {_EI_COLS} FROM engineinstances WHERE id=?", (instance_id,)
        )
        return self._row(rows[0]) if rows else None

    def get_all(self) -> list[EngineInstance]:
        return [self._row(r) for r in self._c.query(f"SELECT {_EI_COLS} FROM engineinstances")]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        rows = self._c.query(
            f"SELECT {_EI_COLS} FROM engineinstances WHERE status=? AND engineId=? "
            "AND engineVersion=? AND engineVariant=? ORDER BY startTime DESC",
            (
                base.EngineInstanceStatus.COMPLETED,
                engine_id,
                engine_version,
                engine_variant,
            ),
        )
        return [self._row(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, i: EngineInstance) -> None:
        self.insert(i)

    def delete(self, instance_id: str) -> None:
        self._c.execute("DELETE FROM engineinstances WHERE id=?", (instance_id,))


_EVI_COLS = (
    "id, status, startTime, endTime, evaluationClass, engineParamsGeneratorClass, "
    "batch, env, sparkConf, evaluatorResults, evaluatorResultsHTML, evaluatorResultsJSON"
)


class SQLiteEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, i: EvaluationInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        i.id = iid
        self._c.execute(
            f"INSERT OR REPLACE INTO evaluationinstances ({_EVI_COLS}) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                iid,
                i.status,
                _micros(i.start_time),
                _micros(i.end_time),
                i.evaluation_class,
                i.engine_params_generator_class,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.evaluator_results,
                i.evaluator_results_html,
                i.evaluator_results_json,
            ),
        )
        return iid

    @staticmethod
    def _row(r: tuple) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=_from_micros(r[2], "Z"),
            end_time=_from_micros(r[3], "Z"),
            evaluation_class=r[4],
            engine_params_generator_class=r[5],
            batch=r[6],
            env=json.loads(r[7]),
            spark_conf=json.loads(r[8]),
            evaluator_results=r[9],
            evaluator_results_html=r[10],
            evaluator_results_json=r[11],
        )

    def get(self, instance_id: str) -> EvaluationInstance | None:
        rows = self._c.query(
            f"SELECT {_EVI_COLS} FROM evaluationinstances WHERE id=?", (instance_id,)
        )
        return self._row(rows[0]) if rows else None

    def get_all(self) -> list[EvaluationInstance]:
        return [
            self._row(r)
            for r in self._c.query(f"SELECT {_EVI_COLS} FROM evaluationinstances")
        ]

    def get_completed(self) -> list[EvaluationInstance]:
        rows = self._c.query(
            f"SELECT {_EVI_COLS} FROM evaluationinstances WHERE status=? "
            "ORDER BY startTime DESC",
            (base.EvaluationInstanceStatus.EVALCOMPLETED,),
        )
        return [self._row(r) for r in rows]

    def update(self, i: EvaluationInstance) -> None:
        self.insert(i)

    def delete(self, instance_id: str) -> None:
        self._c.execute("DELETE FROM evaluationinstances WHERE id=?", (instance_id,))


class SQLiteModels(base.Models):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, model: Model) -> None:
        self._c.execute(
            "INSERT OR REPLACE INTO models (id, models) VALUES (?,?)",
            (model.id, model.models),
        )

    def get(self, model_id: str) -> Model | None:
        rows = self._c.query("SELECT id, models FROM models WHERE id=?", (model_id,))
        return Model(rows[0][0], rows[0][1]) if rows else None

    def delete(self, model_id: str) -> None:
        self._c.execute("DELETE FROM models WHERE id=?", (model_id,))
