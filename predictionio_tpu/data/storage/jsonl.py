"""Append-only JSONL file event store.

The TPU-feed-friendly file backend: one ``events_<app>[_<channel>].jsonl``
per app/channel. Plays the role of the reference's HDFS-resident event data
for bulk training scans (ref ``storage/hbase/.../HBPEvents.scala`` via
``TableInputFormat``): training jobs stream the file once, dictionary-encode
to columnar arrays (``PEvents.to_columnar``) and never touch a SQL store.
Row wire format = the event JSON contract plus ``creationTime`` and ``tags``.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import os
import threading
import uuid
from typing import Iterable, Iterator, Sequence

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event, format_event_time, parse_event_time
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.memory import event_matches


def _event_to_row(e: Event) -> dict:
    d = e.to_json_dict(with_creation_time=True)
    if e.tags:
        d["tags"] = list(e.tags)
    return d


def _row_to_event(d: dict) -> Event:
    return Event(
        event=d["event"],
        entity_type=d["entityType"],
        entity_id=d["entityId"],
        target_entity_type=d.get("targetEntityType"),
        target_entity_id=d.get("targetEntityId"),
        properties=DataMap(d.get("properties") or {}),
        event_time=parse_event_time(d["eventTime"]),
        event_id=d.get("eventId"),
        tags=tuple(d.get("tags") or ()),
        pr_id=d.get("prId"),
        creation_time=parse_event_time(d["creationTime"])
        if d.get("creationTime")
        else parse_event_time(d["eventTime"]),
    )


class JSONLEventFiles:
    def __init__(self, basedir: str):
        self.basedir = basedir
        os.makedirs(basedir, exist_ok=True)
        self._lock = threading.RLock()

    def path(self, app_id: int, channel_id: int | None) -> str:
        name = (
            f"events_{app_id}.jsonl"
            if channel_id is None
            else f"events_{app_id}_{channel_id}.jsonl"
        )
        return os.path.join(self.basedir, name)

    def append(self, events: Sequence[Event], app_id: int, channel_id: int | None) -> None:
        with self._lock, open(self.path(app_id, channel_id), "a") as f:
            for e in events:
                f.write(json.dumps(_event_to_row(e), sort_keys=True) + "\n")

    def scan(self, app_id: int, channel_id: int | None) -> Iterator[Event]:
        """Later rows win on duplicate event ids, giving append-only upsert
        semantics consistent with the memory/sqlite backends."""
        path = self.path(app_id, channel_id)
        if not os.path.exists(path):
            return iter(())
        by_id: dict[str, Event] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    e = _row_to_event(json.loads(line))
                    by_id[e.event_id or ""] = e
        return iter(by_id.values())

    def rewrite(
        self, events: Iterable[Event], app_id: int, channel_id: int | None
    ) -> None:
        path = self.path(app_id, channel_id)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "w") as f:
                for e in events:
                    f.write(json.dumps(_event_to_row(e), sort_keys=True) + "\n")
            os.replace(tmp, path)

    def remove_ids(
        self, drop: set[str], app_id: int, channel_id: int | None
    ) -> int:
        """Atomically scan + rewrite without the dropped ids, holding the
        lock throughout so concurrent appends are never lost."""
        with self._lock:
            kept, found = [], 0
            for e in self.scan(app_id, channel_id):
                if e.event_id in drop:
                    found += 1
                else:
                    kept.append(e)
            if found:
                path = self.path(app_id, channel_id)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    for e in kept:
                        f.write(json.dumps(_event_to_row(e), sort_keys=True) + "\n")
                os.replace(tmp, path)
            return found

    def drop(self, app_id: int, channel_id: int | None) -> None:
        with self._lock:
            try:
                os.remove(self.path(app_id, channel_id))
            except FileNotFoundError:
                pass


class JSONLLEvents(base.LEvents):
    """Row API over the JSONL files. get/delete are O(file) — this backend
    is meant for bulk training feeds; use sqlite for servers that need row
    lookups."""

    def __init__(self, files: JSONLEventFiles):
        self._files = files

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        open(self._files.path(app_id, channel_id), "a").close()
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        self._files.drop(app_id, channel_id)
        return True

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        stamped = [
            e if e.event_id else dataclasses.replace(e, event_id=uuid.uuid4().hex)
            for e in events
        ]
        self._files.append(stamped, app_id, channel_id)
        return [e.event_id for e in stamped]  # type: ignore[misc]

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        for e in self._files.scan(app_id, channel_id):
            if e.event_id == event_id:
                return e
        return None

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        return self._files.remove_ids({event_id}, app_id, channel_id) > 0

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        events = [
            e
            for e in self._files.scan(app_id, channel_id)
            if event_matches(
                e,
                start_time,
                until_time,
                entity_type,
                entity_id,
                event_names,
                target_entity_type,
                target_entity_id,
            )
        ]
        events.sort(key=lambda e: e.event_time, reverse=reversed)
        if limit is not None and limit >= 0:
            events = events[:limit]
        return iter(events)

    def find_after(
        self,
        app_id: int,
        channel_id: int | None = None,
        cursor: tuple[int, str] | None = None,
        limit: int = 100,
    ) -> list[Event]:
        """Scan-based tail read; the dedup-by-id scan keeps upsert
        semantics (a re-appended event tails at its NEW creation time)."""
        return base.scan_find_after(
            self._files.scan(app_id, channel_id), cursor, limit
        )


class JSONLPEvents(base.PEvents):
    def __init__(self, files: JSONLEventFiles):
        self._files = files
        self._l = JSONLLEvents(files)

    def find(self, app_id: int, channel_id: int | None = None, **kw) -> Iterator[Event]:
        return self._l.find(app_id, channel_id, **kw)

    def write(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> None:
        self._l.insert_batch(list(events), app_id, channel_id)

    def delete(
        self, event_ids: Iterable[str], app_id: int, channel_id: int | None = None
    ) -> None:
        self._files.remove_ids(set(event_ids), app_id, channel_id)

    def version_stamp(self, app_id: int, channel_id: int | None = None) -> str | None:
        path = self._files.path(app_id, channel_id)
        try:
            st = os.stat(path)
        except FileNotFoundError:
            return "empty"
        return f"{st.st_size}:{st.st_mtime_ns}"

    def store_identity(self) -> str | None:
        # abs path of this app/store root: two jsonl stores sharing one
        # snapshot root must not alias or GC each other's snapshots
        return os.path.abspath(self._files.basedir)

    def to_columnar(
        self,
        app_id: int,
        channel_id: int | None = None,
        event_names: Sequence[str] | None = None,
        rating_key: str = "rating",
        entity_vocab: Sequence[str] | None = None,
        target_vocab: Sequence[str] | None = None,
        **find_kwargs,
    ):
        """Fast path: native C++ scan of the JSONL file when the filters are
        expressible natively (event names + entity/target types, no time
        window, no frozen vocab). Falls back to the generic python path."""
        # ``...`` is the find() "don't care" sentinel — same as not passing
        # the filter at all, so drop it before deciding on the native path
        native_kwargs = {k: v for k, v in find_kwargs.items() if v is not ...}
        # explicit None filters carry "must be absent" semantics the native
        # scanner does not express; event_names=[] means "match nothing"
        native_ok = (
            entity_vocab is None
            and target_vocab is None
            and set(native_kwargs) <= {"entity_type", "target_entity_type"}
            and native_kwargs.get("entity_type", "") is not None
            and native_kwargs.get("target_entity_type", "") is not None
            # event_names=[] means "match nothing" — handled by generic path
            and not (event_names is not None and len(list(event_names)) == 0)
        )
        if native_ok:
            from predictionio_tpu.utils.native import scan_jsonl_columnar

            raw = scan_jsonl_columnar(
                self._files.path(app_id, channel_id),
                event_names=list(event_names) if event_names else None,
                rating_key=rating_key,
                entity_type=native_kwargs.get("entity_type"),
                target_entity_type=native_kwargs.get("target_entity_type"),
            )
            if raw is not None:
                from predictionio_tpu.data.storage.base import ColumnarEvents

                names = [raw["event_vocab"][c] for c in raw["event_codes"]]
                return ColumnarEvents(
                    event_ids=raw["event_ids"],
                    event_names=names,
                    entity_ids=raw["entity_ids"],
                    target_ids=raw["target_ids"],
                    event_codes=raw["event_codes"],
                    timestamps=raw["timestamps"],
                    ratings=raw["ratings"],
                    entity_vocab=raw["entity_vocab"],
                    target_vocab=raw["target_vocab"],
                    event_vocab=raw["event_vocab"],
                )
        return super().to_columnar(
            app_id,
            channel_id,
            event_names=event_names,
            rating_key=rating_key,
            entity_vocab=entity_vocab,
            target_vocab=target_vocab,
            **find_kwargs,
        )


class JSONLStorageClient:
    """Backend entry point (type name: ``jsonl``). Config key ``PATH``
    selects the directory. Event data only."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        path = self.config.get("PATH") or self.config.get("path")
        if not path:
            path = os.path.join(os.path.expanduser("~"), ".pio_store", "events")
        self._files = JSONLEventFiles(path)

    def l_events(self) -> JSONLLEvents:
        return JSONLLEvents(self._files)

    def p_events(self) -> JSONLPEvents:
        return JSONLPEvents(self._files)
