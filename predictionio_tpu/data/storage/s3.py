"""S3 model blob store (pure-REST, AWS Signature V4, no boto).

Reference parity: ``storage/s3/.../S3Models.scala`` (model blobs only, via
the AWS SDK). This driver signs requests itself with stdlib hmac/hashlib so
no AWS package is required; it works against AWS S3 and any S3-compatible
endpoint (MinIO, Ceph RGW, GCS interop) via the ``ENDPOINT`` config key.

Config keys (``PIO_STORAGE_SOURCES_<NAME>_*``): ``BUCKET_NAME`` (required),
``REGION`` (default us-east-1), ``BASE_PATH`` (key prefix), ``ENDPOINT``
(default ``https://<bucket>.s3.<region>.amazonaws.com``; for path-style
endpoints include the bucket yourself), ``ACCESS_KEY_ID``/
``SECRET_ACCESS_KEY`` (default from AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY
env), ``DISABLE_SSL_VERIFY`` for self-hosted test endpoints.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Any

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model
from predictionio_tpu.resilience import (
    TRANSIENT_HTTP_STATUSES,
    RetryPolicy,
    mark_transient,
)


class S3Error(RuntimeError):
    """``transient`` is set True for connection failures and 5xx responses
    (safe to retry: every op here is an idempotent whole-object
    PUT/GET/DELETE) and stays False for application errors (403, 400...)."""

    transient = False


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(
    method: str,
    url: str,
    region: str,
    access_key: str,
    secret_key: str,
    payload: bytes = b"",
    now: _dt.datetime | None = None,
    service: str = "s3",
) -> dict[str, str]:
    """AWS Signature Version 4 headers for one request (the entire protocol
    the reference gets from the AWS SDK dependency). Returns the headers to
    attach: Authorization, x-amz-date, x-amz-content-sha256, host.

    ``url`` must be the exact percent-encoded form sent on the wire: for S3
    the canonical URI is the path as transmitted, so re-encoding here would
    double-encode (%20 -> %2520) and break the signature."""
    now = now or _dt.datetime.now(tz=_dt.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    parsed = urllib.parse.urlparse(url)
    host = parsed.netloc
    canonical_uri = parsed.path or "/"
    # canonical query: sorted, individually encoded
    query_pairs = sorted(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in query_pairs
    )
    payload_hash = _sha256(payload)
    canonical_headers = f"host:{host}\nx-amz-content-sha256:{payload_hash}\nx-amz-date:{amz_date}\n"
    signed_headers = "host;x-amz-content-sha256;x-amz-date"
    canonical_request = "\n".join(
        [
            method,
            canonical_uri,
            canonical_query,
            canonical_headers,
            signed_headers,
            payload_hash,
        ]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            _sha256(canonical_request.encode()),
        ]
    )
    k_date = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(
        k_signing, string_to_sign.encode(), hashlib.sha256
    ).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        ),
    }


class S3Models(base.Models):
    def __init__(
        self,
        bucket: str,
        region: str = "us-east-1",
        base_path: str = "",
        endpoint: str | None = None,
        access_key: str | None = None,
        secret_key: str | None = None,
        timeout: float = 30.0,
        disable_ssl_verify: bool = False,
        retries: int = 3,
        retry_backoff_s: float = 0.2,
    ):
        self._bucket = bucket
        self._region = region
        self._base_path = base_path.strip("/")
        self._endpoint = (
            endpoint or f"https://{bucket}.s3.{region}.amazonaws.com"
        ).rstrip("/")
        self._access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self._secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        self._timeout = timeout
        self._retry = RetryPolicy(
            max_attempts=max(1, retries), backoff_base_s=retry_backoff_s
        )
        self._ssl_context = None
        if disable_ssl_verify:
            import ssl

            self._ssl_context = ssl._create_unverified_context()

    def _url(self, model_id: str) -> str:
        safe = urllib.parse.quote(f"pio_model_{model_id}", safe="-_.~")
        prefix = f"/{self._base_path}" if self._base_path else ""
        return f"{self._endpoint}{prefix}/{safe}"

    def _request(
        self, method: str, url: str, payload: bytes = b""
    ) -> tuple[int, bytes]:
        """One logical request = up to ``retries`` wire attempts: connection
        failures and 5xx replies retry with exponential backoff (idempotent
        ops only live here, so replay is safe); 4xx return immediately."""
        return self._retry.call(self._request_once, method, url, payload)

    def _request_once(
        self, method: str, url: str, payload: bytes = b""
    ) -> tuple[int, bytes]:
        req = urllib.request.Request(url, data=payload or None, method=method)
        if self._access_key:
            for k, v in sign_v4(
                method,
                url,
                self._region,
                self._access_key,
                self._secret_key,
                payload,
            ).items():
                req.add_header(k, v)
        try:
            with urllib.request.urlopen(
                req, timeout=self._timeout, context=self._ssl_context
            ) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code in TRANSIENT_HTTP_STATUSES:
                raise mark_transient(
                    S3Error(f"{method} {url}: HTTP {exc.code}: {exc.read()[:200]!r}")
                ) from exc
            return exc.code, exc.read()
        except (urllib.error.URLError, OSError) as exc:
            raise mark_transient(S3Error(f"{method} {url}: {exc}")) from exc

    def insert(self, model: Model) -> None:
        status, body = self._request("PUT", self._url(model.id), model.models)
        if status not in (200, 201):
            raise S3Error(f"PUT model {model.id}: HTTP {status}: {body[:200]!r}")

    def get(self, model_id: str) -> Model | None:
        status, body = self._request("GET", self._url(model_id))
        if status == 404:
            return None
        if status != 200:
            raise S3Error(f"GET model {model_id}: HTTP {status}: {body[:200]!r}")
        return Model(model_id, body)

    def delete(self, model_id: str) -> None:
        status, body = self._request("DELETE", self._url(model_id))
        if status not in (200, 204, 404):
            raise S3Error(f"DELETE model {model_id}: HTTP {status}: {body[:200]!r}")


class S3StorageClient:
    """Backend entry point (type name: ``s3``)."""

    def __init__(self, config: dict[str, Any] | None = None):
        cfg = {k.upper(): v for k, v in (config or {}).items()}
        bucket = cfg.get("BUCKET_NAME")
        if not bucket:
            raise S3Error(
                "s3 storage source needs PIO_STORAGE_SOURCES_<NAME>_BUCKET_NAME"
            )
        self._models = S3Models(
            bucket=bucket,
            region=cfg.get("REGION", "us-east-1"),
            base_path=cfg.get("BASE_PATH", ""),
            endpoint=cfg.get("ENDPOINT"),
            access_key=cfg.get("ACCESS_KEY_ID"),
            secret_key=cfg.get("SECRET_ACCESS_KEY"),
            timeout=float(cfg.get("TIMEOUT", 30.0)),
            disable_ssl_verify=str(cfg.get("DISABLE_SSL_VERIFY", "")).lower()
            in ("1", "true", "yes"),
            retries=int(cfg.get("RETRIES", 3)),
            retry_backoff_s=float(cfg.get("RETRY_BACKOFF_S", 0.2)),
        )

    def models(self) -> S3Models:
        return self._models
