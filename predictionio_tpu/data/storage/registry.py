"""Storage locator: env-var driven backend discovery + repository accessors.

Reference parity: ``data/.../storage/Storage.scala:146-466`` — sources are
declared via ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ arbitrary per-source
config keys), repositories bind the three roles to sources via
``PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}``,
and DAOs are instantiated by naming convention. The reference reflects on
JVM class names (``Storage.scala:310-337``); here the convention is a backend
module registered under its type name exposing a ``*StorageClient`` class
with DAO accessor methods (``l_events()``, ``apps()``, ...).

Defaults (no env set): metadata/eventdata/modeldata all on one SQLite file
under ``$PIO_FS_BASEDIR`` (default ``~/.pio_store``) — the zero-config dev
experience the reference only reaches with a full PostgreSQL install.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

from predictionio_tpu.data.storage import base


class StorageError(RuntimeError):
    pass


# type name -> module path, client class name
_BACKENDS: dict[str, tuple[str, str]] = {
    "memory": ("predictionio_tpu.data.storage.memory", "MemoryStorageClient"),
    "sqlite": ("predictionio_tpu.data.storage.sqlite", "SQLiteStorageClient"),
    "localfs": ("predictionio_tpu.data.storage.localfs", "LocalFSStorageClient"),
    "jsonl": ("predictionio_tpu.data.storage.jsonl", "JSONLStorageClient"),
    # client/server SQL databases over DB-API (ref storage/jdbc driver);
    # driver modules are imported lazily at connect time and gated with a
    # clear error if absent (psycopg2/psycopg, pymysql/MySQLdb)
    "postgres": ("predictionio_tpu.data.storage.sql", "PostgresStorageClient"),
    "mysql": ("predictionio_tpu.data.storage.sql", "MySQLStorageClient"),
    "sql": ("predictionio_tpu.data.storage.sql", "SQLStorageClient"),
    # REST drivers, no client libraries needed (ref storage/elasticsearch,
    # storage/s3, storage/hdfs)
    "elasticsearch": (
        "predictionio_tpu.data.storage.elasticsearch",
        "ESStorageClient",
    ),
    "s3": ("predictionio_tpu.data.storage.s3", "S3StorageClient"),
    "hdfs": ("predictionio_tpu.data.storage.hdfs", "HDFSStorageClient"),
}

REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")

# guards _BACKENDS: registration may run from a plugin thread while another
# thread resolves a client class (caught by `pio lint` concurrency audit)
_backends_lock = threading.Lock()


def register_backend(type_name: str, module: str, class_name: str) -> None:
    """Third-party backends plug in here (the reference's equivalent is
    dropping a jar with conventionally-named classes on the classpath)."""
    with _backends_lock:
        _BACKENDS[type_name] = (module, class_name)


class Storage:
    """Process-wide storage locator. ``Storage.instance()`` reads the
    environment once; tests construct isolated instances directly."""

    _singleton: "Storage | None" = None
    _singleton_lock = threading.Lock()

    def __init__(self, env: dict[str, str] | None = None):
        self.env = dict(env if env is not None else os.environ)
        self._clients: dict[str, Any] = {}
        self._lock = threading.RLock()
        self._sources = self._parse_sources()
        self._repositories = self._parse_repositories()

    # -- singleton ----------------------------------------------------------
    @classmethod
    def instance(cls) -> "Storage":
        with cls._singleton_lock:
            if cls._singleton is None:
                cls._singleton = Storage()
            return cls._singleton

    @classmethod
    def reset(cls) -> None:
        with cls._singleton_lock:
            cls._singleton = None

    # -- env parsing (ref Storage.scala:158-223) ----------------------------
    def _parse_sources(self) -> dict[str, dict[str, str]]:
        sources: dict[str, dict[str, str]] = {}
        prefix = "PIO_STORAGE_SOURCES_"
        for key, value in self.env.items():
            if not key.startswith(prefix):
                continue
            rest = key[len(prefix):]
            name, _, prop = rest.partition("_")
            if not prop:
                continue
            sources.setdefault(name, {})[prop] = value
        for name, cfg in sources.items():
            if "TYPE" not in cfg:
                raise StorageError(
                    f"storage source {name} declared without "
                    f"PIO_STORAGE_SOURCES_{name}_TYPE"
                )
        return sources

    def _default_basedir(self) -> str:
        return self.env.get(
            "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".pio_store")
        )

    def _parse_repositories(self) -> dict[str, str]:
        repos: dict[str, str] = {}
        env_declared_sources = bool(self._sources)
        for repo in REPOSITORIES:
            source = self.env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
            if source is None:
                if env_declared_sources:
                    raise StorageError(
                        f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE is not set but "
                        "storage sources are configured"
                    )
                # zero-config default: one sqlite db for everything
                basedir = self._default_basedir()
                os.makedirs(basedir, exist_ok=True)
                self._sources.setdefault(
                    "DEFAULT",
                    {
                        "TYPE": "sqlite",
                        "PATH": os.path.join(basedir, "pio.db"),
                    },
                )
                source = "DEFAULT"
            elif source not in self._sources:
                raise StorageError(
                    f"repository {repo} references undeclared source {source}"
                )
            repos[repo] = source
        return repos

    # -- client / DAO instantiation -----------------------------------------
    def _client(self, source_name: str) -> Any:
        with self._lock:
            if source_name in self._clients:
                return self._clients[source_name]
            cfg = self._sources.get(source_name)
            if cfg is None:
                raise StorageError(f"undeclared storage source {source_name}")
            type_name = cfg["TYPE"].lower()
            with _backends_lock:
                entry = _BACKENDS.get(type_name)
                known = sorted(_BACKENDS)
            if entry is None:
                raise StorageError(
                    f"unknown storage backend type {type_name!r}; "
                    f"known: {known}"
                )
            module_name, class_name = entry
            import importlib

            module = importlib.import_module(module_name)
            client = getattr(module, class_name)(cfg)
            self._clients[source_name] = client
            return client

    def _dao(self, repo: str, accessor: str) -> Any:
        client = self._client(self._repositories[repo])
        fn: Callable[[], Any] | None = getattr(client, accessor, None)
        if fn is None:
            raise StorageError(
                f"storage source {self._repositories[repo]} "
                f"({type(client).__name__}) does not provide {accessor}"
            )
        return fn()

    # -- repository accessors (ref Storage.scala:401-454) --------------------
    def get_l_events(self) -> base.LEvents:
        return self._dao("EVENTDATA", "l_events")

    def get_p_events(self) -> base.PEvents:
        return self._dao("EVENTDATA", "p_events")

    def get_meta_data_apps(self) -> base.Apps:
        return self._dao("METADATA", "apps")

    def get_meta_data_access_keys(self) -> base.AccessKeys:
        return self._dao("METADATA", "access_keys")

    def get_meta_data_channels(self) -> base.Channels:
        return self._dao("METADATA", "channels")

    def get_meta_data_engine_instances(self) -> base.EngineInstances:
        return self._dao("METADATA", "engine_instances")

    def get_meta_data_evaluation_instances(self) -> base.EvaluationInstances:
        return self._dao("METADATA", "evaluation_instances")

    def get_model_data_models(self) -> base.Models:
        return self._dao("MODELDATA", "models")

    # -- health check (ref Storage.verifyAllDataObjects, used by `pio status`)
    def verify_all_data_objects(self) -> list[str]:
        """Instantiate every repository DAO; return a list of failures."""
        failures = []
        checks = [
            ("EVENTDATA l_events", self.get_l_events),
            ("EVENTDATA p_events", self.get_p_events),
            ("METADATA apps", self.get_meta_data_apps),
            ("METADATA access_keys", self.get_meta_data_access_keys),
            ("METADATA channels", self.get_meta_data_channels),
            ("METADATA engine_instances", self.get_meta_data_engine_instances),
            ("METADATA evaluation_instances", self.get_meta_data_evaluation_instances),
            ("MODELDATA models", self.get_model_data_models),
        ]
        for name, fn in checks:
            try:
                fn()
            except Exception as exc:  # noqa: BLE001 — health check reports all
                failures.append(f"{name}: {exc}")
        return failures
