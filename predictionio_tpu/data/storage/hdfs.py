"""HDFS model blob store over WebHDFS REST (no Hadoop client).

Reference parity: ``storage/hdfs/.../HDFSModels.scala`` (model blobs via the
Hadoop FileSystem API). The TPU framework talks WebHDFS — Hadoop's standard
HTTP gateway — with stdlib urllib, including the NameNode -> DataNode
redirect dance on CREATE/OPEN.

Config keys (``PIO_STORAGE_SOURCES_<NAME>_*``): ``URL`` (e.g.
``http://namenode:9870``), ``PATH`` (base dir, default ``/pio_models``),
``USERNAME`` (``user.name`` query param for simple auth).
"""

from __future__ import annotations

import urllib.error
import urllib.parse
import urllib.request
from typing import Any

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model
from predictionio_tpu.resilience import (
    TRANSIENT_HTTP_STATUSES,
    RetryPolicy,
    mark_transient,
)


class HDFSError(RuntimeError):
    """``transient`` is True for connection failures and 5xx responses —
    safe to retry because every operation here is idempotent (CREATE with
    overwrite, OPEN, DELETE)."""

    transient = False


class WebHDFSModels(base.Models):
    def __init__(
        self,
        url: str,
        base_path: str = "/pio_models",
        username: str | None = None,
        timeout: float = 30.0,
        retries: int = 3,
        retry_backoff_s: float = 0.2,
    ):
        self._url = url.rstrip("/")
        self._base = "/" + base_path.strip("/")
        self._username = username
        self._timeout = timeout
        # retries re-run the WHOLE NameNode -> DataNode dance: a DataNode
        # that died mid-redirect gets a fresh placement on the next attempt
        self._retry = RetryPolicy(
            max_attempts=max(1, retries), backoff_base_s=retry_backoff_s
        )

    def _op_url(self, model_id: str, op: str, **params: str) -> str:
        safe = urllib.parse.quote(f"pio_model_{model_id}", safe="-_.~")
        q = {"op": op, **params}
        if self._username:
            q["user.name"] = self._username
        return (
            f"{self._url}/webhdfs/v1{self._base}/{safe}?"
            + urllib.parse.urlencode(q)
        )

    def _request(
        self,
        method: str,
        url: str,
        payload: bytes | None = None,
        follow_redirect: bool = True,
        redirect_payload: bytes | None = None,
    ) -> tuple[int, bytes]:
        """One HTTP call; on a NameNode 301/302/307 re-issues against the
        DataNode ``Location`` with ``redirect_payload`` (the WebHDFS CREATE
        protocol sends NO body to the NameNode — only the DataNode gets the
        file bytes)."""
        req = urllib.request.Request(url, data=payload, method=method)
        req.add_header("Content-Type", "application/octet-stream")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code in (301, 302, 307) and follow_redirect:
                location = exc.headers.get("Location")
                if location:
                    return self._request(
                        method, location, redirect_payload, False
                    )
            return exc.code, exc.read()
        except (urllib.error.URLError, OSError) as exc:
            raise mark_transient(HDFSError(f"{method} {url}: {exc}")) from exc

    @staticmethod
    def _check(status: int, body: bytes, ok: tuple[int, ...], what: str) -> None:
        if status in ok:
            return
        err = HDFSError(f"{what}: HTTP {status}: {body[:200]!r}")
        if status in TRANSIENT_HTTP_STATUSES:
            mark_transient(err)
        raise err

    def insert(self, model: Model) -> None:
        def once() -> None:
            # two-step write: body-less CREATE against the NameNode, then PUT
            # the bytes at the DataNode the 307 redirect names
            status, body = self._request(
                "PUT",
                self._op_url(model.id, "CREATE", overwrite="true"),
                payload=None,
                redirect_payload=model.models,
            )
            self._check(status, body, (200, 201), f"CREATE {model.id}")

        self._retry.call(once)

    def get(self, model_id: str) -> Model | None:
        def once() -> Model | None:
            status, body = self._request("GET", self._op_url(model_id, "OPEN"))
            if status == 404:
                return None
            self._check(status, body, (200,), f"OPEN {model_id}")
            return Model(model_id, body)

        return self._retry.call(once)

    def delete(self, model_id: str) -> None:
        def once() -> None:
            status, body = self._request(
                "DELETE", self._op_url(model_id, "DELETE")
            )
            self._check(status, body, (200, 404), f"DELETE {model_id}")

        self._retry.call(once)


class HDFSStorageClient:
    """Backend entry point (type name: ``hdfs``)."""

    def __init__(self, config: dict[str, Any] | None = None):
        cfg = {k.upper(): v for k, v in (config or {}).items()}
        url = cfg.get("URL")
        if not url:
            raise HDFSError("hdfs storage source needs PIO_STORAGE_SOURCES_<NAME>_URL")
        self._models = WebHDFSModels(
            url=url,
            base_path=cfg.get("PATH", "/pio_models"),
            username=cfg.get("USERNAME"),
            timeout=float(cfg.get("TIMEOUT", 30.0)),
            retries=int(cfg.get("RETRIES", 3)),
            retry_backoff_s=float(cfg.get("RETRY_BACKOFF_S", 0.2)),
        )

    def models(self) -> WebHDFSModels:
        return self._models
