"""Local-filesystem model blob store.

Reference parity: ``storage/localfs/.../LocalFSModels.scala`` (files named
``pio_model_<id>`` under a base dir) — also subsumes the hdfs and s3 drivers'
role (model blobs only) for single-host deployments.
"""

from __future__ import annotations

import os

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model


class LocalFSModels(base.Models):
    def __init__(self, basedir: str):
        self._basedir = basedir
        os.makedirs(basedir, exist_ok=True)

    def _path(self, model_id: str) -> str:
        safe = model_id.replace(os.sep, "_")
        return os.path.join(self._basedir, f"pio_model_{safe}")

    def insert(self, model: Model) -> None:
        tmp = self._path(model.id) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(model.models)
        os.replace(tmp, self._path(model.id))

    def get(self, model_id: str) -> Model | None:
        path = self._path(model_id)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return Model(model_id, f.read())

    def delete(self, model_id: str) -> None:
        try:
            os.remove(self._path(model_id))
        except FileNotFoundError:
            pass


class LocalFSStorageClient:
    """Backend entry point (type name: ``localfs``). Config key ``PATH``
    selects the directory."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        path = self.config.get("PATH") or self.config.get("path")
        if not path:
            path = os.path.join(os.path.expanduser("~"), ".pio_store", "models")
        self._models = LocalFSModels(path)

    def models(self) -> LocalFSModels:
        return self._models
