"""Local-filesystem model blob store.

Reference parity: ``storage/localfs/.../LocalFSModels.scala`` (files named
``pio_model_<id>`` under a base dir) — also subsumes the hdfs and s3 drivers'
role (model blobs only) for single-host deployments.
"""

from __future__ import annotations

import os

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model
from predictionio_tpu.resilience import RetryPolicy


def _retryable_os_error(exc: BaseException) -> bool:
    """Worth replaying on a network filesystem (NFS/FUSE mounts drop I/O
    under load); a missing file is a result, not a fault."""
    return isinstance(exc, OSError) and not isinstance(exc, FileNotFoundError)


class LocalFSModels(base.Models):
    def __init__(self, basedir: str, retries: int = 3):
        self._basedir = basedir
        self._retry = RetryPolicy(
            max_attempts=max(1, retries),
            backoff_base_s=0.05,
            retry_on=_retryable_os_error,
        )
        os.makedirs(basedir, exist_ok=True)

    def _path(self, model_id: str) -> str:
        safe = model_id.replace(os.sep, "_")
        return os.path.join(self._basedir, f"pio_model_{safe}")

    def insert(self, model: Model) -> None:
        def once() -> None:
            tmp = self._path(model.id) + ".tmp"
            with open(tmp, "wb") as f:
                f.write(model.models)
            os.replace(tmp, self._path(model.id))

        self._retry.call(once)

    def get(self, model_id: str) -> Model | None:
        def once() -> Model | None:
            path = self._path(model_id)
            if not os.path.exists(path):
                return None
            try:
                with open(path, "rb") as f:
                    return Model(model_id, f.read())
            except FileNotFoundError:  # deleted between exists() and open()
                return None

        return self._retry.call(once)

    def delete(self, model_id: str) -> None:
        def once() -> None:
            try:
                os.remove(self._path(model_id))
            except FileNotFoundError:
                pass

        self._retry.call(once)


class LocalFSStorageClient:
    """Backend entry point (type name: ``localfs``). Config key ``PATH``
    selects the directory."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        path = self.config.get("PATH") or self.config.get("path")
        if not path:
            path = os.path.join(os.path.expanduser("~"), ".pio_store", "models")
        retries = int(self.config.get("RETRIES") or self.config.get("retries") or 3)
        self._models = LocalFSModels(path, retries=retries)

    def models(self) -> LocalFSModels:
        return self._models
