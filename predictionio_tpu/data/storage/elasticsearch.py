"""Elasticsearch storage driver (REST, no client library).

Reference parity: ``storage/elasticsearch/`` (5.x low-level REST driver) —
meta DAOs + events L+P + an ``ESSequences`` id generator
(``storage/elasticsearch/src/main/scala/.../ESApps.scala`` etc.; query DSL
construction in ``ESUtils.scala``). The reference's Spark-side
``ESPEvents`` reads via the elasticsearch-hadoop input format; here the
bulk path is the same filtered ``_search`` scan feeding the shared
``to_columnar`` dictionary-encoder (the TPU ingest path).

Transport is stdlib ``urllib`` against one or more ``http(s)://host:port``
endpoints; no Elasticsearch client package is required. Config keys
(``PIO_STORAGE_SOURCES_<NAME>_*``): ``HOSTS`` (comma-sep), ``PORTS``
(comma-sep, default 9200), ``SCHEMES`` (default http), or a single ``URL``;
``INDEX_PREFIX`` (default ``pio``); ``USERNAME``/``PASSWORD`` for basic
auth. Writes use ``?refresh=true`` so reads are immediately consistent —
the reference does the same (``ESUtils.scala`` index requests with
RefreshPolicy).
"""

from __future__ import annotations

import base64
import datetime as _dt
import dataclasses
import json
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Any, Iterable, Iterator, Sequence

from predictionio_tpu.data.event import Event, format_event_time, parse_event_time
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
)

UTC = _dt.timezone.utc


class ESError(RuntimeError):
    """Transport/application error from the ES driver.

    Bulk-insert partial failures attach ``indexed_ids`` (documents that DID
    land) and ``attempted_ids`` (the full batch's ids, in order) — see
    ``ESLEvents.insert_batch`` for the retry contract. ``transient`` is
    True when EVERY endpoint failed at the connection level (the cluster
    may come back; outer retry policies may replay).
    """

    transient = False
    indexed_ids: list[str] = []
    attempted_ids: list[str] = []


def _all_endpoints_failed(last: Exception | None) -> ESError:
    from predictionio_tpu.resilience import mark_transient

    return mark_transient(ESError(f"all elasticsearch endpoints failed: {last}"))


class _ESTransport:
    """Minimal JSON-over-HTTP transport with host rotation.

    ``retries`` > 1 adds full-rotation passes with exponential backoff: one
    pass tries every endpoint once (the original failover), later passes
    give a briefly-unreachable cluster time to come back before the driver
    reports it down."""

    def __init__(
        self,
        urls: list[str],
        auth: str | None = None,
        timeout: float = 10.0,
        retries: int = 1,
        retry_backoff_s: float = 0.2,
    ):
        if not urls:
            raise ESError("elasticsearch driver needs at least one endpoint")
        self.urls = urls
        self.auth = auth
        self.timeout = timeout
        from predictionio_tpu.resilience import RetryPolicy

        self._retry = RetryPolicy(
            max_attempts=max(1, retries), backoff_base_s=retry_backoff_s
        )

    def request(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        params: dict[str, str] | None = None,
        ok_statuses: tuple[int, ...] = (),
    ) -> dict[str, Any]:
        return self._retry.call(
            self._request_pass, method, path, body, params, ok_statuses
        )

    def _request_pass(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        params: dict[str, str] | None = None,
        ok_statuses: tuple[int, ...] = (),
    ) -> dict[str, Any]:
        q = f"?{urllib.parse.urlencode(params)}" if params else ""
        data = json.dumps(body).encode() if body is not None else None
        last: Exception | None = None
        for url in self.urls:
            req = urllib.request.Request(
                url.rstrip("/") + path + q, data=data, method=method
            )
            req.add_header("Content-Type", "application/json")
            if self.auth:
                req.add_header("Authorization", f"Basic {self.auth}")
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    payload = resp.read()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as exc:
                if exc.code in ok_statuses:
                    try:
                        return json.loads(exc.read() or b"{}")
                    except Exception:
                        return {}
                # HTTP error from a live node is an application error, not a
                # transport failure: report it as such (and don't retry other
                # endpoints — they'd return the same thing)
                raise ESError(
                    f"{method} {path}: HTTP {exc.code}: {exc.read()[:200]!r}"
                ) from exc
            except (urllib.error.URLError, OSError) as exc:
                if not _retry_safe(method, path, exc):
                    # the request may have been APPLIED before the connection
                    # died; replaying a non-idempotent op on another endpoint
                    # double-executes it (_update double-increments a
                    # sequence; a replayed _create 409s and orphans its
                    # sentinel). Surface the ambiguity instead — and tell
                    # outer retry policies not to replay either.
                    raise ESError(
                        f"{method} {path}: connection failed after send and "
                        f"the operation is not idempotent — not retried on "
                        f"another endpoint: {exc}"
                    ) from exc
                last = exc  # node down: try the next endpoint
        raise _all_endpoints_failed(last) from last

    def bulk(self, lines: list[dict], params: dict[str, str] | None = None) -> dict:
        """POST newline-delimited JSON to ``/_bulk``."""
        return self._retry.call(self._bulk_pass, lines, params)

    def _bulk_pass(
        self, lines: list[dict], params: dict[str, str] | None = None
    ) -> dict:
        q = f"?{urllib.parse.urlencode(params)}" if params else ""
        data = ("\n".join(json.dumps(line) for line in lines) + "\n").encode()
        last: Exception | None = None
        for url in self.urls:
            req = urllib.request.Request(
                url.rstrip("/") + "/_bulk" + q, data=data, method="POST"
            )
            req.add_header("Content-Type", "application/x-ndjson")
            if self.auth:
                req.add_header("Authorization", f"Basic {self.auth}")
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as exc:
                raise ESError(
                    f"_bulk: HTTP {exc.code}: {exc.read()[:200]!r}"
                ) from exc
            except (urllib.error.URLError, OSError) as exc:
                # bulk bodies here carry only explicit-_id index/delete
                # actions (idempotent overwrite/delete), so cross-endpoint
                # replay after an ambiguous failure is safe
                last = exc
        raise _all_endpoints_failed(last) from last


def _retry_safe(method: str, path: str, exc: Exception) -> bool:
    """May this failed request be replayed on another endpoint?

    Everything except the two genuinely non-idempotent operations:
    ``_update`` scripts (a replay re-applies the script — a sequence
    counter would double-increment) and ``_create`` (a replay 409s and
    the caller misreads "already taken"). POST reads (_search, _count,
    scroll) and addressed-document PUT/DELETE writes are idempotent and
    keep the multi-endpoint failover a dead node depends on. A refused
    connection never reached the server and is always safe.
    """
    reason = getattr(exc, "reason", exc)
    if isinstance(reason, ConnectionRefusedError):
        return True
    return "/_update/" not in path and "/_create/" not in path


def _iso(ts: _dt.datetime | None) -> str | None:
    return ts.isoformat() if ts is not None else None


def _parse_iso(s: str | None) -> _dt.datetime | None:
    return _dt.datetime.fromisoformat(s) if s else None


# Dynamic mapping would analyze strings as text, so term queries on values
# like "$set" or "MyApp1" would match nothing on a real server (the mock does
# exact equality and can't catch this). Every index is created with string
# fields mapped to keyword and *Time fields to date. The explicit
# ``properties`` exist because dynamic templates only materialize mappings
# as documents arrive: a sorted query against an EMPTY index 400s on a real
# server ("No mapping found for [eventTime] in order to sort on") unless the
# sorted fields are mapped at creation — which broke every fresh-app read,
# version stamp, and first-deploy instance lookup (code-review r4).
_INDEX_MAPPINGS = {
    "mappings": {
        "dynamic_templates": [
            {
                "times_as_dates": {
                    "match": "*Time",
                    "mapping": {"type": "date"},
                }
            },
            {
                "strings_as_keywords": {
                    "match_mapping_type": "string",
                    "mapping": {"type": "keyword"},
                }
            },
        ],
        # every field any DAO sorts on, shared across index types (an
        # unused mapping is harmless; an unmapped sort field is a 400)
        "properties": {
            "eventTime": {"type": "date"},
            "creationTime": {"type": "date"},
            "eventId": {"type": "keyword"},
            "startTime": {"type": "date"},
            "endTime": {"type": "date"},
        },
    }
}


def _ensure_index(transport: _ESTransport, index: str) -> None:
    out = transport.request(
        "PUT", f"/{index}", body=_INDEX_MAPPINGS, ok_statuses=(400,)
    )
    err = out.get("error")
    if err is None:
        return
    # only "already exists" may be swallowed: any other 400 (invalid index
    # name, rejected mapping body) would otherwise let the first write
    # auto-create the index with analyzed-text dynamic mappings, where every
    # term query silently matches nothing. Real ES wraps the type in a dict;
    # the protocol mock reports it as a bare string.
    etype = err.get("type") if isinstance(err, dict) else err
    if "resource_already_exists" not in str(etype):
        raise ESError(f"index create {index} failed: {err}")


# ---------------------------------------------------------------------------
# Sequences (ref ESSequences.scala — atomic id generator)
# ---------------------------------------------------------------------------


class ESSequences:
    def __init__(self, transport: _ESTransport, index: str):
        self._t = transport
        self._index = index

    def gen_next(self, name: str) -> int:
        out = self._t.request(
            "POST",
            f"/{self._index}/_update/{urllib.parse.quote(name)}",
            body={
                "script": {"source": "ctx._source.n += 1", "lang": "painless"},
                "upsert": {"n": 1},
            },
            params={"refresh": "true", "_source": "true"},
        )
        try:
            return int(out["get"]["_source"]["n"])
        except KeyError as exc:  # pragma: no cover - malformed server reply
            raise ESError(f"sequence response missing counter: {out}") from exc


# ---------------------------------------------------------------------------
# Generic doc-store helpers for the metadata DAOs
# ---------------------------------------------------------------------------


class _ESDocs:
    def __init__(self, transport: _ESTransport, index: str):
        self._t = transport
        self._index = index

    def put(self, doc_id: str, doc: dict) -> None:
        self._t.request(
            "PUT",
            f"/{self._index}/_doc/{urllib.parse.quote(str(doc_id))}",
            body=doc,
            params={"refresh": "true"},
        )

    def create(self, doc_id: str, doc: dict) -> bool:
        """Atomic create-if-absent (``_create`` endpoint); False on 409.
        The check-then-put alternative races under concurrent writers."""
        out = self._t.request(
            "PUT",
            f"/{self._index}/_create/{urllib.parse.quote(str(doc_id))}",
            body=doc,
            params={"refresh": "true"},
            ok_statuses=(409,),
        )
        return out.get("result") == "created"

    def get(self, doc_id: str) -> dict | None:
        out = self._t.request(
            "GET",
            f"/{self._index}/_doc/{urllib.parse.quote(str(doc_id))}",
            ok_statuses=(404,),
        )
        return out.get("_source") if out.get("found") else None

    def delete(self, doc_id: str) -> bool:
        out = self._t.request(
            "DELETE",
            f"/{self._index}/_doc/{urllib.parse.quote(str(doc_id))}",
            params={"refresh": "true"},
            ok_statuses=(404,),
        )
        return out.get("result") == "deleted"

    def search(
        self,
        query: dict,
        size: int = 10_000,
        sort: list | None = None,
        search_after: list | None = None,
    ) -> list[dict]:
        body: dict[str, Any] = {"query": query, "size": size}
        if sort:
            body["sort"] = sort
        if search_after is not None:
            body["search_after"] = search_after
        out = self._t.request(
            "POST", f"/{self._index}/_search", body=body, ok_statuses=(404,)
        )
        hits = out.get("hits", {}).get("hits", [])
        return [h["_source"] for h in hits]

    def scan(
        self, query: dict, sort: list[dict], page_size: int = 5_000
    ) -> Iterator[dict]:
        """Deep pagination via search_after (a plain size cap dies at ES's
        10k index.max_result_window). ``sort`` fields must exist in every
        document so the cursor tuple is well-defined."""
        fields = [next(iter(s)) for s in sort]
        cursor: list | None = None
        while True:
            page = self.search(query, size=page_size, sort=sort, search_after=cursor)
            yield from page
            if len(page) < page_size:
                return
            cursor = [page[-1][f] for f in fields]

    def scan_sliced(
        self,
        query: dict,
        slice_id: int,
        n_slices: int,
        page_size: int = 5_000,
    ) -> Iterator[dict]:
        """One slice of a sliced scroll (the official ES parallel-scan
        protocol: ``"slice": {"id": i, "max": n}`` on a scroll search).
        The n slices partition the index disjointly, so n concurrent
        scanners cover it exactly once — the ES answer to HBase
        region-split parallel scans (ref ``HBPEvents.scala:63-95``) and
        what elasticsearch-hadoop does per input split
        (ref ``ESPEvents.scala:44-100``)."""
        body: dict[str, Any] = {"query": query, "size": page_size}
        if n_slices > 1:
            body["slice"] = {"id": slice_id, "max": n_slices}
        out = self._t.request(
            "POST",
            f"/{self._index}/_search",
            body=body,
            params={"scroll": "5m"},
            ok_statuses=(404,),
        )
        scroll_id = out.get("_scroll_id")
        try:
            while True:
                hits = out.get("hits", {}).get("hits", [])
                if not hits:
                    return
                for h in hits:
                    yield h["_source"]
                if scroll_id is None:
                    return
                out = self._t.request(
                    "POST",
                    "/_search/scroll",
                    body={"scroll": "5m", "scroll_id": scroll_id},
                )
                scroll_id = out.get("_scroll_id", scroll_id)
        finally:
            if scroll_id is not None:
                # best-effort release of the server-side scroll context — a
                # cleanup flake must not turn an already-complete scan into
                # a failure (the context expires server-side regardless)
                try:
                    self._t.request(
                        "DELETE",
                        "/_search/scroll",
                        body={"scroll_id": [scroll_id]},
                        ok_statuses=(404,),
                    )
                except (ESError, OSError):
                    pass

    def delete_by_query(self, query: dict) -> None:
        self._t.request(
            "POST",
            f"/{self._index}/_delete_by_query",
            body={"query": query},
            params={"refresh": "true"},
            ok_statuses=(404,),
        )


# ---------------------------------------------------------------------------
# Metadata DAOs (ref ESApps/ESAccessKeys/ESChannels/ESEngineInstances/
# ESEvaluationInstances)
# ---------------------------------------------------------------------------


class ESApps(base.Apps):
    def __init__(self, docs: _ESDocs, names: _ESDocs, seq: ESSequences):
        self._docs = docs
        self._seq = seq
        # Name-uniqueness sentinels live in a sibling index keyed by name and
        # are created via the atomic ``_create`` endpoint, so two concurrent
        # inserts with the same name cannot both succeed (a check-then-put on
        # the search index races; cf. ESAccessKeys which is naturally keyed).
        # Index creation/memoization is the factory's job (``_meta_docs``).
        self._names = names

    def insert(self, app: App) -> int | None:
        # search-index guard first: protects names of apps created before the
        # sentinel index existed (they have no sentinel doc to collide with)
        if self.get_by_name(app.name) is not None:
            return None  # names are unique (ref Apps.scala)
        app_id = app.id or self._seq.gen_next("apps")
        if not self._names.create(app.name, {"app_id": app_id}):
            return None
        try:
            created = self._docs.create(
                str(app_id),
                {"id": app_id, "name": app.name, "description": app.description},
            )
        except ESError:
            self._names.delete(app.name)  # don't orphan the name sentinel
            raise
        if not created:
            self._names.delete(app.name)  # id collision: roll back sentinel
            return None
        return app_id

    def get(self, app_id: int) -> App | None:
        d = self._docs.get(str(app_id))
        return App(d["id"], d["name"], d.get("description")) if d else None

    def get_by_name(self, name: str) -> App | None:
        hits = self._docs.search({"term": {"name": name}}, size=1)
        if not hits:
            return None
        d = hits[0]
        return App(d["id"], d["name"], d.get("description"))

    def get_all(self) -> list[App]:
        return [
            App(d["id"], d["name"], d.get("description"))
            for d in self._docs.search({"match_all": {}})
        ]

    def update(self, app: App) -> None:
        old = self.get(app.id)
        renaming = old is not None and old.name != app.name
        if renaming:
            # claim the new name before touching the doc; refuse the rename
            # if another app holds it (otherwise two apps would share a name
            # and the later sentinel cleanup would corrupt uniqueness)
            other = self.get_by_name(app.name)
            if other is not None and other.id != app.id:
                raise ESError(f"app name already in use: {app.name!r}")
            if not self._names.create(app.name, {"app_id": app.id}):
                sent = self._names.get(app.name)
                if not (sent and sent.get("app_id") == app.id):
                    raise ESError(f"app name already in use: {app.name!r}")
                # else: our own claim from an interrupted rename — proceed
        try:
            self._docs.put(
                str(app.id),
                {"id": app.id, "name": app.name, "description": app.description},
            )
        except ESError:
            if renaming:
                self._names.delete(app.name)  # release the claimed name
            raise
        if renaming:
            self._names.delete(old.name)

    def delete(self, app_id: int) -> None:
        app = self.get(app_id)
        # sentinel first: if the doc delete then fails, the app is still
        # findable by name and insert()'s get_by_name guard keeps uniqueness;
        # the reverse order would orphan the sentinel and block the name
        if app is not None:
            self._names.delete(app.name)
        self._docs.delete(str(app_id))


class ESAccessKeys(base.AccessKeys):
    def __init__(self, docs: _ESDocs):
        self._docs = docs

    def insert(self, k: AccessKey) -> str | None:
        key = k.key or base.generate_access_key()
        created = self._docs.create(
            key, {"key": key, "appid": k.appid, "events": list(k.events)}
        )
        # atomic create: a concurrent writer can never rebind a credential
        return key if created else None

    def get(self, key: str) -> AccessKey | None:
        d = self._docs.get(key)
        return AccessKey(d["key"], d["appid"], tuple(d["events"])) if d else None

    def get_all(self) -> list[AccessKey]:
        return [
            AccessKey(d["key"], d["appid"], tuple(d["events"]))
            for d in self._docs.search({"match_all": {}})
        ]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [
            AccessKey(d["key"], d["appid"], tuple(d["events"]))
            for d in self._docs.search({"term": {"appid": app_id}})
        ]

    def update(self, k: AccessKey) -> None:
        self._docs.put(
            k.key, {"key": k.key, "appid": k.appid, "events": list(k.events)}
        )

    def delete(self, key: str) -> None:
        self._docs.delete(key)


class ESChannels(base.Channels):
    def __init__(self, docs: _ESDocs, seq: ESSequences):
        self._docs = docs
        self._seq = seq

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        channel_id = channel.id or self._seq.gen_next("channels")
        if self._docs.get(str(channel_id)) is not None:
            return None  # explicit id collision
        self._docs.put(
            str(channel_id),
            {"id": channel_id, "name": channel.name, "appid": channel.appid},
        )
        return channel_id

    def get(self, channel_id: int) -> Channel | None:
        d = self._docs.get(str(channel_id))
        return Channel(d["id"], d["name"], d["appid"]) if d else None

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [
            Channel(d["id"], d["name"], d["appid"])
            for d in self._docs.search({"term": {"appid": app_id}})
        ]

    def delete(self, channel_id: int) -> None:
        self._docs.delete(str(channel_id))


def _instance_to_doc(i: EngineInstance) -> dict:
    return {
        "id": i.id,
        "status": i.status,
        "startTime": _iso(i.start_time),
        "endTime": _iso(i.end_time),
        "engineId": i.engine_id,
        "engineVersion": i.engine_version,
        "engineVariant": i.engine_variant,
        "engineFactory": i.engine_factory,
        "batch": i.batch,
        "env": i.env,
        "sparkConf": i.spark_conf,
        "dataSourceParams": i.data_source_params,
        "preparatorParams": i.preparator_params,
        "algorithmsParams": i.algorithms_params,
        "servingParams": i.serving_params,
    }


def _doc_to_instance(d: dict) -> EngineInstance:
    return EngineInstance(
        id=d["id"],
        status=d["status"],
        start_time=_parse_iso(d.get("startTime")),
        end_time=_parse_iso(d.get("endTime")),
        engine_id=d.get("engineId", ""),
        engine_version=d.get("engineVersion", ""),
        engine_variant=d.get("engineVariant", ""),
        engine_factory=d.get("engineFactory", ""),
        batch=d.get("batch", ""),
        env=d.get("env", {}),
        spark_conf=d.get("sparkConf", {}),
        data_source_params=d.get("dataSourceParams", ""),
        preparator_params=d.get("preparatorParams", ""),
        algorithms_params=d.get("algorithmsParams", ""),
        serving_params=d.get("servingParams", ""),
    )


class ESEngineInstances(base.EngineInstances):
    def __init__(self, docs: _ESDocs):
        self._docs = docs

    def insert(self, instance: EngineInstance) -> str:
        instance_id = instance.id or uuid.uuid4().hex
        instance.id = instance_id
        self._docs.put(instance_id, _instance_to_doc(instance))
        return instance_id

    def get(self, instance_id: str) -> EngineInstance | None:
        d = self._docs.get(instance_id)
        return _doc_to_instance(d) if d else None

    def get_all(self) -> list[EngineInstance]:
        return [_doc_to_instance(d) for d in self._docs.search({"match_all": {}})]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        hits = self._docs.search(
            {
                "bool": {
                    "filter": [
                        {"term": {"status": "COMPLETED"}},
                        {"term": {"engineId": engine_id}},
                        {"term": {"engineVersion": engine_version}},
                        {"term": {"engineVariant": engine_variant}},
                    ]
                }
            },
            sort=[{"startTime": {"order": "desc", "unmapped_type": "date"}}],
        )
        return [_doc_to_instance(d) for d in hits]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance: EngineInstance) -> None:
        self._docs.put(instance.id, _instance_to_doc(instance))

    def delete(self, instance_id: str) -> None:
        self._docs.delete(instance_id)


def _eval_to_doc(i: EvaluationInstance) -> dict:
    return {
        "id": i.id,
        "status": i.status,
        "startTime": _iso(i.start_time),
        "endTime": _iso(i.end_time),
        "evaluationClass": i.evaluation_class,
        "engineParamsGeneratorClass": i.engine_params_generator_class,
        "batch": i.batch,
        "env": i.env,
        "sparkConf": i.spark_conf,
        "evaluatorResults": i.evaluator_results,
        "evaluatorResultsHTML": i.evaluator_results_html,
        "evaluatorResultsJSON": i.evaluator_results_json,
    }


def _doc_to_eval(d: dict) -> EvaluationInstance:
    return EvaluationInstance(
        id=d["id"],
        status=d["status"],
        start_time=_parse_iso(d.get("startTime")),
        end_time=_parse_iso(d.get("endTime")),
        evaluation_class=d.get("evaluationClass", ""),
        engine_params_generator_class=d.get("engineParamsGeneratorClass", ""),
        batch=d.get("batch", ""),
        env=d.get("env", {}),
        spark_conf=d.get("sparkConf", {}),
        evaluator_results=d.get("evaluatorResults", ""),
        evaluator_results_html=d.get("evaluatorResultsHTML", ""),
        evaluator_results_json=d.get("evaluatorResultsJSON", ""),
    )


class ESEvaluationInstances(base.EvaluationInstances):
    def __init__(self, docs: _ESDocs):
        self._docs = docs

    def insert(self, instance: EvaluationInstance) -> str:
        instance_id = instance.id or uuid.uuid4().hex
        instance.id = instance_id
        self._docs.put(instance_id, _eval_to_doc(instance))
        return instance_id

    def get(self, instance_id: str) -> EvaluationInstance | None:
        d = self._docs.get(instance_id)
        return _doc_to_eval(d) if d else None

    def get_all(self) -> list[EvaluationInstance]:
        return [_doc_to_eval(d) for d in self._docs.search({"match_all": {}})]

    def get_completed(self) -> list[EvaluationInstance]:
        hits = self._docs.search(
            {"term": {"status": "EVALCOMPLETED"}},
            sort=[{"startTime": {"order": "desc", "unmapped_type": "date"}}],
        )
        return [_doc_to_eval(d) for d in hits]

    def update(self, instance: EvaluationInstance) -> None:
        self._docs.put(instance.id, _eval_to_doc(instance))

    def delete(self, instance_id: str) -> None:
        self._docs.delete(instance_id)


class ESModels(base.Models):
    """Model blobs as base64 documents (the reference's JSON serializer for
    ``Model`` base64-encodes the blob the same way, ``Models.scala:60-80``;
    the reference ES driver itself delegates models elsewhere, but a
    same-source models repo keeps single-source deployments possible)."""

    def __init__(self, docs: _ESDocs):
        self._docs = docs

    def insert(self, model: base.Model) -> None:
        self._docs.put(
            model.id,
            {"id": model.id, "models": base64.b64encode(model.models).decode()},
        )

    def get(self, model_id: str) -> base.Model | None:
        d = self._docs.get(model_id)
        if d is None:
            return None
        return base.Model(d["id"], base64.b64decode(d["models"]))

    def delete(self, model_id: str) -> None:
        self._docs.delete(model_id)


# ---------------------------------------------------------------------------
# Events (ref ESLEvents / ESPEvents; query DSL per ESUtils.createEventQuery)
# ---------------------------------------------------------------------------


class ESLEvents(base.LEvents):
    def __init__(self, transport: _ESTransport, prefix: str):
        self._t = transport
        self._prefix = prefix
        self._ensured: set[str] = set()

    def _index(self, app_id: int, channel_id: int | None) -> str:
        suffix = f"_{channel_id}" if channel_id is not None else ""
        return f"{self._prefix}_event_{app_id}{suffix}"

    def _docs(self, app_id: int, channel_id: int | None) -> _ESDocs:
        index = self._index(app_id, channel_id)
        if index not in self._ensured:
            _ensure_index(self._t, index)  # keyword/date mappings, not dynamic
            self._ensured.add(index)
        return _ESDocs(self._t, index)

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        _ensure_index(self._t, self._index(app_id, channel_id))
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        index = self._index(app_id, channel_id)
        self._t.request("DELETE", f"/{index}", ok_statuses=(404,))
        self._ensured.discard(index)
        return True

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        doc = event.to_json_dict(with_creation_time=True)
        doc["eventId"] = event_id
        self._docs(app_id, channel_id).put(event_id, doc)
        return event_id

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        """One ``_bulk`` request + one refresh for the whole batch (a
        per-event loop would pay an HTTP round trip and an index refresh
        per document).

        Partial-failure contract: ``_bulk`` is non-atomic, so on rejection
        the raised :class:`ESError` carries ``indexed_ids`` (the documents
        that DID land, in batch order). All documents are written with
        explicit ``_id``s, so retrying with the same event ids (pass events
        whose ``event_id`` is already set, e.g. from the error's
        ``attempted_ids``) is an idempotent overwrite, never a duplicate.
        """
        if not events:
            return []
        index = self._docs(app_id, channel_id)._index  # ensures mappings
        lines: list[dict] = []
        ids: list[str] = []
        for event in events:
            event_id = event.event_id or uuid.uuid4().hex
            doc = event.to_json_dict(with_creation_time=True)
            doc["eventId"] = event_id
            lines.append({"index": {"_index": index, "_id": event_id}})
            lines.append(doc)
            ids.append(event_id)
        out = self._t.bulk(lines, params={"refresh": "true"})
        if out.get("errors"):
            items = out.get("items", [])
            failed = [
                item["index"] for item in items if item.get("index", {}).get("error")
            ]
            indexed = [
                item["index"]["_id"]
                for item in items
                if not item.get("index", {}).get("error")
                and item.get("index", {}).get("_id")
            ]
            exc = ESError(
                f"_bulk rejected {len(failed)} of {len(ids)} event(s) "
                f"({len(indexed)} were indexed; retry with the same ids to "
                f"overwrite, not duplicate): {failed[:3]}"
            )
            exc.indexed_ids = indexed
            exc.attempted_ids = ids
            raise exc
        return ids

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        d = self._docs(app_id, channel_id).get(event_id)
        return _doc_to_event(d) if d else None

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        return self._docs(app_id, channel_id).delete(event_id)

    @staticmethod
    def _query(
        start_time,
        until_time,
        entity_type,
        entity_id,
        event_names,
        target_entity_type,
        target_entity_id,
    ) -> dict:
        """Bool-filter query mirroring ``ESUtils.createEventQuery``."""
        filters: list[dict] = []
        must_not: list[dict] = []
        if start_time is not None or until_time is not None:
            # bounds use the exact wire format documents carry so string
            # comparison (mock) and date parsing (real ES) both order right
            rng: dict[str, str] = {}
            if start_time is not None:
                rng["gte"] = format_event_time(start_time)
            if until_time is not None:
                rng["lt"] = format_event_time(until_time)
            filters.append({"range": {"eventTime": rng}})
        if entity_type is not None:
            filters.append({"term": {"entityType": entity_type}})
        if entity_id is not None:
            filters.append({"term": {"entityId": entity_id}})
        if event_names:
            filters.append({"terms": {"event": list(event_names)}})
        if target_entity_type is None:
            must_not.append({"exists": {"field": "targetEntityType"}})
        elif target_entity_type is not ...:
            filters.append({"term": {"targetEntityType": target_entity_type}})
        if target_entity_id is None:
            must_not.append({"exists": {"field": "targetEntityId"}})
        elif target_entity_id is not ...:
            filters.append({"term": {"targetEntityId": target_entity_id}})
        if not filters and not must_not:
            return {"match_all": {}}
        return {"bool": {"filter": filters, "must_not": must_not}}

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: str | None | type(...) = ...,
        target_entity_id: str | None | type(...) = ...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        if limit is not None and limit < 0:
            limit = None  # the reference treats limit=-1 as "no cap"
        query = self._query(
            start_time,
            until_time,
            entity_type,
            entity_id,
            event_names,
            target_entity_type,
            target_entity_id,
        )
        order = "desc" if reversed else "asc"
        # eventId tiebreak makes the search_after cursor total-ordered
        sort = [
            {"eventTime": {"order": order, "unmapped_type": "date"}},
            {"eventId": {"order": order, "unmapped_type": "keyword"}},
        ]
        docs = self._docs(app_id, channel_id)
        if limit is not None and limit <= 10_000:
            hits: Iterable[dict] = docs.search(query, size=limit, sort=sort)
        else:  # unlimited or beyond index.max_result_window: paginate
            hits = docs.scan(query, sort=sort)
            if limit is not None:
                import itertools

                hits = itertools.islice(hits, limit)
        for d in hits:
            yield _doc_to_event(d)


def _doc_to_event(d: dict) -> Event:
    """Stored doc -> Event, restoring the fields the REST decoder
    deliberately ignores (the API disables ``creationTime``, but the
    STORED doc carries it and the tail-read ordering contract —
    ``base.event_seq_key`` — depends on it round-tripping; without this,
    every scan re-minted creation_time = now() and a ``find_after``
    cursor could never pass a row)."""
    e = Event.from_json_dict(d)
    raw_ct = d.get("creationTime")
    if raw_ct:
        e = dataclasses.replace(e, creation_time=parse_event_time(raw_ct))
    return e


class ESPEvents(base.PEvents):
    """Bulk scan over the same indices (the reference reads through
    elasticsearch-hadoop's EsInputFormat, ``ESPEvents.scala:44-100``; the
    TPU feed path is the shared dictionary-encoder in ``base.PEvents``).

    This driver is the framework's SCALE-OUT event store (the HBase-class
    role — see docs/DECISIONS.md): bulk training scans fan out over ES
    sliced scrolls, one concurrent scanner per slice, the REST analog of
    the reference's HBase region-split parallel scan
    (``HBPEvents.scala:63-95``). ``scan_slices`` comes from the storage
    source config (``PIO_STORAGE_SOURCES_<name>_SCAN_SLICES``, default 4 —
    the same default as ``JDBCPEvents`` partitions, ``JDBCPEvents.scala:53``).
    """

    def __init__(
        self,
        transport: _ESTransport,
        prefix: str,
        levents: ESLEvents,
        scan_slices: int = 4,
    ):
        self._t = transport
        self._prefix = prefix
        self._levents = levents
        self._scan_slices = max(1, int(scan_slices))

    def find(self, app_id: int, channel_id: int | None = None, **kw) -> Iterator[Event]:
        return self._levents.find(app_id=app_id, channel_id=channel_id, **kw)

    _SLICE_FILTERS = frozenset(
        (
            "start_time",
            "until_time",
            "entity_type",
            "entity_id",
            "event_names",
            "target_entity_type",
            "target_entity_id",
        )
    )

    def find_sliced(
        self,
        app_id: int,
        channel_id: int | None = None,
        n_slices: int | None = None,
        **filters: Any,
    ) -> list[Iterator[Event]]:
        """Disjoint slice iterators jointly covering the filtered scan.
        Each iterator is independently consumable (own scroll context), so
        callers can hand one per worker thread/process."""
        unknown = set(filters) - self._SLICE_FILTERS
        if unknown:
            # silently ignoring a typo'd (or unsliceable, e.g. limit/
            # reversed) filter would return the wrong row set
            raise TypeError(f"find_sliced: unsupported filter(s) {sorted(unknown)}")
        n = n_slices or self._scan_slices
        query = ESLEvents._query(
            filters.get("start_time"),
            filters.get("until_time"),
            filters.get("entity_type"),
            filters.get("entity_id"),
            filters.get("event_names"),
            filters.get("target_entity_type", ...),
            filters.get("target_entity_id", ...),
        )
        docs = self._levents._docs(app_id, channel_id)

        def one(i: int) -> Iterator[Event]:
            for d in docs.scan_sliced(query, i, n):
                yield _doc_to_event(d)

        return [one(i) for i in range(n)]

    def find_parallel(
        self,
        app_id: int,
        channel_id: int | None = None,
        n_slices: int | None = None,
        **filters: Any,
    ) -> Iterator[Event]:
        """Merge the slices through a bounded queue, one thread per slice
        (shared merge: ``base.merge_parallel_scans``). Yields in
        nondeterministic order (bulk consumers — columnar encode,
        aggregation — are order-free)."""
        slices = self.find_sliced(app_id, channel_id, n_slices, **filters)
        return base.merge_parallel_scans(slices)

    _COLUMNAR_OWN_KW = frozenset(("rating_key", "entity_vocab", "target_vocab", "events"))

    def to_columnar(self, app_id: int, channel_id: int | None = None, **kw):
        """Columnar ingest reads through the sliced parallel scan — the
        training feed overlaps N scroll streams instead of paying one
        serial deep-pagination walk. Falls back to the serial scan when the
        call carries find() kwargs slices can't honor (limit, reversed, …)
        so semantics never silently diverge from the other drivers."""
        filters = {k: v for k, v in kw.items() if k in self._SLICE_FILTERS}
        unsliceable = set(kw) - self._SLICE_FILTERS - self._COLUMNAR_OWN_KW
        if self._scan_slices > 1 and "events" not in kw and not unsliceable:
            kw = {k: v for k, v in kw.items() if k not in self._SLICE_FILTERS}
            kw["events"] = self.find_parallel(app_id, channel_id, **filters)
            # erase the slice-merge nondeterminism (row order AND the
            # scan-encounter dictionary encoding) so direct consumers —
            # exports, multi-host ingest, golden tests — are reproducible
            return base.canonical_order(
                super().to_columnar(app_id, channel_id, **kw),
                frozen_entity_vocab=kw.get("entity_vocab") is not None,
                frozen_target_vocab=kw.get("target_vocab") is not None,
            )
        return super().to_columnar(app_id, channel_id, **kw)

    def write(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> None:
        batch: list[Event] = []
        for e in events:
            batch.append(e)
            if len(batch) >= 1_000:
                self._levents.insert_batch(batch, app_id, channel_id)
                batch = []
        if batch:
            self._levents.insert_batch(batch, app_id, channel_id)

    def delete(
        self, event_ids: Iterable[str], app_id: int, channel_id: int | None = None
    ) -> None:
        # _bulk delete actions: the per-event loop paid one HTTP round trip
        # AND one forced index refresh per document (minutes for a 100k-event
        # self-cleaning pass); one refresh per 1000-doc chunk instead
        index = self._levents._index(app_id, channel_id)
        chunk: list[dict] = []
        for event_id in event_ids:
            chunk.append({"delete": {"_index": index, "_id": event_id}})
            if len(chunk) >= 1_000:
                self._t.bulk(chunk, params={"refresh": "true"})
                chunk = []
        if chunk:
            self._t.bulk(chunk, params={"refresh": "true"})

    def version_stamp(self, app_id: int, channel_id: int | None = None) -> str | None:
        index = self._levents._index(app_id, channel_id)
        out = self._t.request(
            "POST", f"/{index}/_count", body={}, ok_statuses=(404,)
        )
        count = out.get("count")
        if count is None:
            return None
        # count alone misses delete+insert pairs; include the max eventTime
        hits = _ESDocs(self._t, index).search(
            {"match_all": {}},
            size=1,
            sort=[{"eventTime": {"order": "desc", "unmapped_type": "date"}}],
        )
        latest = hits[0].get("eventTime", "") if hits else ""
        return f"{count}:{latest}"

    def store_identity(self) -> str | None:
        return f"es:{self._t.urls[0]}/{self._prefix}"


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class ESStorageClient:
    """Backend entry point (type name: ``elasticsearch``)."""

    def __init__(self, config: dict | None = None):
        self.config = {k.upper(): v for k, v in (config or {}).items()}
        url = self.config.get("URL")
        if url:
            urls = [u.strip() for u in url.split(",") if u.strip()]
        else:
            hosts = [
                h.strip()
                for h in self.config.get("HOSTS", "localhost").split(",")
            ]
            ports = [
                p.strip() for p in str(self.config.get("PORTS", "9200")).split(",")
            ]
            schemes = [
                s.strip() for s in self.config.get("SCHEMES", "http").split(",")
            ]
            urls = []
            for i, host in enumerate(hosts):
                port = ports[min(i, len(ports) - 1)]
                scheme = schemes[min(i, len(schemes) - 1)]
                urls.append(f"{scheme}://{host}:{port}")
        auth = None
        if self.config.get("USERNAME"):
            cred = f"{self.config['USERNAME']}:{self.config.get('PASSWORD', '')}"
            auth = base64.b64encode(cred.encode()).decode()
        self._transport = _ESTransport(
            urls,
            auth=auth,
            timeout=float(self.config.get("TIMEOUT", 10.0)),
            retries=int(self.config.get("RETRIES", 1)),
            retry_backoff_s=float(self.config.get("RETRY_BACKOFF_S", 0.2)),
        )
        self._prefix = self.config.get("INDEX_PREFIX", "pio")
        self._ensured_meta: set[str] = set()
        self._seq = ESSequences(self._transport, f"{self._prefix}_meta_sequences")
        self._levents = ESLEvents(self._transport, self._prefix)

    def _meta_docs(self, kind: str) -> _ESDocs:
        index = f"{self._prefix}_meta_{kind}"
        if index not in self._ensured_meta:
            _ensure_index(self._transport, index)
            self._ensured_meta.add(index)
        return _ESDocs(self._transport, index)

    def l_events(self) -> ESLEvents:
        return self._levents

    def p_events(self) -> ESPEvents:
        return ESPEvents(
            self._transport,
            self._prefix,
            self._levents,
            scan_slices=int(self.config.get("SCAN_SLICES", 4)),
        )

    def apps(self) -> ESApps:
        return ESApps(
            self._meta_docs("apps"), self._meta_docs("apps_names"), self._seq
        )

    def access_keys(self) -> ESAccessKeys:
        return ESAccessKeys(self._meta_docs("accesskeys"))

    def channels(self) -> ESChannels:
        return ESChannels(self._meta_docs("channels"), self._seq)

    def engine_instances(self) -> ESEngineInstances:
        return ESEngineInstances(self._meta_docs("engineinstances"))

    def evaluation_instances(self) -> ESEvaluationInstances:
        return ESEvaluationInstances(self._meta_docs("evaluationinstances"))

    def models(self) -> ESModels:
        index = f"{self._prefix}_model"
        if index not in self._ensured_meta:
            _ensure_index(self._transport, index)
            self._ensured_meta.add(index)
        return ESModels(_ESDocs(self._transport, index))
