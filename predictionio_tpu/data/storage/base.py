"""Metadata records and abstract DAO interfaces.

Reference parity (record shapes verified in SURVEY.md Appendix A):
  - ``App(id, name, description)``                    Apps.scala:31-34
  - ``AccessKey(key, appid, events)``                 AccessKeys.scala:34-49
  - ``Channel(id, name, appid)``                      Channels.scala:31-57
  - ``EngineInstance(...)``                           EngineInstances.scala:44-61
  - ``EvaluationInstance(...)``                       EvaluationInstances.scala:41-54
  - ``Model(id, models)``                             Models.scala:32-80
  - ``LEvents`` row CRUD + filtered find + aggregate  LEvents.scala:40-513
  - ``PEvents`` bulk find/write/delete                PEvents.scala:38-189

The reference's L (local, row-at-a-time, async futures) vs P (parallel,
RDD-valued) DAO split maps here to: ``LEvents`` = synchronous row API (the
event server wraps calls in a thread executor), ``PEvents`` = bulk scan API
returning event iterators plus a columnar export for the TPU ingest path.
"""

from __future__ import annotations

import abc
import base64
import dataclasses
import datetime as _dt
import re
import secrets
from typing import Any, Iterable, Iterator, Sequence

import numpy as np

from predictionio_tpu.data.aggregator import (
    SPECIAL_EVENTS,
    aggregate_properties,
    aggregate_properties_single,
)
from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event

# ---------------------------------------------------------------------------
# Metadata records
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class App:
    id: int
    name: str
    description: str | None = None


@dataclasses.dataclass(frozen=True)
class AccessKey:
    key: str
    appid: int
    events: tuple[str, ...] = ()  # empty = all events allowed


@dataclasses.dataclass(frozen=True)
class Channel:
    id: int
    name: str
    appid: int

    NAME_RE = re.compile(r"^[a-zA-Z0-9-]{1,16}$")

    @staticmethod
    def is_valid_name(name: str) -> bool:
        return bool(Channel.NAME_RE.match(name))


class EngineInstanceStatus:
    INIT = "INIT"
    TRAINING = "TRAINING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"


@dataclasses.dataclass
class EngineInstance:
    """One training run (ref EngineInstances.scala:44-61)."""

    id: str
    status: str
    start_time: _dt.datetime
    end_time: _dt.datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    spark_conf: dict[str, str] = dataclasses.field(default_factory=dict)
    data_source_params: str = "{}"
    preparator_params: str = "{}"
    algorithms_params: str = "[]"
    serving_params: str = "{}"


class EvaluationInstanceStatus:
    INIT = "INIT"
    EVALUATING = "EVALUATING"
    EVALCOMPLETED = "EVALCOMPLETED"


@dataclasses.dataclass
class EvaluationInstance:
    """One evaluation run (ref EvaluationInstances.scala:41-54)."""

    id: str
    status: str
    start_time: _dt.datetime
    end_time: _dt.datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    spark_conf: dict[str, str] = dataclasses.field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


@dataclasses.dataclass
class Model:
    """Serialized model blob keyed by engine-instance id (ref Models.scala:32)."""

    id: str
    models: bytes

    def to_json_dict(self) -> dict[str, Any]:
        return {"id": self.id, "models": base64.b64encode(self.models).decode()}


def generate_access_key() -> str:
    """48 random bytes, base64 url-safe, no padding (ref AccessKeys.scala:44-49).

    A key must never START with ``-``: every CLI that takes a key as a
    positional (``pio accesskey delete <key>``) would parse it as a flag.
    The url-safe alphabet includes ``-`` (~1.6% of keys would hit it), so
    regenerate until the first character is safe — a uniformity loss of one
    character class on one position, not a security-relevant bias.
    """
    while True:
        key = base64.urlsafe_b64encode(secrets.token_bytes(48)).decode().rstrip("=")
        if not key.startswith("-"):
            return key


# ---------------------------------------------------------------------------
# Metadata DAO interfaces
# ---------------------------------------------------------------------------


class Apps(abc.ABC):
    @abc.abstractmethod
    def insert(self, app: App) -> int | None:
        """Insert; auto-generate id when app.id == 0. Returns the id, or
        None when the id or name is already taken (names are unique,
        ref Apps.scala)."""

    @abc.abstractmethod
    def get(self, app_id: int) -> App | None: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> App | None: ...

    @abc.abstractmethod
    def get_all(self) -> list[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> None:
        """Update in place. Renaming to a name held by a different app is a
        contract violation: drivers raise (name uniqueness must hold)."""

    @abc.abstractmethod
    def delete(self, app_id: int) -> None: ...


class AccessKeys(abc.ABC):
    @abc.abstractmethod
    def insert(self, k: AccessKey) -> str | None:
        """Insert; auto-generate key when blank. Returns the key."""

    @abc.abstractmethod
    def get(self, key: str) -> AccessKey | None: ...

    @abc.abstractmethod
    def get_all(self) -> list[AccessKey]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> list[AccessKey]: ...

    @abc.abstractmethod
    def update(self, k: AccessKey) -> None: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...


class Channels(abc.ABC):
    @abc.abstractmethod
    def insert(self, channel: Channel) -> int | None:
        """Insert; auto-generate id when 0; reject invalid names."""

    @abc.abstractmethod
    def get(self, channel_id: int) -> Channel | None: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> list[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> None: ...


class EngineInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str:
        """Insert; auto-generate id when blank. Returns the id."""

    @abc.abstractmethod
    def get(self, instance_id: str) -> EngineInstance | None: ...

    @abc.abstractmethod
    def get_all(self) -> list[EngineInstance]: ...

    @abc.abstractmethod
    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        """Most recent COMPLETED instance for the tuple — drives deploy
        (ref EngineInstances.scala getLatestCompleted)."""

    @abc.abstractmethod
    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> None: ...


class EvaluationInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> EvaluationInstance | None: ...

    @abc.abstractmethod
    def get_all(self) -> list[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_completed(self) -> list[EvaluationInstance]:
        """EVALCOMPLETED instances, newest first (drives the dashboard)."""

    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> None: ...


class Models(abc.ABC):
    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...

    @abc.abstractmethod
    def get(self, model_id: str) -> Model | None: ...

    @abc.abstractmethod
    def delete(self, model_id: str) -> None: ...


# ---------------------------------------------------------------------------
# Event DAOs
# ---------------------------------------------------------------------------


def event_seq_key(e: Event) -> tuple[int, str]:
    """The event store's total ORDERING CONTRACT for tailing reads.

    Events order by ``(creation_time micros, event_id)`` — creation time
    (when the store accepted the row, not the client-supplied event time)
    with the event id as the tiebreak. Two events accepted in the same
    microsecond therefore still have ONE total order on every backend, so
    a resumed tail (``find_after``) can neither skip nor double-read
    either of them. Backends with a native sequence (SQL creationTime
    column + id) implement the same order server-side.
    """
    return (int(e.creation_time.timestamp() * 1_000_000), e.event_id or "")


def check_tail_limit(limit: int) -> int:
    """``find_after`` requires an explicit non-negative bound on EVERY
    backend — ``find``'s "negative = no cap" convention must not leak in,
    or the same call would return everything on the scan backends and
    ``LIMIT 0`` (nothing, forever) on SQL."""
    if limit is None or int(limit) < 0:
        raise ValueError(f"find_after requires a non-negative limit, got {limit!r}")
    return int(limit)


def scan_find_after(
    events: "Iterable[Event]",
    cursor: tuple[int, str] | None,
    limit: int,
) -> list[Event]:
    """Shared scan-based ``find_after``: filter strictly past the cursor,
    sort by :func:`event_seq_key`, cap at ``limit``. O(table) — backends
    with an index override ``find_after`` instead of calling this."""
    limit = check_tail_limit(limit)
    keyed = [
        (key, e)
        for e in events
        for key in (event_seq_key(e),)
        if cursor is None or key > (int(cursor[0]), str(cursor[1]))
    ]
    keyed.sort(key=lambda p: p[0])
    return [e for _, e in keyed[:limit]]


class LEvents(abc.ABC):
    """Row-level event CRUD with the reference's filter surface
    (ref LEvents.scala futureFind :188-200 — 9 filter dimensions + limit +
    reversed)."""

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        """Initialize storage for an app/channel (ref init)."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        """Drop all events for an app/channel (ref remove)."""

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        """Insert one event, returning its id."""

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        return [self.insert(e, app_id, channel_id) for e in events]

    @abc.abstractmethod
    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None: ...

    @abc.abstractmethod
    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool: ...

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: str | None | type(...) = ...,
        target_entity_id: str | None | type(...) = ...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        """Filtered scan ordered by eventTime asc (desc when reversed).

        ``target_entity_type``/``target_entity_id`` are tri-state like the
        reference's Option[Option[String]]: ``...`` (ellipsis) = no filter,
        ``None`` = must be absent, a string = must equal. ``limit=None`` means
        no cap; the reference treats limit=-1 the same way.
        """

    def find_after(
        self,
        app_id: int,
        channel_id: int | None = None,
        cursor: tuple[int, str] | None = None,
        limit: int = 100,
    ) -> list[Event]:
        """Ordered tail read for the speed layer: up to ``limit`` events
        strictly after ``cursor`` in :func:`event_seq_key` order
        (``(creation_time micros, event_id)`` — the documented tiebreak).

        The cursor is EXCLUSIVE (the event at the cursor position is
        already consumed); ``None`` starts from the beginning. ``limit``
        must be non-negative on every backend (``find``'s negative
        no-cap convention does not apply — see :func:`check_tail_limit`).
        This generic implementation is an O(table) scan + sort; the
        sql/sqlite drivers override it with an indexed range read.
        Callers on the stream path must always pass an explicit ``limit``
        (lint rule ``stream-unbounded-drain``).
        """
        return scan_find_after(
            self.find(app_id=app_id, channel_id=channel_id), cursor, limit
        )

    def seq_head(
        self, app_id: int, channel_id: int | None = None
    ) -> tuple[int, str] | None:
        """The store's current tail-order head — max :func:`event_seq_key`
        over the app's events, ``None`` when empty. Seeds a fresh stream
        cursor ("start from now"). One O(table) scan here; the sql/sqlite
        drivers answer from the ``(creationTime, id)`` index."""
        return max(
            (event_seq_key(e) for e in self.find(app_id=app_id, channel_id=channel_id)),
            default=None,
        )

    def aggregate_properties(
        self,
        app_id: int,
        channel_id: int | None = None,
        entity_type: str = "",
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        required: Sequence[str] | None = None,
    ) -> dict[str, PropertyMap]:
        """Replay $set/$unset/$delete into per-entity PropertyMaps
        (ref futureAggregateProperties, LEvents.scala:393-428)."""
        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=list(SPECIAL_EVENTS),
        )
        result = aggregate_properties(events)
        if required:
            req = set(required)
            result = {
                k: v for k, v in result.items() if req.issubset(v.keyset())
            }
        return result

    def aggregate_properties_of_entity(
        self,
        app_id: int,
        entity_type: str,
        entity_id: str,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
    ) -> PropertyMap | None:
        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=list(SPECIAL_EVENTS),
        )
        return aggregate_properties_single(events)


@dataclasses.dataclass
class ColumnarEvents:
    """Dictionary-encoded column block for TPU ingest.

    Replaces the reference's RDD partition feed (JdbcRDD / TableInputFormat /
    EsInputFormat in the L3 drivers): entity/target/event strings are
    dictionary-encoded to dense int32 ids so the training path can go straight
    to device gathers, and ratings/weights ride in a float32 column.
    """

    event_ids: list[str]
    event_names: list[str]  # per-row event name (small vocab)
    entity_ids: np.ndarray  # int32 index into entity_vocab
    target_ids: np.ndarray  # int32 index into target_vocab, -1 when absent
    event_codes: np.ndarray  # int32 index into event_vocab
    timestamps: np.ndarray  # float64 epoch seconds
    ratings: np.ndarray  # float32, value of properties[rating_key] or nan
    entity_vocab: list[str]
    target_vocab: list[str]
    event_vocab: list[str]

    def __len__(self) -> int:
        return len(self.event_ids)


def _remap_vocab(
    vocab: list[str], codes: np.ndarray
) -> tuple[list[str], np.ndarray]:
    """Sort ``vocab`` lexicographically and rewrite integer ``codes`` into the
    sorted index space. Codes < 0 (absent target) pass through unchanged."""
    if not vocab:
        return vocab, codes
    order = np.argsort(np.asarray(vocab, dtype=object))
    inv = np.empty(len(vocab), np.int32)
    inv[order] = np.arange(len(vocab), dtype=np.int32)
    sorted_vocab = [vocab[int(i)] for i in order]
    if np.array_equal(inv, np.arange(len(vocab), dtype=np.int32)):
        return sorted_vocab, codes
    new_codes = codes.copy()
    present = codes >= 0
    new_codes[present] = inv[codes[present]]
    return sorted_vocab, new_codes


def _rows_canonical(event_ids: list[str], timestamps: np.ndarray) -> bool:
    """True iff rows are already in (timestamp, event_id) lexsort order.

    O(n) timestamp diff; event-id string comparisons only at timestamp
    ties (vectorized when bulk imports make ties pervasive)."""
    if len(timestamps) < 2:
        return True
    d = np.diff(timestamps)
    if np.any(d < 0):
        return False
    ties = np.flatnonzero(d == 0)
    if len(ties) == 0:
        return True
    if len(ties) > 1024:  # one object-array build beats a python loop
        ev = np.asarray(event_ids, dtype=object)
        return bool(np.all(ev[ties] <= ev[ties + 1]))
    return all(event_ids[int(i)] <= event_ids[int(i) + 1] for i in ties)


def canonical_order(
    cols: "ColumnarEvents",
    frozen_entity_vocab: bool = False,
    frozen_target_vocab: bool = False,
) -> "ColumnarEvents":
    """Reorder rows to the canonical (timestamp, event_id) order AND
    canonicalize the dictionary encoding (sorted vocabs, remapped codes).

    Drivers with parallel bulk scans (ES sliced scroll, SQL time-range
    partitions) merge their streams in nondeterministic order, which
    affects two things consumers depend on: the ROW order (the multi-host
    block partition must be disjoint and jointly complete across hosts,
    and exports must be reproducible run-to-run) and the VOCAB order
    (``to_columnar`` dictionary-encodes in scan-encounter order, so two
    hosts that each build the columnar independently would otherwise
    assign different integer codes to the same entity and silently mix
    entities when their blocks are combined). Canonicalizing both makes
    the result scan-order-independent. Each frozen flag skips the remap
    for THAT vocab only — a caller-supplied vocab is already a canonical
    index space (eval splits encoded with the training split's space must
    keep it), but the other, scan-encounter-ordered vocabs still need the
    remap; the event vocab can never be frozen and is always
    canonicalized."""
    n = len(cols)
    ent_vocab, ent_ids = cols.entity_vocab, cols.entity_ids
    tgt_vocab, tgt_ids = cols.target_vocab, cols.target_ids
    if not frozen_entity_vocab:
        ent_vocab, ent_ids = _remap_vocab(ent_vocab, ent_ids)
    if not frozen_target_vocab:
        tgt_vocab, tgt_ids = _remap_vocab(tgt_vocab, tgt_ids)
    ev_vocab, ev_codes = _remap_vocab(cols.event_vocab, cols.event_codes)
    # O(n) already-sorted precheck before the O(n log n) lexsort: the
    # common consumer chain canonicalizes twice (driver to_columnar, then
    # the snapshot cache on the same result), and the second pass must be
    # cheap. Rows are canonical iff timestamps are nondecreasing and
    # event_ids are nondecreasing within equal timestamps.
    if _rows_canonical(cols.event_ids, cols.timestamps):
        if ent_ids is cols.entity_ids and tgt_ids is cols.target_ids and (
            ev_codes is cols.event_codes
        ):
            return cols
        return dataclasses.replace(
            cols,
            entity_ids=ent_ids,
            target_ids=tgt_ids,
            event_codes=ev_codes,
            entity_vocab=ent_vocab,
            target_vocab=tgt_vocab,
            event_vocab=ev_vocab,
        )
    order = np.lexsort((np.asarray(cols.event_ids), cols.timestamps))
    take = order.tolist()
    return ColumnarEvents(
        event_ids=[cols.event_ids[i] for i in take],
        event_names=[cols.event_names[i] for i in take],
        entity_ids=ent_ids[order],
        target_ids=tgt_ids[order],
        event_codes=ev_codes[order],
        timestamps=cols.timestamps[order],
        ratings=cols.ratings[order],
        entity_vocab=ent_vocab,
        target_vocab=tgt_vocab,
        event_vocab=ev_vocab,
    )


def merge_parallel_scans(iterators: Sequence[Iterator[Event]]) -> Iterator[Event]:
    """Merge N scan iterators through a bounded queue, one thread per
    iterator. Yields in nondeterministic order (bulk consumers — columnar
    encode, aggregation — are order-free; the snapshot cache canonicalizes
    row order AND dictionary encoding afterward). Shared by the drivers with
    a parallel bulk path: ES sliced scroll, SQL time-range partitions.

    Failure/early-exit contract: a worker exception is re-raised to the
    consumer; when the consumer goes away every pump thread is unblocked and
    each source iterator's ``close()`` runs (releasing scroll contexts /
    database connections)."""
    import queue as _q
    import threading

    if len(iterators) == 1:
        yield from iterators[0]
        return
    out: _q.Queue = _q.Queue(maxsize=10_000)
    stop = threading.Event()  # set when the consumer goes away
    _DONE = object()

    def put_until_stopped(item) -> bool:
        while not stop.is_set():
            try:
                out.put(item, timeout=0.2)
                return True
            except _q.Full:
                continue
        return False

    def pump(it):
        try:
            try:
                for e in it:
                    if not put_until_stopped(e):
                        break
            except BaseException as exc:  # surface worker failures to consumer
                put_until_stopped(exc)
            # closing the source generator runs its finally blocks, releasing
            # per-scan resources (scroll context, connection). A close()
            # failure — or a plain iterator without close() — must neither
            # kill the thread nor swallow the _DONE handoff below, or the
            # consumer blocks forever on out.get().
            try:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
            except BaseException as exc:
                put_until_stopped(exc)
        finally:
            put_until_stopped(_DONE)

    threads = [
        threading.Thread(target=pump, args=(s,), daemon=True) for s in iterators
    ]
    for t in threads:
        t.start()
    live = len(threads)
    try:
        while live:
            item = out.get()
            if item is _DONE:
                live -= 1
            elif isinstance(item, BaseException):
                raise item
            else:
                yield item
    finally:
        # consumer finished, broke out early, or a scan failed: unblock
        # every pump (they exit without putting once stop is set) so no
        # thread is left parked on a full queue holding Events
        stop.set()
        for t in threads:
            t.join(timeout=5.0)


class PEvents(abc.ABC):
    """Bulk scan API (ref PEvents.scala:38-189). ``find`` streams events;
    ``to_columnar`` is the TPU feed path."""

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type: str | None | type(...) = ...,
        target_entity_id: str | None | type(...) = ...,
    ) -> Iterator[Event]: ...

    @abc.abstractmethod
    def write(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> None: ...

    @abc.abstractmethod
    def delete(
        self, event_ids: Iterable[str], app_id: int, channel_id: int | None = None
    ) -> None: ...

    def version_stamp(self, app_id: int, channel_id: int | None = None) -> str | None:
        """Cheap content stamp of this app/channel's events, used by the
        columnar snapshot cache (``data/store/snapshot.py``) for invalidation.
        Any write must change the stamp. ``None`` (the default) means the
        backend cannot stamp cheaply and snapshots will not be persisted.
        """
        return None

    def store_identity(self) -> str | None:
        """Stable identifier of the underlying store (db path, connection,
        instance nonce) — part of the snapshot signature so two stores
        sharing one snapshot root never garbage-collect or alias each
        other's snapshots. Stable across writes; distinct across stores."""
        return None

    def aggregate_properties(
        self,
        app_id: int,
        channel_id: int | None = None,
        entity_type: str = "",
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        required: Sequence[str] | None = None,
    ) -> dict[str, PropertyMap]:
        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=list(SPECIAL_EVENTS),
        )
        result = aggregate_properties(events)
        if required:
            req = set(required)
            result = {k: v for k, v in result.items() if req.issubset(v.keyset())}
        return result

    def extract_entity_map(
        self,
        app_id: int,
        entity_type: str,
        channel_id: int | None = None,
    ) -> dict[str, PropertyMap]:
        """ref PEvents.extractEntityMap — properties per entity of a type."""
        return self.aggregate_properties(
            app_id=app_id, channel_id=channel_id, entity_type=entity_type
        )

    def to_columnar(
        self,
        app_id: int,
        channel_id: int | None = None,
        event_names: Sequence[str] | None = None,
        rating_key: str = "rating",
        entity_vocab: Sequence[str] | None = None,
        target_vocab: Sequence[str] | None = None,
        events: "Iterable[Event] | None" = None,
        **find_kwargs: Any,
    ) -> ColumnarEvents:
        """Scan once and dictionary-encode into dense arrays.

        Pass pre-built ``entity_vocab``/``target_vocab`` to encode an eval
        split with the training split's index space (unknown ids get -1).
        ``events`` overrides the scan source — drivers with a parallel bulk
        path (ES sliced scroll) feed their merged stream through here so
        the encoder stays shared.
        """
        ent_index: dict[str, int] = (
            {v: i for i, v in enumerate(entity_vocab)} if entity_vocab else {}
        )
        tgt_index: dict[str, int] = (
            {v: i for i, v in enumerate(target_vocab)} if target_vocab else {}
        )
        frozen_ent = entity_vocab is not None
        frozen_tgt = target_vocab is not None
        ev_index: dict[str, int] = {}
        event_ids: list[str] = []
        names: list[str] = []
        ent_col: list[int] = []
        tgt_col: list[int] = []
        ev_col: list[int] = []
        ts_col: list[float] = []
        rating_col: list[float] = []
        if events is None:
            events = self.find(
                app_id=app_id,
                channel_id=channel_id,
                event_names=event_names,
                **find_kwargs,
            )
        for e in events:
            event_ids.append(e.event_id or "")
            names.append(e.event)
            if frozen_ent:
                ent_col.append(ent_index.get(e.entity_id, -1))
            else:
                ent_col.append(ent_index.setdefault(e.entity_id, len(ent_index)))
            if e.target_entity_id is None:
                tgt_col.append(-1)
            elif frozen_tgt:
                tgt_col.append(tgt_index.get(e.target_entity_id, -1))
            else:
                tgt_col.append(tgt_index.setdefault(e.target_entity_id, len(tgt_index)))
            ev_col.append(ev_index.setdefault(e.event, len(ev_index)))
            ts_col.append(e.event_time.timestamp())
            r = e.properties.get_opt(rating_key)
            rating_col.append(float(r) if isinstance(r, (int, float)) else float("nan"))
        return ColumnarEvents(
            event_ids=event_ids,
            event_names=names,
            entity_ids=np.asarray(ent_col, dtype=np.int32),
            target_ids=np.asarray(tgt_col, dtype=np.int32),
            event_codes=np.asarray(ev_col, dtype=np.int32),
            timestamps=np.asarray(ts_col, dtype=np.float64),
            ratings=np.asarray(rating_col, dtype=np.float32),
            entity_vocab=list(entity_vocab) if frozen_ent else list(ent_index),
            target_vocab=list(target_vocab) if frozen_tgt else list(tgt_index),
            event_vocab=list(ev_index),
        )
