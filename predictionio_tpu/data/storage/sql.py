"""Client/server SQL storage backend over any DB-API 2.0 driver.

Plays the role of the reference's JDBC driver for *external* databases
(``storage/jdbc/`` — scalikejdbc against PostgreSQL/MySQL, implementing every
DAO: ``JDBCApps/AccessKeys/Channels/EngineInstances/EvaluationInstances/
JDBCLEvents/JDBCPEvents/JDBCModels``; discovery contract
``Storage.scala:310-337``). Where the reference binds to JDBC URLs, this
driver binds to any Python DB-API 2.0 module — ``psycopg2``/``psycopg``
(PostgreSQL), ``pymysql``/``MySQLdb`` (MySQL/MariaDB) — selected by backend
type name ``postgres`` / ``mysql``, or any other module via the generic
``sql`` type with ``MODULE=<dbapi module>``. Driver imports are gated: the
module is imported at connect time, with a clear error naming the missing
dependency (nothing is ever auto-installed).

SQL is written once against a small dialect table (placeholder style,
auto-increment PK clause, blob column type); statements are portable across
SQLite, PostgreSQL and MySQL. Upserts are DELETE+INSERT inside one
transaction rather than per-dialect ``ON CONFLICT``/``ON DUPLICATE KEY``.
The event-table column layout matches the reference's JDBC DDL
(``storage/jdbc/.../JDBCLEvents.scala:54-68``) and the sqlite backend:
timestamps as UTC epoch micros + original offset.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import importlib
import json
import logging
import threading
import uuid
from typing import Iterable, Iterator, Sequence

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)
from predictionio_tpu.data.storage.registry import StorageError
from predictionio_tpu.data.storage.sqlite import (
    _event_table,
    _from_micros,
    _micros,
    _offset_of,
)
from predictionio_tpu.resilience import RetryPolicy

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class SQLDialect:
    """The portable subset of DDL/DML that differs across engines."""

    paramstyle: str  # qmark | format | pyformat | numeric
    serial_pk: str  # auto-increment integer primary key clause
    blob_type: str
    # psycopg2 cursors have no useful lastrowid; use INSERT .. RETURNING id
    use_returning: bool = False

    def sql(self, statement: str) -> str:
        """Statements are written with ``?`` placeholders; rewrite for the
        driver's paramstyle. None of our SQL contains literal '?'."""
        if self.paramstyle == "qmark":
            return statement
        if self.paramstyle in ("format", "pyformat"):
            return statement.replace("?", "%s")
        if self.paramstyle == "numeric":
            out, n = [], 0
            for ch in statement:
                if ch == "?":
                    n += 1
                    out.append(f":{n}")
                else:
                    out.append(ch)
            return "".join(out)
        raise StorageError(f"unsupported DB-API paramstyle {self.paramstyle!r}")


_DIALECTS = {
    "sqlite": SQLDialect("qmark", "INTEGER PRIMARY KEY AUTOINCREMENT", "BLOB"),
    "postgres": SQLDialect("pyformat", "SERIAL PRIMARY KEY", "BYTEA", use_returning=True),
    "mysql": SQLDialect("format", "INTEGER PRIMARY KEY AUTO_INCREMENT", "LONGBLOB"),
}

# backend type name -> (candidate DB-API modules, dialect)
_DRIVERS = {
    "postgres": (("psycopg2", "psycopg"), "postgres"),
    "mysql": (("pymysql", "MySQLdb"), "mysql"),
}


def _load_driver(type_name: str, config: dict):
    """Resolve (dbapi module, dialect name) from config. Gated imports."""
    module_name = config.get("MODULE") or config.get("module")
    if module_name:
        try:
            mod = importlib.import_module(module_name)
        except ImportError as exc:
            raise StorageError(
                f"DB-API module {module_name!r} is not installed; install it or "
                f"switch PIO_STORAGE_SOURCES_*_TYPE to sqlite/jsonl/memory"
            ) from exc
        dialect = config.get("DIALECT") or config.get("dialect")
        if not dialect:
            lowered = module_name.lower()
            if module_name == "sqlite3":
                dialect = "sqlite"
            elif lowered.startswith("psycopg") or "postgres" in lowered:
                dialect = "postgres"
            elif "mysql" in lowered or lowered == "mariadb":
                dialect = "mysql"
            else:
                raise StorageError(
                    f"cannot infer SQL dialect from module {module_name!r}; set "
                    f"DIALECT to one of {sorted(_DIALECTS)}"
                )
        if dialect not in _DIALECTS:
            raise StorageError(
                f"unknown SQL dialect {dialect!r}; known: {sorted(_DIALECTS)}"
            )
        return mod, dialect
    candidates, dialect = _DRIVERS.get(type_name, ((), ""))
    for name in candidates:
        try:
            return importlib.import_module(name), dialect
        except ImportError:
            continue
    raise StorageError(
        f"storage type {type_name!r} needs one of {list(candidates)} installed "
        f"(none found); use sqlite/jsonl/memory for a dependency-free setup"
    )


class SQLStorageClient:
    """Backend entry point (type names ``postgres``, ``mysql``, ``sql``).

    Config keys (reference ``conf/pio-env.sh.template`` JDBC block:
    ``PIO_STORAGE_SOURCES_PGSQL_{TYPE,URL,USERNAME,PASSWORD}``):
    ``HOST/PORT/DATABASE/USERNAME/PASSWORD`` or ``CONNECT_ARGS`` (JSON dict
    passed to ``connect``), plus ``MODULE``/``DIALECT`` for the generic type.
    """

    def __init__(self, config: dict | None = None, type_name: str = "postgres"):
        self.config = {k.upper(): v for k, v in (config or {}).items()}
        self._mod, dialect_name = _load_driver(
            self.config.get("TYPE", type_name).lower(), self.config
        )
        self.dialect = _DIALECTS[dialect_name]
        self._lock = threading.RLock()
        self._initialized_event_tables: set[str] = set()
        # reconnect-and-retry for dropped connections (see docs/resilience.md):
        # reads retry by default; writes only with RETRY_WRITES=true, because
        # a connection that died after the server applied the commit makes a
        # replayed INSERT a duplicate (the ES driver documents the same
        # ambiguity; idempotent callers can opt in)
        self._retry = RetryPolicy(
            max_attempts=max(1, int(self.config.get("RETRIES", 3))),
            backoff_base_s=float(self.config.get("RETRY_BACKOFF_S", 0.1)),
            retry_on=self._is_transient_db_error,
        )
        self._retry_writes = str(self.config.get("RETRY_WRITES", "")).lower() in (
            "1",
            "true",
            "yes",
        )
        self._conn = self._connect()
        self._init_schema()

    @property
    def store_identity(self) -> str:
        """Disambiguates snapshot-cache stamps across distinct databases
        sharing one snapshot root (same counts on two DBs must not alias)."""
        import hashlib

        ident = json.dumps(
            {
                k: v
                for k, v in sorted(self.config.items())
                if k not in ("PASSWORD",)
            },
            sort_keys=True,
            default=str,
        )
        return hashlib.sha1(ident.encode()).hexdigest()[:12]

    def _connect(self):
        raw = self.config.get("CONNECT_ARGS")
        if raw is not None:
            kwargs = json.loads(raw) if isinstance(raw, str) else dict(raw)
        else:
            kwargs = {}
            for cfg_key, arg in (
                ("HOST", "host"),
                ("PORT", "port"),
                ("DATABASE", "database"),
                ("USERNAME", "user"),
                ("PASSWORD", "password"),
            ):
                if self.config.get(cfg_key) is not None:
                    kwargs[arg] = self.config[cfg_key]
            if "port" in kwargs:
                kwargs["port"] = int(kwargs["port"])
        if self._mod.__name__ == "sqlite3":
            kwargs.setdefault("check_same_thread", False)
        return self._mod.connect(**kwargs)

    # -- resilience helpers -------------------------------------------------
    # OperationalError is a grab-bag: it covers dropped connections AND
    # permanent programming errors ('no such table', unknown column). Only
    # messages matching these markers (the SQLAlchemy is_disconnect
    # approach) are treated as transient — a schema mismatch must surface
    # immediately, not become a retry + reconnect storm.
    _DISCONNECT_MARKERS = (
        "database is locked",  # sqlite busy: clears on retry
        "server closed the connection",
        "connection already closed",
        "connection is closed",
        "could not connect",
        "connection refused",
        "connection reset",
        "connection timed out",
        "broken pipe",
        "lost connection",
        "gone away",
        "ssl connection has been closed",
        "terminating connection",
    )

    def _is_transient_db_error(self, exc: BaseException) -> bool:
        """Driver-level connection trouble worth a reconnect + replay."""
        if isinstance(exc, (ConnectionError, TimeoutError)):
            return True
        iface = getattr(self._mod, "InterfaceError", None)
        if iface is not None and isinstance(exc, iface):
            return True  # interface errors are connection-level by contract
        oper = getattr(self._mod, "OperationalError", None)
        if oper is not None and isinstance(exc, oper):
            msg = str(exc).lower()
            return any(marker in msg for marker in self._DISCONNECT_MARKERS)
        return False

    def _reset_connection(self) -> None:
        """Drop and rebuild the connection before a retry. Skipped for
        sqlite3: its transient error (locked db) clears on the SAME
        connection, and reconnecting would wipe a ``:memory:`` database."""
        if self._mod.__name__ == "sqlite3":
            return
        with self._lock:
            try:
                self._conn.close()
            except Exception:
                pass
            try:
                self._conn = self._connect()
            except Exception:
                logger.warning("reconnect failed; next attempt will retry")

    def _resilient(self, fn, write: bool):
        if write and not self._retry_writes:
            # no replay (ambiguous-commit risk) — but still heal a dead
            # connection so the NEXT call works; otherwise a write-dominated
            # workload never recovers from a server restart
            try:
                return fn()
            except Exception as exc:
                if self._is_transient_db_error(exc):
                    self._reset_connection()
                raise

        def attempt():
            try:
                return fn()
            except Exception as exc:
                if self._is_transient_db_error(exc):
                    self._reset_connection()
                raise

        return self._retry.call(attempt)

    # -- low-level helpers --------------------------------------------------
    def execute(self, statement: str, params: Sequence = ()):
        """One write statement in its own transaction; returns the cursor."""
        return self._resilient(lambda: self._execute_once(statement, params), write=True)

    def _execute_once(self, statement: str, params: Sequence = ()):
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(self.dialect.sql(statement), tuple(params))
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise
            return cur

    def executemany(self, statement: str, rows: Sequence[Sequence]) -> None:
        self._resilient(lambda: self._executemany_once(statement, rows), write=True)

    def _executemany_once(self, statement: str, rows: Sequence[Sequence]) -> None:
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.executemany(self.dialect.sql(statement), [tuple(r) for r in rows])
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise

    def query(self, statement: str, params: Sequence = ()) -> list[tuple]:
        return self._resilient(lambda: self._query_once(statement, params), write=False)

    def _query_once(self, statement: str, params: Sequence = ()) -> list[tuple]:
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(self.dialect.sql(statement), tuple(params))
                rows = cur.fetchall()
                self._conn.commit()  # close PG's implicit read transaction
            except Exception:
                # without this, one failed read leaves a PG connection in an
                # aborted transaction and every later statement fails
                self._conn.rollback()
                raise
            return [tuple(r) for r in rows]

    def query_iter(
        self, statement: str, params: Sequence = (), chunk_rows: int = 10_000
    ) -> Iterator[tuple]:
        """Streaming read: rows are yielded in ``chunk_rows`` fetches instead
        of materialized with one fetchall — the difference between scanning a
        20M-event table in bounded memory and OOMing the train job. On
        PostgreSQL a server-side (named) cursor keeps the result set on the
        server; sqlite3 streams natively via fetchmany."""
        cur = None
        with self._lock:
            if self.dialect.use_returning:  # postgres: server-side cursor
                try:
                    cur = self._conn.cursor(name=f"pio_scan_{uuid.uuid4().hex[:8]}")
                except TypeError:
                    cur = None
            if cur is None:
                cur = self._conn.cursor()
            try:
                cur.execute(self.dialect.sql(statement), tuple(params))
            except Exception:
                self._conn.rollback()
                raise
        try:
            while True:
                with self._lock:
                    try:
                        rows = cur.fetchmany(chunk_rows)
                    except Exception:
                        self._conn.rollback()
                        raise
                if not rows:
                    break
                for r in rows:
                    yield tuple(r)
        finally:
            with self._lock:
                try:
                    cur.close()
                    self._conn.commit()
                except Exception:
                    try:
                        self._conn.rollback()
                    except Exception:
                        pass

    def insert_returning_id(self, statement: str, params: Sequence) -> int:
        """INSERT into a serial-PK table, returning the generated id."""
        with self._lock:
            cur = self._conn.cursor()
            try:
                if self.dialect.use_returning:
                    cur.execute(
                        self.dialect.sql(statement + " RETURNING id"), tuple(params)
                    )
                    new_id = cur.fetchone()[0]
                else:
                    cur.execute(self.dialect.sql(statement), tuple(params))
                    new_id = cur.lastrowid
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise
            return int(new_id)

    def upsert(self, table: str, id_col: str, id_val, statement: str, params: Sequence):
        """Portable REPLACE: delete-then-insert in one transaction."""
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(
                    self.dialect.sql(f"DELETE FROM {table} WHERE {id_col} = ?"),
                    (id_val,),
                )
                cur.execute(self.dialect.sql(statement), tuple(params))
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise

    def is_integrity_error(self, exc: Exception) -> bool:
        ie = getattr(self._mod, "IntegrityError", None)
        return ie is not None and isinstance(exc, ie)

    def close(self) -> None:
        self._conn.close()

    # -- schema -------------------------------------------------------------
    def _init_schema(self) -> None:
        d = self.dialect
        statements = [
            """CREATE TABLE IF NOT EXISTS event_versions (
                 tbl VARCHAR(255) PRIMARY KEY, version BIGINT NOT NULL DEFAULT 0)""",
            f"""CREATE TABLE IF NOT EXISTS apps (
                 id {d.serial_pk}, name VARCHAR(255) NOT NULL UNIQUE, description TEXT)""",
            """CREATE TABLE IF NOT EXISTS accesskeys (
                 accesskey VARCHAR(64) PRIMARY KEY, appid INTEGER NOT NULL,
                 events TEXT NOT NULL)""",
            f"""CREATE TABLE IF NOT EXISTS channels (
                 id {d.serial_pk}, name VARCHAR(16) NOT NULL, appid INTEGER NOT NULL)""",
            """CREATE TABLE IF NOT EXISTS engineinstances (
                 id VARCHAR(64) PRIMARY KEY, status VARCHAR(32) NOT NULL,
                 startTime BIGINT NOT NULL, endTime BIGINT NOT NULL,
                 engineId TEXT NOT NULL, engineVersion TEXT NOT NULL,
                 engineVariant TEXT NOT NULL, engineFactory TEXT NOT NULL,
                 batch TEXT NOT NULL, env TEXT NOT NULL, sparkConf TEXT NOT NULL,
                 dataSourceParams TEXT NOT NULL, preparatorParams TEXT NOT NULL,
                 algorithmsParams TEXT NOT NULL, servingParams TEXT NOT NULL)""",
            """CREATE TABLE IF NOT EXISTS evaluationinstances (
                 id VARCHAR(64) PRIMARY KEY, status VARCHAR(32) NOT NULL,
                 startTime BIGINT NOT NULL, endTime BIGINT NOT NULL,
                 evaluationClass TEXT NOT NULL, engineParamsGeneratorClass TEXT NOT NULL,
                 batch TEXT NOT NULL, env TEXT NOT NULL, sparkConf TEXT NOT NULL,
                 evaluatorResults TEXT NOT NULL, evaluatorResultsHTML TEXT NOT NULL,
                 evaluatorResultsJSON TEXT NOT NULL)""",
            f"""CREATE TABLE IF NOT EXISTS models (
                 id VARCHAR(64) PRIMARY KEY, models {d.blob_type} NOT NULL)""",
        ]
        for statement in statements:
            self.execute(statement)

    def ensure_event_table(self, table: str) -> None:
        if table in self._initialized_event_tables:
            return
        self.execute(
            f"""CREATE TABLE IF NOT EXISTS {table} (
                 id VARCHAR(64) PRIMARY KEY, event TEXT NOT NULL,
                 entityType TEXT NOT NULL, entityId TEXT NOT NULL,
                 targetEntityType TEXT, targetEntityId TEXT, properties TEXT,
                 eventTime BIGINT NOT NULL, eventTimeZone VARCHAR(8) NOT NULL,
                 tags TEXT, prId TEXT,
                 creationTime BIGINT NOT NULL, creationTimeZone VARCHAR(8) NOT NULL)"""
        )
        try:
            self.execute(f"CREATE INDEX {table}_time ON {table} (eventTime)")
        except Exception:
            pass  # index exists (CREATE INDEX IF NOT EXISTS isn't MySQL-portable)
        try:
            # tail-read index: the (creationTime, id) ordering contract of
            # base.event_seq_key, served by a range scan (find_after)
            self.execute(
                f"CREATE INDEX {table}_ctime ON {table} (creationTime, id)"
            )
        except Exception:
            pass
        # seed the version row so later bumps are a single UPDATE that can
        # join the data-write transaction (atomic data+stamp commit)
        try:
            self.execute(
                "INSERT INTO event_versions (tbl, version) VALUES (?, 0)", (table,)
            )
        except Exception as exc:
            if not self.is_integrity_error(exc):
                raise
        self._initialized_event_tables.add(table)

    _BUMP_SQL = "UPDATE event_versions SET version = version + 1 WHERE tbl = ?"

    def bump_event_version(self, table: str) -> None:
        """Standalone bump (table drop etc.). Data writes instead run
        ``_BUMP_SQL`` inside their own transaction so a crash can never
        commit data without the stamp change (the version row is seeded by
        ``ensure_event_table``, making the bump a plain UPDATE)."""
        update = self.dialect.sql(self._BUMP_SQL)
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(update, (table,))
                if not cur.rowcount:
                    try:
                        cur.execute(
                            self.dialect.sql(
                                "INSERT INTO event_versions (tbl, version) VALUES (?, 1)"
                            ),
                            (table,),
                        )
                    except Exception as exc:
                        # concurrent writer won the first-bump race; re-UPDATE
                        if not self.is_integrity_error(exc):
                            raise
                        self._conn.rollback()
                        cur = self._conn.cursor()
                        cur.execute(update, (table,))
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise

    def event_version(self, table: str) -> int:
        rows = self.query("SELECT version FROM event_versions WHERE tbl = ?", (table,))
        return rows[0][0] if rows else 0

    # DAO accessors used by registry reflection
    def l_events(self) -> "SQLLEvents":
        return SQLLEvents(self)

    def p_events(self) -> "SQLPEvents":
        return SQLPEvents(self)

    def apps(self) -> "SQLApps":
        return SQLApps(self)

    def access_keys(self) -> "SQLAccessKeys":
        return SQLAccessKeys(self)

    def channels(self) -> "SQLChannels":
        return SQLChannels(self)

    def engine_instances(self) -> "SQLEngineInstances":
        return SQLEngineInstances(self)

    def evaluation_instances(self) -> "SQLEvaluationInstances":
        return SQLEvaluationInstances(self)

    def models(self) -> "SQLModels":
        return SQLModels(self)


class PostgresStorageClient(SQLStorageClient):
    """Type name ``postgres`` (ref jdbc driver with a PostgreSQL URL)."""

    def __init__(self, config: dict | None = None):
        super().__init__(config, type_name="postgres")


class MySQLStorageClient(SQLStorageClient):
    """Type name ``mysql`` (ref jdbc driver with a MySQL URL)."""

    def __init__(self, config: dict | None = None):
        super().__init__(config, type_name="mysql")


_EVENT_COLS = (
    "id, event, entityType, entityId, targetEntityType, targetEntityId, "
    "properties, eventTime, eventTimeZone, tags, prId, creationTime, creationTimeZone"
)


def _find_clauses(
    start_time,
    until_time,
    entity_type,
    entity_id,
    event_names,
    target_entity_type=...,
    target_entity_id=...,
) -> tuple[list[str], list]:
    """The 9-filter WHERE builder shared by the row scan and the
    partitioned bulk scan (``...`` = filter absent for the target fields,
    None = IS NULL — the reference's Option[Option[String]] semantics)."""
    clauses, params = [], []
    if start_time is not None:
        clauses.append("eventTime >= ?")
        params.append(_micros(start_time))
    if until_time is not None:
        clauses.append("eventTime < ?")
        params.append(_micros(until_time))
    if entity_type is not None:
        clauses.append("entityType = ?")
        params.append(entity_type)
    if entity_id is not None:
        clauses.append("entityId = ?")
        params.append(entity_id)
    if event_names is not None:
        placeholders = ",".join("?" for _ in event_names)
        clauses.append(f"event IN ({placeholders})")
        params.extend(event_names)
    if target_entity_type is not ...:
        if target_entity_type is None:
            clauses.append("targetEntityType IS NULL")
        else:
            clauses.append("targetEntityType = ?")
            params.append(target_entity_type)
    if target_entity_id is not ...:
        if target_entity_id is None:
            clauses.append("targetEntityId IS NULL")
        else:
            clauses.append("targetEntityId = ?")
            params.append(target_entity_id)
    return clauses, params


class SQLLEvents(base.LEvents):
    """Row-level event DAO (ref ``JDBCLEvents.scala``)."""

    def __init__(self, client: SQLStorageClient):
        self._c = client

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        self._c.ensure_event_table(_event_table(app_id, channel_id))
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        table = _event_table(app_id, channel_id)
        self._c.execute(f"DROP TABLE IF EXISTS {table}")
        self._c._initialized_event_tables.discard(table)
        self._c.bump_event_version(table)
        return True

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        ids, rows = [], []
        for event in events:
            event_id = event.event_id or uuid.uuid4().hex
            ids.append(event_id)
            rows.append(
                (
                    event_id,
                    event.event,
                    event.entity_type,
                    event.entity_id,
                    event.target_entity_type,
                    event.target_entity_id,
                    event.properties.to_json(),
                    _micros(event.event_time),
                    _offset_of(event.event_time),
                    json.dumps(list(event.tags)),
                    event.pr_id,
                    _micros(event.creation_time),
                    _offset_of(event.creation_time),
                )
            )
        # one transaction for the whole batch: bulk delete of colliding ids
        # then executemany insert — not a commit per event
        placeholders = ",".join("?" * 13)
        insert_sql = self._c.dialect.sql(
            f"INSERT INTO {table} ({_EVENT_COLS}) VALUES ({placeholders})"
        )
        with self._c._lock:
            cur = self._c._conn.cursor()
            try:
                for chunk_start in range(0, len(ids), 500):
                    chunk = ids[chunk_start : chunk_start + 500]
                    id_ph = ",".join("?" for _ in chunk)
                    cur.execute(
                        self._c.dialect.sql(
                            f"DELETE FROM {table} WHERE id IN ({id_ph})"
                        ),
                        tuple(chunk),
                    )
                cur.executemany(insert_sql, [tuple(r) for r in rows])
                # stamp bump rides the same commit: data can never land
                # without invalidating cached snapshots
                cur.execute(self._c.dialect.sql(self._c._BUMP_SQL), (table,))
                self._c._conn.commit()
            except Exception:
                self._c._conn.rollback()
                raise
        return ids

    @staticmethod
    def _row_to_event(row: tuple) -> Event:
        return Event(
            event=row[1],
            entity_type=row[2],
            entity_id=row[3],
            target_entity_type=row[4],
            target_entity_id=row[5],
            properties=DataMap.from_json(row[6] or "{}"),
            event_time=_from_micros(row[7], row[8]),
            event_id=row[0],
            tags=tuple(json.loads(row[9] or "[]")),
            pr_id=row[10],
            creation_time=_from_micros(row[11], row[12]),
        )

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        rows = self._c.query(
            f"SELECT {_EVENT_COLS} FROM {table} WHERE id = ?", (event_id,)
        )
        return self._row_to_event(rows[0]) if rows else None

    def delete(self, event_id: str, app_id: int, channel_id: int | None = None) -> bool:
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        with self._c._lock:
            cur = self._c._conn.cursor()
            try:
                cur.execute(
                    self._c.dialect.sql(f"DELETE FROM {table} WHERE id = ?"),
                    (event_id,),
                )
                deleted = cur.rowcount > 0
                if deleted:
                    cur.execute(self._c.dialect.sql(self._c._BUMP_SQL), (table,))
                self._c._conn.commit()
            except Exception:
                self._c._conn.rollback()
                raise
        return deleted

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        clauses, params = _find_clauses(
            start_time,
            until_time,
            entity_type,
            entity_id,
            event_names,
            target_entity_type,
            target_entity_id,
        )
        where = f" WHERE {' AND '.join(clauses)}" if clauses else ""
        order = "DESC" if reversed else "ASC"
        statement = f"SELECT {_EVENT_COLS} FROM {table}{where} ORDER BY eventTime {order}"
        if limit is not None and limit >= 0:
            statement += f" LIMIT {int(limit)}"
        # streamed: bounded memory even on multi-million-row scans
        return (self._row_to_event(r) for r in self._c.query_iter(statement, params))

    def find_after(
        self,
        app_id: int,
        channel_id: int | None = None,
        cursor: tuple[int, str] | None = None,
        limit: int = 100,
    ) -> list[Event]:
        """Indexed tail read on ``(creationTime, id)`` (see
        ``ensure_event_table``'s ``_ctime`` index) — the ordering contract
        of ``base.event_seq_key`` executed server-side."""
        limit = base.check_tail_limit(limit)
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        where, params = "", []
        if cursor is not None:
            where = " WHERE creationTime > ? OR (creationTime = ? AND id > ?)"
            params = [int(cursor[0]), int(cursor[0]), str(cursor[1])]
        statement = (
            f"SELECT {_EVENT_COLS} FROM {table}{where} "
            f"ORDER BY creationTime, id LIMIT {limit}"
        )
        return [self._row_to_event(r) for r in self._c.query(statement, params)]

    def seq_head(
        self, app_id: int, channel_id: int | None = None
    ) -> tuple[int, str] | None:
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        rows = self._c.query(
            f"SELECT creationTime, id FROM {table} "
            "ORDER BY creationTime DESC, id DESC LIMIT 1"
        )
        return (int(rows[0][0]), str(rows[0][1])) if rows else None


class SQLPEvents(base.PEvents):
    """Bulk/columnar event DAO (ref ``JDBCPEvents.scala``).

    The bulk path mirrors the reference's JdbcRDD time-range partitioning
    (``JDBCPEvents.scala:91-121``; default partition count 4, ``:53-55``):
    ``find_partitioned`` splits ``[min(eventTime), max(eventTime)]`` into N
    ranges and scans each on its OWN database connection — the reference
    opens one JDBC connection per Spark partition the same way. The
    columnar train feed reads through the threaded merge of those
    partitions; the snapshot cache canonicalizes row order + encoding
    afterward, so merge nondeterminism never leaks.

    Partition count: storage-source config ``PARTITIONS`` or env
    ``PIO_SQL_SCAN_PARTITIONS``, default 4. Single-connection stores that
    cannot open a second session to the same data (sqlite ``:memory:``)
    fall back to one partition automatically.
    """

    def __init__(self, client: SQLStorageClient):
        self._c = client
        self._l = SQLLEvents(client)
        import os

        raw = client.config.get("PARTITIONS") or os.environ.get(
            "PIO_SQL_SCAN_PARTITIONS", "4"
        )
        try:
            self._partitions = max(1, int(raw))
        except ValueError:
            self._partitions = 4

    def find(self, app_id: int, channel_id: int | None = None, **kw) -> Iterator[Event]:
        return self._l.find(app_id, channel_id, **kw)

    # -- partitioned bulk scan ---------------------------------------------

    def _can_partition(self, table: str) -> bool:
        """Partitioned scans need a SECOND connection that sees the same
        data — true for server databases and file-backed sqlite, false for
        ``:memory:`` stores where every connect() opens a fresh empty
        database. Probed once per table (a fresh connection must see the
        event table) rather than guessed from config. The configured
        default partition count is NOT consulted here: an explicit
        ``n_partitions`` argument must win over the config default, so
        count gating belongs to the callers."""
        cache = getattr(self._c, "_partition_probe", None)
        if cache is None:
            cache = self._c._partition_probe = {}
        if table not in cache:
            try:
                conn = self._c._connect()
                try:
                    cur = conn.cursor()
                    # existence probe, O(1) — COUNT(*) would full-scan a 20M
                    # row table on Postgres just to prove visibility
                    cur.execute(
                        self._c.dialect.sql(f"SELECT 1 FROM {table} LIMIT 1")
                    )
                    cur.fetchone()
                    cache[table] = True
                finally:
                    conn.close()
            except Exception as exc:
                # do NOT cache: a transient failure (server blip, connection
                # limit) must not silently downgrade every later bulk scan
                # of this table to serial for the process lifetime — the
                # next scan re-probes. Only a successful probe is sticky.
                logger.warning(
                    "partition probe for %s failed (scanning serial this "
                    "time): %s", table, exc
                )
                return False
        return cache[table]

    def find_partitioned(
        self,
        app_id: int,
        channel_id: int | None = None,
        n_partitions: int | None = None,
        **filters,
    ) -> list[Iterator[Event]]:
        """N iterators over disjoint eventTime ranges whose union is exactly
        the serial scan's row set (ref ``JDBCPEvents.scala:91-121``)."""
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        n = n_partitions or self._partitions
        unknown = set(filters) - self._PARTITION_FILTERS
        if unknown:
            # a silently-dropped limit/reversed would return a DIFFERENT row
            # set than the serial scan honoring it — refuse loudly instead
            raise TypeError(
                f"find_partitioned cannot honor filters {sorted(unknown)}; "
                f"supported: {sorted(self._PARTITION_FILTERS)} (use find() for "
                "limit/reversed)"
            )
        clauses, params = _find_clauses(**{
            "start_time": filters.get("start_time"),
            "until_time": filters.get("until_time"),
            "entity_type": filters.get("entity_type"),
            "entity_id": filters.get("entity_id"),
            "event_names": filters.get("event_names"),
            "target_entity_type": filters.get("target_entity_type", ...),
            "target_entity_id": filters.get("target_entity_id", ...),
        })
        where = f" WHERE {' AND '.join(clauses)}" if clauses else ""
        bounds = self._c.query(
            f"SELECT MIN(eventTime), MAX(eventTime) FROM {table}{where}", params
        )[0]
        if bounds[0] is None or n <= 1 or not self._can_partition(table):
            return [self._l.find(app_id, channel_id, **filters)]
        lo, hi = int(bounds[0]), int(bounds[1]) + 1  # [lo, hi) covers all
        edges = [lo + (hi - lo) * i // n for i in range(n + 1)]
        sql = self._c.dialect.sql(
            f"SELECT {_EVENT_COLS} FROM {table}{where}"
            f"{' AND' if clauses else ' WHERE'} eventTime >= ? AND eventTime < ?"
            " ORDER BY eventTime ASC"
        )

        def scan_range(p_lo: int, p_hi: int) -> Iterator[Event]:
            # fresh connection per partition: concurrent range scans must
            # not serialize on the client's shared-connection lock
            conn = self._c._connect()
            try:
                # server-side (named) cursor where the dialect has one
                # (postgres): a client-side cursor materializes the WHOLE
                # partition at execute() — at ML-20M / 4 partitions that is
                # ~5M rows held per partition, the exact OOM query_iter's
                # streaming exists to avoid (same rationale, :233-240)
                cur = None
                if self._c.dialect.use_returning:
                    try:
                        cur = conn.cursor(name=f"pio_part_{uuid.uuid4().hex[:8]}")
                    except TypeError:
                        cur = None
                if cur is None:
                    cur = conn.cursor()
                cur.execute(sql, tuple(params) + (p_lo, p_hi))
                while True:
                    rows = cur.fetchmany(10_000)
                    if not rows:
                        break
                    for r in rows:
                        yield SQLLEvents._row_to_event(tuple(r))
            finally:
                conn.close()

        return [
            scan_range(edges[i], edges[i + 1])
            for i in range(n)
            if edges[i] < edges[i + 1]
        ]

    def find_parallel(
        self,
        app_id: int,
        channel_id: int | None = None,
        n_partitions: int | None = None,
        **filters,
    ) -> Iterator[Event]:
        """Threaded merge of the time-range partitions (nondeterministic
        order; bulk consumers are order-free)."""
        return base.merge_parallel_scans(
            self.find_partitioned(app_id, channel_id, n_partitions, **filters)
        )

    _PARTITION_FILTERS = frozenset(
        (
            "start_time",
            "until_time",
            "entity_type",
            "entity_id",
            "event_names",
            "target_entity_type",
            "target_entity_id",
        )
    )
    _COLUMNAR_OWN_KW = frozenset(
        ("rating_key", "entity_vocab", "target_vocab", "events")
    )

    def to_columnar(self, app_id: int, channel_id: int | None = None, **kw):
        """Columnar ingest through the partitioned parallel scan when the
        filters allow it; serial otherwise (limit/reversed can't partition
        without changing semantics). The merged stream's nondeterministic
        order is erased by ``canonical_order`` before returning, so every
        consumer (exports, multi-host ingest, golden tests) sees the same
        rows, codes, and vocabs run-to-run."""
        filters = {k: v for k, v in kw.items() if k in self._PARTITION_FILTERS}
        unpartitionable = set(kw) - self._PARTITION_FILTERS - self._COLUMNAR_OWN_KW
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        # cheap gates first; the second-connection probe involves a real
        # connect and only matters when partitioning is otherwise possible
        if (
            "events" not in kw
            and not unpartitionable
            and self._partitions > 1
            and self._can_partition(table)
        ):
            kw = {k: v for k, v in kw.items() if k not in self._PARTITION_FILTERS}
            kw["events"] = self.find_parallel(app_id, channel_id, **filters)
            return base.canonical_order(
                super().to_columnar(app_id, channel_id, **kw),
                frozen_entity_vocab=kw.get("entity_vocab") is not None,
                frozen_target_vocab=kw.get("target_vocab") is not None,
            )
        return super().to_columnar(app_id, channel_id, **kw)

    def write(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> None:
        self._l.insert_batch(list(events), app_id, channel_id)

    def delete(
        self, event_ids: Iterable[str], app_id: int, channel_id: int | None = None
    ) -> None:
        ids = list(event_ids)
        if not ids:
            return
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        # chunked DELETE ... IN plus the stamp bump in ONE transaction — not
        # a round trip per event, and no crash window between data and stamp
        with self._c._lock:
            cur = self._c._conn.cursor()
            try:
                for chunk_start in range(0, len(ids), 500):
                    chunk = ids[chunk_start : chunk_start + 500]
                    placeholders = ",".join("?" for _ in chunk)
                    cur.execute(
                        self._c.dialect.sql(
                            f"DELETE FROM {table} WHERE id IN ({placeholders})"
                        ),
                        tuple(chunk),
                    )
                cur.execute(self._c.dialect.sql(self._c._BUMP_SQL), (table,))
                self._c._conn.commit()
            except Exception:
                self._c._conn.rollback()
                raise

    def version_stamp(self, app_id: int, channel_id: int | None = None) -> str | None:
        table = _event_table(app_id, channel_id)
        self._c.ensure_event_table(table)
        version = self._c.event_version(table)
        count = self._c.query(f"SELECT COUNT(*) FROM {table}")[0][0]
        return f"v{version}:{count}"

    def store_identity(self) -> str | None:
        return self._c.store_identity


class SQLApps(base.Apps):
    def __init__(self, client: SQLStorageClient):
        self._c = client

    def insert(self, app: App) -> int | None:
        try:
            if app.id:
                self._c.execute(
                    "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                    (app.id, app.name, app.description),
                )
                return app.id
            return self._c.insert_returning_id(
                "INSERT INTO apps (name, description) VALUES (?,?)",
                (app.name, app.description),
            )
        except Exception as exc:
            if self._c.is_integrity_error(exc):
                return None
            raise

    def get(self, app_id: int) -> App | None:
        rows = self._c.query(
            "SELECT id, name, description FROM apps WHERE id=?", (app_id,)
        )
        return App(*rows[0]) if rows else None

    def get_by_name(self, name: str) -> App | None:
        rows = self._c.query(
            "SELECT id, name, description FROM apps WHERE name=?", (name,)
        )
        return App(*rows[0]) if rows else None

    def get_all(self) -> list[App]:
        return [
            App(*r)
            for r in self._c.query("SELECT id, name, description FROM apps ORDER BY id")
        ]

    def update(self, app: App) -> None:
        self._c.execute(
            "UPDATE apps SET name=?, description=? WHERE id=?",
            (app.name, app.description, app.id),
        )

    def delete(self, app_id: int) -> None:
        self._c.execute("DELETE FROM apps WHERE id=?", (app_id,))


class SQLAccessKeys(base.AccessKeys):
    def __init__(self, client: SQLStorageClient):
        self._c = client

    def insert(self, k: AccessKey) -> str | None:
        key = k.key or base.generate_access_key()
        try:
            self._c.execute(
                "INSERT INTO accesskeys (accesskey, appid, events) VALUES (?,?,?)",
                (key, k.appid, json.dumps(list(k.events))),
            )
            return key
        except Exception as exc:
            if self._c.is_integrity_error(exc):
                return None
            raise

    @staticmethod
    def _row(r: tuple) -> AccessKey:
        return AccessKey(r[0], r[1], tuple(json.loads(r[2] or "[]")))

    def get(self, key: str) -> AccessKey | None:
        rows = self._c.query(
            "SELECT accesskey, appid, events FROM accesskeys WHERE accesskey=?", (key,)
        )
        return self._row(rows[0]) if rows else None

    def get_all(self) -> list[AccessKey]:
        return [
            self._row(r)
            for r in self._c.query("SELECT accesskey, appid, events FROM accesskeys")
        ]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [
            self._row(r)
            for r in self._c.query(
                "SELECT accesskey, appid, events FROM accesskeys WHERE appid=?",
                (app_id,),
            )
        ]

    def update(self, k: AccessKey) -> None:
        self._c.execute(
            "UPDATE accesskeys SET appid=?, events=? WHERE accesskey=?",
            (k.appid, json.dumps(list(k.events)), k.key),
        )

    def delete(self, key: str) -> None:
        self._c.execute("DELETE FROM accesskeys WHERE accesskey=?", (key,))


class SQLChannels(base.Channels):
    def __init__(self, client: SQLStorageClient):
        self._c = client

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        try:
            if channel.id:
                self._c.execute(
                    "INSERT INTO channels (id, name, appid) VALUES (?,?,?)",
                    (channel.id, channel.name, channel.appid),
                )
                return channel.id
            return self._c.insert_returning_id(
                "INSERT INTO channels (name, appid) VALUES (?,?)",
                (channel.name, channel.appid),
            )
        except Exception as exc:
            if self._c.is_integrity_error(exc):
                return None
            raise

    def get(self, channel_id: int) -> Channel | None:
        rows = self._c.query(
            "SELECT id, name, appid FROM channels WHERE id=?", (channel_id,)
        )
        return Channel(*rows[0]) if rows else None

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [
            Channel(*r)
            for r in self._c.query(
                "SELECT id, name, appid FROM channels WHERE appid=?", (app_id,)
            )
        ]

    def delete(self, channel_id: int) -> None:
        self._c.execute("DELETE FROM channels WHERE id=?", (channel_id,))


_EI_COLS = (
    "id, status, startTime, endTime, engineId, engineVersion, engineVariant, "
    "engineFactory, batch, env, sparkConf, dataSourceParams, preparatorParams, "
    "algorithmsParams, servingParams"
)


class SQLEngineInstances(base.EngineInstances):
    def __init__(self, client: SQLStorageClient):
        self._c = client

    def insert(self, i: EngineInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        i.id = iid
        self._c.upsert(
            "engineinstances",
            "id",
            iid,
            f"INSERT INTO engineinstances ({_EI_COLS}) "
            f"VALUES ({','.join('?' * 15)})",
            (
                iid,
                i.status,
                _micros(i.start_time),
                _micros(i.end_time),
                i.engine_id,
                i.engine_version,
                i.engine_variant,
                i.engine_factory,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.data_source_params,
                i.preparator_params,
                i.algorithms_params,
                i.serving_params,
            ),
        )
        return iid

    @staticmethod
    def _row(r: tuple) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=_from_micros(r[2], "Z"),
            end_time=_from_micros(r[3], "Z"),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8],
            env=json.loads(r[9]),
            spark_conf=json.loads(r[10]),
            data_source_params=r[11],
            preparator_params=r[12],
            algorithms_params=r[13],
            serving_params=r[14],
        )

    def get(self, instance_id: str) -> EngineInstance | None:
        rows = self._c.query(
            f"SELECT {_EI_COLS} FROM engineinstances WHERE id=?", (instance_id,)
        )
        return self._row(rows[0]) if rows else None

    def get_all(self) -> list[EngineInstance]:
        return [
            self._row(r)
            for r in self._c.query(f"SELECT {_EI_COLS} FROM engineinstances")
        ]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        rows = self._c.query(
            f"SELECT {_EI_COLS} FROM engineinstances WHERE status=? AND engineId=? "
            "AND engineVersion=? AND engineVariant=? ORDER BY startTime DESC",
            (
                base.EngineInstanceStatus.COMPLETED,
                engine_id,
                engine_version,
                engine_variant,
            ),
        )
        return [self._row(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, i: EngineInstance) -> None:
        self.insert(i)

    def delete(self, instance_id: str) -> None:
        self._c.execute("DELETE FROM engineinstances WHERE id=?", (instance_id,))


_EVI_COLS = (
    "id, status, startTime, endTime, evaluationClass, engineParamsGeneratorClass, "
    "batch, env, sparkConf, evaluatorResults, evaluatorResultsHTML, evaluatorResultsJSON"
)


class SQLEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client: SQLStorageClient):
        self._c = client

    def insert(self, i: EvaluationInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        i.id = iid
        self._c.upsert(
            "evaluationinstances",
            "id",
            iid,
            f"INSERT INTO evaluationinstances ({_EVI_COLS}) "
            f"VALUES ({','.join('?' * 12)})",
            (
                iid,
                i.status,
                _micros(i.start_time),
                _micros(i.end_time),
                i.evaluation_class,
                i.engine_params_generator_class,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.evaluator_results,
                i.evaluator_results_html,
                i.evaluator_results_json,
            ),
        )
        return iid

    @staticmethod
    def _row(r: tuple) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=_from_micros(r[2], "Z"),
            end_time=_from_micros(r[3], "Z"),
            evaluation_class=r[4],
            engine_params_generator_class=r[5],
            batch=r[6],
            env=json.loads(r[7]),
            spark_conf=json.loads(r[8]),
            evaluator_results=r[9],
            evaluator_results_html=r[10],
            evaluator_results_json=r[11],
        )

    def get(self, instance_id: str) -> EvaluationInstance | None:
        rows = self._c.query(
            f"SELECT {_EVI_COLS} FROM evaluationinstances WHERE id=?", (instance_id,)
        )
        return self._row(rows[0]) if rows else None

    def get_all(self) -> list[EvaluationInstance]:
        return [
            self._row(r)
            for r in self._c.query(f"SELECT {_EVI_COLS} FROM evaluationinstances")
        ]

    def get_completed(self) -> list[EvaluationInstance]:
        rows = self._c.query(
            f"SELECT {_EVI_COLS} FROM evaluationinstances WHERE status=? "
            "ORDER BY startTime DESC",
            (base.EvaluationInstanceStatus.EVALCOMPLETED,),
        )
        return [self._row(r) for r in rows]

    def update(self, i: EvaluationInstance) -> None:
        self.insert(i)

    def delete(self, instance_id: str) -> None:
        self._c.execute("DELETE FROM evaluationinstances WHERE id=?", (instance_id,))


class SQLModels(base.Models):
    def __init__(self, client: SQLStorageClient):
        self._c = client

    def insert(self, model: Model) -> None:
        blob = model.models
        binary = getattr(self._c._mod, "Binary", None)
        if binary is not None:
            blob = binary(blob)
        self._c.upsert(
            "models",
            "id",
            model.id,
            "INSERT INTO models (id, models) VALUES (?,?)",
            (model.id, blob),
        )

    def get(self, model_id: str) -> Model | None:
        rows = self._c.query("SELECT id, models FROM models WHERE id=?", (model_id,))
        if not rows:
            return None
        blob = rows[0][1]
        return Model(rows[0][0], bytes(blob))

    def delete(self, model_id: str) -> None:
        self._c.execute("DELETE FROM models WHERE id=?", (model_id,))
