"""Storage DAO tracing: every method call becomes a ``storage`` span.

The serving/ingestion paths touch storage through DAO objects (LEvents,
Apps, EngineInstances, ...); wrapping one with :func:`trace_dao` records
a span per method call — name ``storage.<dao>.<method>``, kind
``storage`` — carrying whatever trace id is current in the caller's
context. Combined with the ingress trace id installed by the servers,
that is the third hop of the acceptance trail: one trace id observed
across ingress, batch, and storage spans.

Composes with the resilience layer in either order; the convention used
by the servers is ``policy.call(traced_dao.method, ...)`` so retries of
one storage call show up as multiple storage spans on the same trace —
which is exactly what an operator debugging a slow request wants to see.
"""

from __future__ import annotations

from typing import Any

from predictionio_tpu.obs.tracing import Tracer, get_tracer


class TracedDAO:
    """Transparent proxy: callable public attributes are wrapped in a
    span; dunder/private attributes and non-callables pass through
    untouched (same shape as ``resilience.ResilientProxy``)."""

    def __init__(self, target: Any, dao_name: str, tracer: Tracer | None = None):
        object.__setattr__(self, "_target", target)
        object.__setattr__(self, "_dao_name", dao_name)
        object.__setattr__(self, "_tracer", tracer or get_tracer())

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._target, name)
        if not callable(attr) or name.startswith("_"):
            return attr
        tracer: Tracer = self._tracer
        span_name = f"storage.{self._dao_name}.{name}"

        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with tracer.span(span_name, kind="storage"):
                return attr(*args, **kwargs)

        wrapper.__name__ = getattr(attr, "__name__", name)
        return wrapper

    def __setattr__(self, name: str, value: Any) -> None:
        setattr(self._target, name, value)

    def __repr__(self) -> str:
        return f"TracedDAO({self._dao_name}, {self._target!r})"


def trace_dao(dao: Any, dao_name: str, tracer: Tracer | None = None) -> TracedDAO:
    """Wrap a storage DAO so every method call records a storage span."""
    return TracedDAO(dao, dao_name, tracer=tracer)
