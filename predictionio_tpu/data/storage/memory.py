"""In-memory storage backend — the unit-test default.

Plays the role the reference's H2-in-MySQL-mode test database plays in
``data/src/test/scala/.../storage/StorageMockContext.scala:22-62``: a fully
functional implementation of every DAO with zero external dependencies.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import threading
import uuid
from typing import Iterable, Iterator, Sequence

from predictionio_tpu.data.event import Event, ensure_aware
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)


def event_matches(
    e: Event,
    start_time: _dt.datetime | None = None,
    until_time: _dt.datetime | None = None,
    entity_type: str | None = None,
    entity_id: str | None = None,
    event_names: Sequence[str] | None = None,
    target_entity_type=...,
    target_entity_id=...,
) -> bool:
    """The shared filter predicate for the 9 find dimensions
    (ref LEvents.scala:188-200). start inclusive, until exclusive."""
    start_time = ensure_aware(start_time)
    until_time = ensure_aware(until_time)
    if start_time is not None and e.event_time < start_time:
        return False
    if until_time is not None and e.event_time >= until_time:
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in event_names:
        return False
    if target_entity_type is not ... and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not ... and e.target_entity_id != target_entity_id:
        return False
    return True


class MemoryEventStore:
    """Shared per-(app, channel) event table used by both L and P DAOs."""

    def __init__(self):
        self._lock = threading.RLock()
        self._tables: dict[tuple[int, int | None], dict[str, Event]] = {}
        self._versions: dict[tuple[int, int | None], int] = {}
        # snapshot-cache stamps must never collide with a *different*
        # in-memory store (another process, or another instance in this one)
        # whose counter happens to match — see version_stamp
        self.nonce = uuid.uuid4().hex[:12]

    def table(self, app_id: int, channel_id: int | None) -> dict[str, Event]:
        with self._lock:
            return self._tables.setdefault((app_id, channel_id), {})

    def bump(self, app_id: int, channel_id: int | None) -> None:
        with self._lock:
            key = (app_id, channel_id)
            self._versions[key] = self._versions.get(key, 0) + 1

    def version(self, app_id: int, channel_id: int | None) -> int:
        with self._lock:
            return self._versions.get((app_id, channel_id), 0)

    def drop(self, app_id: int, channel_id: int | None) -> None:
        with self._lock:
            self._tables.pop((app_id, channel_id), None)
            self.bump(app_id, channel_id)


class MemoryLEvents(base.LEvents):
    def __init__(self, store: MemoryEventStore | None = None):
        self._store = store or MemoryEventStore()

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        self._store.table(app_id, channel_id)
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        self._store.drop(app_id, channel_id)
        return True

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        stored = (
            event
            if event.event_id == event_id
            else dataclasses.replace(event, event_id=event_id)
        )
        with self._store._lock:
            self._store.table(app_id, channel_id)[event_id] = stored
            self._store.bump(app_id, channel_id)
        return event_id

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        return self._store.table(app_id, channel_id).get(event_id)

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        with self._store._lock:
            removed = self._store.table(app_id, channel_id).pop(event_id, None)
            if removed is not None:
                self._store.bump(app_id, channel_id)
            return removed is not None

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        with self._store._lock:
            events = list(self._store.table(app_id, channel_id).values())
        events = [
            e
            for e in events
            if event_matches(
                e,
                start_time,
                until_time,
                entity_type,
                entity_id,
                event_names,
                target_entity_type,
                target_entity_id,
            )
        ]
        events.sort(key=lambda e: e.event_time, reverse=reversed)
        if limit is not None and limit >= 0:
            events = events[:limit]
        return iter(events)

    def find_after(
        self,
        app_id: int,
        channel_id: int | None = None,
        cursor: tuple[int, str] | None = None,
        limit: int = 100,
    ) -> list[base.Event]:
        with self._store._lock:
            events = list(self._store.table(app_id, channel_id).values())
        return base.scan_find_after(events, cursor, limit)


class MemoryPEvents(base.PEvents):
    def __init__(self, store: MemoryEventStore, levents: MemoryLEvents | None = None):
        self._store = store
        self._l = levents or MemoryLEvents(store)

    def find(self, app_id: int, channel_id: int | None = None, **kw) -> Iterator[Event]:
        return self._l.find(app_id, channel_id, **kw)

    def write(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> None:
        for e in events:
            self._l.insert(e, app_id, channel_id)

    def delete(
        self, event_ids: Iterable[str], app_id: int, channel_id: int | None = None
    ) -> None:
        for eid in event_ids:
            self._l.delete(eid, app_id, channel_id)

    def version_stamp(self, app_id: int, channel_id: int | None = None) -> str | None:
        return f"mem:{self._store.version(app_id, channel_id)}"

    def store_identity(self) -> str | None:
        return f"mem:{self._store.nonce}"


class MemoryApps(base.Apps):
    def __init__(self):
        self._apps: dict[int, App] = {}
        self._next = 1
        self._lock = threading.RLock()

    def insert(self, app: App) -> int | None:
        with self._lock:
            app_id = app.id
            if app_id == 0:
                app_id = self._next
            if app_id in self._apps or any(
                a.name == app.name for a in self._apps.values()
            ):
                return None
            self._next = max(self._next, app_id) + 1
            self._apps[app_id] = App(app_id, app.name, app.description)
            return app_id

    def get(self, app_id: int) -> App | None:
        return self._apps.get(app_id)

    def get_by_name(self, name: str) -> App | None:
        return next((a for a in self._apps.values() if a.name == name), None)

    def get_all(self) -> list[App]:
        return sorted(self._apps.values(), key=lambda a: a.id)

    def update(self, app: App) -> None:
        with self._lock:
            if any(
                a.name == app.name and a.id != app.id
                for a in self._apps.values()
            ):
                raise ValueError(f"app name already in use: {app.name!r}")
            self._apps[app.id] = app

    def delete(self, app_id: int) -> None:
        with self._lock:
            self._apps.pop(app_id, None)


class MemoryAccessKeys(base.AccessKeys):
    def __init__(self):
        self._keys: dict[str, AccessKey] = {}
        self._lock = threading.RLock()

    def insert(self, k: AccessKey) -> str | None:
        with self._lock:
            key = k.key or base.generate_access_key()
            if key in self._keys:
                return None
            self._keys[key] = AccessKey(key, k.appid, tuple(k.events))
            return key

    def get(self, key: str) -> AccessKey | None:
        return self._keys.get(key)

    def get_all(self) -> list[AccessKey]:
        return list(self._keys.values())

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        return [k for k in self._keys.values() if k.appid == app_id]

    def update(self, k: AccessKey) -> None:
        with self._lock:
            self._keys[k.key] = k

    def delete(self, key: str) -> None:
        with self._lock:
            self._keys.pop(key, None)


class MemoryChannels(base.Channels):
    def __init__(self):
        self._channels: dict[int, Channel] = {}
        self._next = 1
        self._lock = threading.RLock()

    def insert(self, channel: Channel) -> int | None:
        if not Channel.is_valid_name(channel.name):
            return None
        with self._lock:
            channel_id = channel.id or self._next
            if channel_id in self._channels:
                return None
            self._next = max(self._next, channel_id) + 1
            self._channels[channel_id] = Channel(channel_id, channel.name, channel.appid)
            return channel_id

    def get(self, channel_id: int) -> Channel | None:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> list[Channel]:
        return [c for c in self._channels.values() if c.appid == app_id]

    def delete(self, channel_id: int) -> None:
        with self._lock:
            self._channels.pop(channel_id, None)


class MemoryEngineInstances(base.EngineInstances):
    def __init__(self):
        self._instances: dict[str, EngineInstance] = {}
        self._lock = threading.RLock()

    def insert(self, instance: EngineInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            instance.id = iid
            self._instances[iid] = instance
            return iid

    def get(self, instance_id: str) -> EngineInstance | None:
        return self._instances.get(instance_id)

    def get_all(self) -> list[EngineInstance]:
        return list(self._instances.values())

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        out = [
            i
            for i in self._instances.values()
            if i.status == base.EngineInstanceStatus.COMPLETED
            and i.engine_id == engine_id
            and i.engine_version == engine_version
            and i.engine_variant == engine_variant
        ]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> EngineInstance | None:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance: EngineInstance) -> None:
        with self._lock:
            self._instances[instance.id] = instance

    def delete(self, instance_id: str) -> None:
        with self._lock:
            self._instances.pop(instance_id, None)


class MemoryEvaluationInstances(base.EvaluationInstances):
    def __init__(self):
        self._instances: dict[str, EvaluationInstance] = {}
        self._lock = threading.RLock()

    def insert(self, instance: EvaluationInstance) -> str:
        with self._lock:
            iid = instance.id or uuid.uuid4().hex
            instance.id = iid
            self._instances[iid] = instance
            return iid

    def get(self, instance_id: str) -> EvaluationInstance | None:
        return self._instances.get(instance_id)

    def get_all(self) -> list[EvaluationInstance]:
        return list(self._instances.values())

    def get_completed(self) -> list[EvaluationInstance]:
        out = [
            i
            for i in self._instances.values()
            if i.status == base.EvaluationInstanceStatus.EVALCOMPLETED
        ]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def update(self, instance: EvaluationInstance) -> None:
        with self._lock:
            self._instances[instance.id] = instance

    def delete(self, instance_id: str) -> None:
        with self._lock:
            self._instances.pop(instance_id, None)


class MemoryModels(base.Models):
    def __init__(self):
        self._models: dict[str, Model] = {}
        self._lock = threading.RLock()

    def insert(self, model: Model) -> None:
        with self._lock:
            self._models[model.id] = model

    def get(self, model_id: str) -> Model | None:
        return self._models.get(model_id)

    def delete(self, model_id: str) -> None:
        with self._lock:
            self._models.pop(model_id, None)


class MemoryStorageClient:
    """Backend entry point discovered by the registry (type name: ``memory``).

    One client instance = one isolated universe of DAOs (like one H2 database
    in the reference's tests)."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self._event_store = MemoryEventStore()
        self._levents = MemoryLEvents(self._event_store)
        self._pevents = MemoryPEvents(self._event_store, self._levents)
        self._apps = MemoryApps()
        self._access_keys = MemoryAccessKeys()
        self._channels = MemoryChannels()
        self._engine_instances = MemoryEngineInstances()
        self._evaluation_instances = MemoryEvaluationInstances()
        self._models = MemoryModels()

    # DAO accessors used by registry reflection
    def l_events(self) -> MemoryLEvents:
        return self._levents

    def p_events(self) -> MemoryPEvents:
        return self._pevents

    def apps(self) -> MemoryApps:
        return self._apps

    def access_keys(self) -> MemoryAccessKeys:
        return self._access_keys

    def channels(self) -> MemoryChannels:
        return self._channels

    def engine_instances(self) -> MemoryEngineInstances:
        return self._engine_instances

    def evaluation_instances(self) -> MemoryEvaluationInstances:
        return self._evaluation_instances

    def models(self) -> MemoryModels:
        return self._models
