"""Storage SPI: metadata/event/model DAOs + pluggable backend registry.

Reference parity: ``data/.../storage/Storage.scala`` (env-var source
discovery, reflection instantiation, repository accessors) and the DAO traits
``LEvents.scala`` / ``PEvents.scala`` / ``Apps.scala`` / ``AccessKeys.scala``
/ ``Channels.scala`` / ``EngineInstances.scala`` / ``EvaluationInstances.scala``
/ ``Models.scala``.
"""

from predictionio_tpu.data.storage.base import (
    AccessKey,
    AccessKeys,
    App,
    Apps,
    Channel,
    Channels,
    EngineInstance,
    EngineInstances,
    EvaluationInstance,
    EvaluationInstances,
    LEvents,
    Model,
    Models,
    PEvents,
)
from predictionio_tpu.data.storage.registry import Storage, StorageError

__all__ = [
    "AccessKey",
    "AccessKeys",
    "App",
    "Apps",
    "Channel",
    "Channels",
    "EngineInstance",
    "EngineInstances",
    "EvaluationInstance",
    "EvaluationInstances",
    "LEvents",
    "Model",
    "Models",
    "PEvents",
    "Storage",
    "StorageError",
]
