"""aiohttp glue shared by QueryServer and EventServer.

Both servers export the identical observability surface — ``/metrics``
(Prometheus text), ``/traces/recent`` (span ring), and breaker
state/transition instruments. This module is that surface's single
definition, so the two servers cannot drift apart route by route.
"""

from __future__ import annotations

from aiohttp import web

from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import Tracer
from predictionio_tpu.resilience import CLOSED, HALF_OPEN, OPEN, CircuitBreaker

# numeric encoding of breaker states for the pio_breaker_state gauge
BREAKER_STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class BreakerInstruments:
    """Breaker observability: a transition counter fed by the breaker's
    listener hook plus a state gauge refreshed at scrape time (the
    open->half-open move happens lazily on the clock, which no listener
    event covers)."""

    def __init__(self, registry: MetricsRegistry):
        self._transitions = registry.counter(
            "pio_breaker_transitions_total",
            "circuit breaker state transitions, by breaker and target state",
            labelnames=("breaker", "to"),
        )
        self._state = registry.gauge(
            "pio_breaker_state",
            "breaker state (0=closed, 1=half-open, 2=open)",
            labelnames=("breaker",),
        )
        self._breakers: list[CircuitBreaker] = []

    def watch(self, breaker: CircuitBreaker) -> CircuitBreaker:
        """Attach the transition listener and include the breaker in
        scrape-time state refreshes. Returns the breaker for chaining.
        Chains (never overwrites) any listener already installed — the
        rollout router hangs its trip-to-rollback hook on the same
        breaker the instruments watch."""
        breaker.chain_listener(self.on_transition)
        self._breakers.append(breaker)
        self.collect()
        return breaker

    def on_transition(self, name: str, old: str, new: str) -> None:
        self._transitions.inc(breaker=name, to=new)
        self._state.set(BREAKER_STATE_VALUES.get(new, -1.0), breaker=name)

    def collect(self) -> None:
        """Registry collector: refresh every watched breaker's gauge."""
        for breaker in self._breakers:
            state = breaker.snapshot()["state"]
            self._state.set(
                BREAKER_STATE_VALUES.get(state, -1.0), breaker=breaker.name
            )


def metrics_response(registry: MetricsRegistry) -> web.Response:
    """Prometheus text exposition of the registry. Rendering snapshots
    under per-metric locks; cheap enough to run on the event loop."""
    return web.Response(
        text=registry.render_prometheus(),
        headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
    )


def traces_response(tracer: Tracer, request: web.Request) -> web.Response:
    """Recent spans from the ring buffer (``?limit=N``, newest first)."""
    try:
        limit = int(request.query.get("limit", 100))
    except ValueError:
        return web.json_response(
            {"message": "limit must be an integer"}, status=400
        )
    return web.json_response({"spans": tracer.recent(limit)})
