"""aiohttp glue shared by QueryServer and EventServer.

Both servers export the identical observability surface — ``/metrics``
(Prometheus text), ``/traces/recent`` (span ring), and breaker
state/transition instruments. This module is that surface's single
definition, so the two servers cannot drift apart route by route.
"""

from __future__ import annotations

from aiohttp import web

from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.slo import SLOEngine
from predictionio_tpu.obs.tracing import Tracer
from predictionio_tpu.resilience import CLOSED, HALF_OPEN, OPEN, CircuitBreaker

# numeric encoding of breaker states for the pio_breaker_state gauge
BREAKER_STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)


class BreakerInstruments:
    """Breaker observability: a transition counter fed by the breaker's
    listener hook plus a state gauge refreshed at scrape time (the
    open->half-open move happens lazily on the clock, which no listener
    event covers)."""

    def __init__(self, registry: MetricsRegistry):
        self._transitions = registry.counter(
            "pio_breaker_transitions_total",
            "circuit breaker state transitions, by breaker and target state",
            labelnames=("breaker", "to"),
        )
        self._state = registry.gauge(
            "pio_breaker_state",
            "breaker state (0=closed, 1=half-open, 2=open)",
            labelnames=("breaker",),
        )
        self._breakers: list[CircuitBreaker] = []

    def watch(self, breaker: CircuitBreaker) -> CircuitBreaker:
        """Attach the transition listener and include the breaker in
        scrape-time state refreshes. Returns the breaker for chaining.
        Chains (never overwrites) any listener already installed — the
        rollout router hangs its trip-to-rollback hook on the same
        breaker the instruments watch."""
        breaker.chain_listener(self.on_transition)
        self._breakers.append(breaker)
        self.collect()
        return breaker

    def unwatch(self, breaker: CircuitBreaker) -> None:
        """Forget a retired replica's breaker: stop refreshing it and
        drop its state gauge series from the exposition (the transition
        *counter* stays — history is monotonic truth, the gauge is a
        live-set claim)."""
        self._breakers = [b for b in self._breakers if b is not breaker]
        self._state.remove(breaker=breaker.name)

    def on_transition(self, name: str, old: str, new: str) -> None:
        self._transitions.inc(breaker=name, to=new)
        self._state.set(BREAKER_STATE_VALUES.get(new, -1.0), breaker=name)

    def collect(self) -> None:
        """Registry collector: refresh every watched breaker's gauge."""
        for breaker in self._breakers:
            state = breaker.snapshot()["state"]
            self._state.set(
                BREAKER_STATE_VALUES.get(state, -1.0), breaker=breaker.name
            )


def _wants_exemplars(request: web.Request | None) -> bool:
    """Exemplars ride only on negotiated scrapes: OpenMetrics in Accept
    (what Prometheus sends when exemplar scraping is on) or an explicit
    ``?exemplars=1``. The default stays strict v0.0.4 — a plain-text
    parser rejects exemplar syntax, and breaking every stock scrape to
    decorate buckets would be a bad trade."""
    if request is None:
        return False
    if request.query.get("exemplars", "") not in ("", "0", "false"):
        return True
    return "openmetrics" in request.headers.get("Accept", "").lower()


def metrics_response(
    registry: MetricsRegistry, request: web.Request | None = None
) -> web.Response:
    """Prometheus text exposition of the registry. Rendering snapshots
    under per-metric locks; cheap enough to run on the event loop."""
    exemplars = _wants_exemplars(request)
    return web.Response(
        text=registry.render_prometheus(exemplars=exemplars),
        headers={
            "Content-Type": (
                OPENMETRICS_CONTENT_TYPE if exemplars else PROMETHEUS_CONTENT_TYPE
            )
        },
    )


def slo_response(engine: SLOEngine) -> web.Response:
    """The ``/slo`` JSON report: burn rates per objective and window."""
    return web.json_response(engine.report())


def traces_response(tracer: Tracer, request: web.Request) -> web.Response:
    """Recent spans from the ring buffer (``?limit=N``, newest first)."""
    try:
        limit = int(request.query.get("limit", 100))
    except ValueError:
        return web.json_response(
            {"message": "limit must be an integer"}, status=400
        )
    return web.json_response({"spans": tracer.recent(limit)})
