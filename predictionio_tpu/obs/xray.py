"""``pio xray``: training made as observable as serving.

PRs 3 and 6 gave every *query* a phase waterfall, SLOs, and a perf gate;
training was still a black box — ``run_train``, the stream fold-in loop,
and the ``parallel/`` mesh path emitted no step timings, no memory
numbers, and no sharding evidence. ALX (PAPERS.md) ships pod-scale ALS by
reasoning explicitly about per-chip factor-table placement and step cost;
this module builds the same instruments for the framework:

- :class:`TrainProfile` — a **training step profiler**. Trainers run
  inside one recorder that captures a per-iteration timeline of phases
  (``host_etl`` / ``sweep`` / ``solve`` / ``eval``; open vocabulary with
  those four canonical) with monotonic wall time, device time (through
  :meth:`TrainProfile.device_barrier` / ``timed_block_until_ready``),
  rows/s throughput, and a per-iteration convergence metric. The phases
  **tile the measured train wall clock** (the contract tests assert the
  attributed sum lands within 10% — the same contract style as the PR-6
  serving waterfall), export as ``pio_train_*`` metrics + ``train.step``
  spans, and serialize as a compact JSON profile that every registry
  publish attaches to its :class:`~predictionio_tpu.registry.ModelManifest`.
- :func:`estimate_factors` — an **HBM capacity planner**: predicted
  per-device bytes for the factor tables and solver workspace of an ALS
  train over a mesh, cross-checked at runtime against
  ``jax.live_arrays()`` (:func:`live_array_bytes` /
  :func:`live_bytes_per_device`). Surfaced as ``pio doctor --capacity``
  so ROADMAP item 1's "10M+ users without exceeding per-device HBM"
  becomes a preflight answer instead of an OOM.
- a **sharding inspector** — given a pjit'd train step over a
  ``parallel/mesh.py`` mesh, report each array's axis→mesh placement
  (:func:`describe_shardings`), flag fully-replicated large arrays
  (:func:`find_replicated`), and count collectives in the compiled HLO
  (:func:`count_collectives`) so an unintended all-gather is a number in
  ``MULTICHIP_r*.json``, not a surprise on the pod.

Profiles flow through a contextvar (:func:`use_profile` /
:func:`current_profile`): trainer code calls the module-level
:func:`phase` / :func:`device_fetch` helpers, which no-op when nothing is
recording — the un-profiled path stays fully async.

jax is imported lazily; constructing a profile or running the capacity
planner costs nothing on processes that never touch a device
(``pio doctor --capacity`` is pure arithmetic).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import time
from typing import Any, Callable, Iterator

from predictionio_tpu.obs.jaxprof import monitoring_totals
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import Tracer, get_tracer

# canonical phase vocabulary (open: trainers may add more, these four are
# what the docs tables and `pio top` expect)
PHASE_HOST_ETL = "host_etl"  # event-store reads, packing, uploads, serialize
PHASE_SWEEP = "sweep"  # the alternating half-solves / fold-in absorbs
PHASE_SOLVE = "solve"  # whole-algorithm train when not iteration-split
PHASE_EVAL = "eval"  # convergence / drift evaluation

TRAIN_PHASES: tuple[str, ...] = (PHASE_HOST_ETL, PHASE_SWEEP, PHASE_SOLVE, PHASE_EVAL)

# per-step timeline entries kept in the serialized profile; aggregates are
# exact regardless (a 10k-iteration train must not ship a 10k-row JSON)
DEFAULT_TIMELINE_CAP = 256


@dataclasses.dataclass
class _PhaseAgg:
    wall_s: float = 0.0
    device_s: float = 0.0
    count: int = 0


def register_train_metrics(registry: MetricsRegistry) -> dict[str, Any]:
    """Get-or-create the ``pio_train_*`` metric family on a registry.
    Idempotent; shared by every :class:`TrainProfile` bound to the same
    registry, and called eagerly by surfaces that export the family
    (``StreamInstruments``) so the documented metrics exist — with zero
    series — before the first train step lands."""
    return {
        "steps": registry.counter(
            "pio_train_steps_total",
            "training iterations (batch sweeps / stream fold-in batches)",
            labelnames=("trainer",),
        ),
        "phase": registry.histogram(
            "pio_train_phase_seconds",
            "per-occurrence training phase wall time "
            "(host_etl|sweep|solve|eval; exclusive/self time)",
            labelnames=("trainer", "phase"),
        ),
        "device": registry.counter(
            "pio_train_device_seconds_total",
            "device time accounted inside training phases "
            "(barrier-confirmed fetches)",
            labelnames=("trainer", "phase"),
        ),
        "rows": registry.counter(
            "pio_train_rows_total",
            "training rows/examples processed",
            labelnames=("trainer",),
        ),
        "active": registry.gauge(
            "pio_train_active",
            "1 while this trainer's profile is measuring",
            labelnames=("trainer",),
        ),
        "phase_g": registry.gauge(
            "pio_train_phase",
            "1 for the phase this trainer is currently executing",
            labelnames=("trainer", "phase"),
        ),
        "peak": registry.gauge(
            "pio_train_peak_bytes_per_device",
            "peak live device bytes sampled during training (busiest device)",
            labelnames=("trainer",),
        ),
        "est": registry.gauge(
            "pio_train_est_bytes_per_device",
            "capacity-planner predicted per-device bytes "
            "(obs.xray.estimate_factors)",
            labelnames=("trainer",),
        ),
    }


class TrainProfile:
    """Per-train recorder: phases, steps, device time, memory, lineage.

    Wall clock accumulates only inside :meth:`measure` blocks, so a
    stream pipeline that folds a publish-span across many cycles (with
    sleeps in between) still satisfies the tiling contract. Phases nest
    with *exclusive* (self-time) semantics: a ``host_etl`` pack inside a
    ``solve`` block attributes to ``host_etl``, never double-counts.

    Not thread-safe by design — one profile records one trainer's loop
    (the contextvar keeps concurrent trains on separate profiles).
    """

    def __init__(
        self,
        trainer: str,
        registry: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
        timeline_cap: int = DEFAULT_TIMELINE_CAP,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self.trainer = trainer
        self.registry = registry
        self.tracer = tracer or get_tracer()
        self.timeline_cap = max(1, timeline_cap)
        self._clock = clock
        self.phases: dict[str, _PhaseAgg] = {}
        self.timeline: list[dict[str, Any]] = []
        self.timeline_truncated = False
        self.steps_total = 0
        self.rows_total = 0
        self.device_s = 0.0
        self.peak_live_bytes = 0
        self.peak_bytes_per_device = 0
        self.device_memory_stats: dict[str, Any] | None = None
        self.estimate: CapacityEstimate | None = None
        self.finished = False
        self._wall_s = 0.0
        self._measure_t0: float | None = None
        self._phase_stack: list[list[Any]] = []  # [name, t0, child_elapsed]
        self._step_rec: dict[str, Any] | None = None
        self._xla0 = monitoring_totals()
        self.xla_compiles = 0
        self.xla_compile_s = 0.0
        if registry is not None:
            m = register_train_metrics(registry)
            self._m_steps = m["steps"]
            self._m_phase = m["phase"]
            self._m_device = m["device"]
            self._m_rows = m["rows"]
            self._m_active = m["active"]
            self._m_phase_g = m["phase_g"]
            self._m_peak = m["peak"]
            self._m_est = m["est"]

    # ----------------------------------------------------------- measuring
    def resume(self) -> None:
        if self.finished or self._measure_t0 is not None:
            return
        self._measure_t0 = self._clock()
        if self.registry is not None:
            self._m_active.set(1.0, trainer=self.trainer)

    def pause(self) -> None:
        if self._measure_t0 is None:
            return
        self._wall_s += self._clock() - self._measure_t0
        self._measure_t0 = None
        if self.registry is not None:
            self._m_active.set(0.0, trainer=self.trainer)

    @contextlib.contextmanager
    def measure(self) -> Iterator["TrainProfile"]:
        """Accumulate wall clock for the duration of the block."""
        self.resume()
        try:
            yield self
        finally:
            self.pause()

    @property
    def wall_s(self) -> float:
        if self._measure_t0 is not None:
            return self._wall_s + (self._clock() - self._measure_t0)
        return self._wall_s

    @property
    def attributed_s(self) -> float:
        """Wall time covered by phases — the tiling-contract numerator."""
        return sum(p.wall_s for p in self.phases.values())

    # -------------------------------------------------------------- phases
    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Record a phase with exclusive-time nesting semantics."""
        frame: list[Any] = [name, self._clock(), 0.0]
        self._phase_stack.append(frame)
        if self.registry is not None:
            self._m_phase_g.set(1.0, trainer=self.trainer, phase=name)
        try:
            yield
        finally:
            self._phase_stack.pop()
            elapsed = self._clock() - frame[1]
            self_s = max(0.0, elapsed - frame[2])
            if self._phase_stack:
                # parent sees the whole nested interval as child time
                self._phase_stack[-1][2] += elapsed
            agg = self.phases.setdefault(name, _PhaseAgg())
            agg.wall_s += self_s
            agg.count += 1
            if self._step_rec is not None:
                ph = self._step_rec["phases"]
                ph[name] = ph.get(name, 0.0) + self_s
            if self.registry is not None:
                self._m_phase.observe(self_s, trainer=self.trainer, phase=name)
                self._m_phase_g.set(0.0, trainer=self.trainer, phase=name)
                if self._phase_stack:
                    self._m_phase_g.set(
                        1.0, trainer=self.trainer, phase=self._phase_stack[-1][0]
                    )

    # --------------------------------------------------------------- steps
    @contextlib.contextmanager
    def step(self, **tags: Any) -> Iterator[dict[str, Any]]:
        """One training iteration: a ``train.step`` span plus a timeline
        record. The yielded dict is the record — set ``metric`` (the
        iteration's convergence number) or extra keys mid-flight."""
        rec: dict[str, Any] = {"i": self.steps_total, "phases": {}, "metric": None}
        prev = self._step_rec
        self._step_rec = rec
        t0 = self._clock()
        try:
            with self.tracer.span(
                "train.step", kind="train", trainer=self.trainer,
                step=self.steps_total, **tags,
            ) as sp:
                yield rec
                sp.tags["metric"] = rec.get("metric")
        finally:
            rec["wall_s"] = round(self._clock() - t0, 6)
            rec["phases"] = {k: round(v, 6) for k, v in rec["phases"].items()}
            self._step_rec = prev
            self.steps_total += 1
            if len(self.timeline) < self.timeline_cap:
                self.timeline.append(rec)
            else:
                self.timeline_truncated = True
            if self.registry is not None:
                self._m_steps.inc(trainer=self.trainer)

    def add_rows(self, n: int) -> None:
        self.rows_total += int(n)
        if self.registry is not None:
            self._m_rows.inc(int(n), trainer=self.trainer)

    # -------------------------------------------------------- device time
    def _current_phase(self) -> str:
        return self._phase_stack[-1][0] if self._phase_stack else "unattributed"

    def note_device_time(self, seconds: float, where: str = "") -> None:
        """Attribute device/stall seconds to the current phase. Called by
        ``obs.jaxprof.timed_block_until_ready`` so sanctioned host-syncs
        anywhere inside a profiled train land in the profile."""
        seconds = max(0.0, seconds)
        self.device_s += seconds
        phase = self._current_phase()
        agg = self.phases.setdefault(phase, _PhaseAgg())
        agg.device_s += seconds
        if self._step_rec is not None:
            self._step_rec["device_s"] = round(
                self._step_rec.get("device_s", 0.0) + seconds, 6
            )
        if self.registry is not None:
            self._m_device.inc(seconds, trainer=self.trainer, phase=phase)

    def device_fetch(self, x: Any, where: str = "train") -> Any:
        """``np.asarray`` fetch with the stall accounted into the profile
        (the sanctioned form the ``train-unaccounted-sync`` lint demands)."""
        import numpy as np

        t0 = self._clock()
        out = np.asarray(x)
        self.note_device_time(self._clock() - t0, where)
        # sample while ``x`` is still referenced: for one-shot fetch paths
        # (fold-in solve, sharded final fetch) this is the only moment the
        # transient device arrays are observable as live
        self.sample_memory()
        return out

    def device_barrier(self, *arrays: Any, where: str = "train") -> float:
        """TRUE completion barrier (same rationale as ``ops.als
        .fetch_barrier``: ``block_until_ready`` only acks dispatch through
        a tunnel): fetch a scalar *derived* from every array — it cannot
        exist until the arrays are materialized. The stall is accounted to
        the current phase; returns the checksum (a cheap per-iteration
        convergence signal: its deltas shrink as factors converge)."""
        t0 = self._clock()
        try:
            import jax.numpy as jnp
            import numpy as np

            acc = None
            for a in arrays:
                s = jnp.sum(a, dtype=jnp.float32)
                acc = s if acc is None else acc + s
            # ONE fetch for the combined scalar (the ops.als.fetch_barrier
            # methodology): per-array fetches would pay N tunnel RTTs each
            # iteration and inflate the recorded device time
            total = float(np.asarray(acc)) if acc is not None else 0.0
        except Exception:
            import jax

            jax.block_until_ready(arrays)
            total = 0.0
        self.note_device_time(self._clock() - t0, where)
        return total

    # -------------------------------------------------------------- memory
    def sample_memory(self) -> int:
        """Sample live-array bytes (global + busiest device) and device
        allocator stats; tracks peaks. Cheap enough to run per iteration."""
        total = live_array_bytes()
        if total > self.peak_live_bytes:
            self.peak_live_bytes = total
        per = live_bytes_per_device()
        busiest = max(per.values(), default=total)
        if busiest > self.peak_bytes_per_device:
            self.peak_bytes_per_device = busiest
            if self.registry is not None:
                self._m_peak.set(float(busiest), trainer=self.trainer)
        stats = device_memory_stats()
        if stats:
            self.device_memory_stats = stats
        return total

    def set_estimate(self, estimate: "CapacityEstimate") -> None:
        self.estimate = estimate
        if self.registry is not None:
            self._m_est.set(
                float(estimate.per_device_bytes), trainer=self.trainer
            )

    # -------------------------------------------------------------- finish
    def finish(self) -> "TrainProfile":
        """Close the profile: stop the clock, final memory sample, capture
        XLA compile totals. Idempotent."""
        if self.finished:
            return self
        self.pause()
        try:
            self.sample_memory()
        except Exception:  # noqa: BLE001 - memory evidence is best-effort
            pass
        ev, secs = monitoring_totals()
        self.xla_compiles = max(0, ev - self._xla0[0])
        self.xla_compile_s = max(0.0, secs - self._xla0[1])
        self.finished = True
        if self.registry is not None:
            for ph in self.phases:
                self._m_phase_g.set(0.0, trainer=self.trainer, phase=ph)
        return self

    def to_json_dict(self) -> dict[str, Any]:
        """The compact profile a ModelManifest carries (``pio models
        show`` renders it; ``diff`` compares wall + memory)."""
        wall = self.wall_s
        attributed = self.attributed_s
        return {
            "trainer": self.trainer,
            "wallClockS": round(wall, 6),
            "attributedS": round(attributed, 6),
            "deviceS": round(self.device_s, 6),
            # device seconds ÷ ATTRIBUTED wall — the docs/PERF.md and
            # `pio top` definition; ÷ raw wall would read up to the 10%
            # tiling slack lower for the same train
            "deviceTimeFrac": (
                round(self.device_s / attributed, 4)
                if attributed > 0
                else (round(self.device_s / wall, 4) if wall > 0 else 0.0)
            ),
            "steps": self.steps_total,
            "rowsTotal": self.rows_total,
            "rowsPerS": round(self.rows_total / wall, 2) if wall > 0 else 0.0,
            "phases": {
                name: {
                    "count": agg.count,
                    "wallS": round(agg.wall_s, 6),
                    "deviceS": round(agg.device_s, 6),
                    "meanS": round(agg.wall_s / agg.count, 6) if agg.count else 0.0,
                }
                for name, agg in sorted(self.phases.items())
            },
            "timeline": self.timeline,
            "timelineTruncated": self.timeline_truncated,
            "memory": {
                "peakLiveBytes": self.peak_live_bytes,
                "peakBytesPerDevice": self.peak_bytes_per_device,
                "deviceStats": self.device_memory_stats,
            },
            "estimate": (
                self.estimate.to_json_dict() if self.estimate is not None else None
            ),
            "xlaCompiles": self.xla_compiles,
            "xlaCompileS": round(self.xla_compile_s, 3),
        }


# ---------------------------------------------------------------------------
# current-profile plumbing (module-level helpers trainers call)
# ---------------------------------------------------------------------------

_CURRENT: contextvars.ContextVar[TrainProfile | None] = contextvars.ContextVar(
    "pio_train_profile", default=None
)


def current_profile() -> TrainProfile | None:
    prof = _CURRENT.get()
    if prof is not None and prof.finished:
        return None
    return prof


@contextlib.contextmanager
def use_profile(profile: TrainProfile) -> Iterator[TrainProfile]:
    token = _CURRENT.set(profile)
    try:
        yield profile
    finally:
        _CURRENT.reset(token)


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Module-level phase marker: records into the current profile, no-ops
    when nothing is profiling — trainer code stays unconditional."""
    prof = current_profile()
    if prof is None:
        yield
        return
    with prof.phase(name):
        yield


def device_fetch(x: Any, where: str = "train") -> Any:
    """Profiled ``np.asarray`` (plain fetch when nothing is recording)."""
    prof = current_profile()
    if prof is None:
        import numpy as np

        # pio-lint: disable=train-unaccounted-sync -- device_fetch IS the accounted fetch; unprofiled runs have no profile to account into
        return np.asarray(x)
    return prof.device_fetch(x, where)


# ---------------------------------------------------------------------------
# live-memory accounting
# ---------------------------------------------------------------------------


def _jax_backend_live() -> bool:
    """True only when jax is imported AND its backend is already
    initialized. ``jax.live_arrays()`` calls ``get_backend()``, which
    would *initialize* the backend — on a pure-host train (LocalAlgorithm
    engines) that means contending for an exclusively-held accelerator,
    or hanging on a wedged TPU tunnel, just to read a memory gauge. The
    samplers below therefore report 0/empty until some trainer actually
    touched a device (same contract as run_train's multi-host probe)."""
    import sys

    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge as xb

        return bool(getattr(xb, "_backends", None))
    except Exception:  # noqa: BLE001 - private API drift: degrade quietly
        return False


def live_array_bytes() -> int:
    """Total bytes of live jax arrays (global across shards); 0 without
    an initialized jax backend. The runtime cross-check for
    :func:`estimate_factors`."""
    if not _jax_backend_live():
        return 0
    try:
        import jax

        return sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())
    except Exception:  # noqa: BLE001 - absent/old jax, backend teardown
        return 0


def live_bytes_per_device() -> dict[str, int]:
    """Live bytes per addressable device (replicated arrays count once per
    device they occupy — this is resident HBM, not logical size)."""
    if not _jax_backend_live():
        return {}
    per: dict[str, int] = {}
    try:
        import jax

        for a in jax.live_arrays():
            try:
                for sh in a.addressable_shards:
                    data = sh.data
                    if data is not None:
                        key = str(sh.device)
                        per[key] = per.get(key, 0) + int(data.nbytes)
            except Exception:  # noqa: BLE001 - deleted/donated buffers race
                continue
    except Exception:  # noqa: BLE001
        return {}
    return per


def device_memory_stats() -> dict[str, Any] | None:
    """Allocator stats of the busiest device (``bytes_in_use`` /
    ``peak_bytes_in_use`` on TPU/GPU; CPU backends return None)."""
    if not _jax_backend_live():
        return None
    try:
        import jax

        best: dict[str, Any] | None = None
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            if best is None or stats.get("bytes_in_use", 0) > best.get(
                "bytes_in_use", 0
            ):
                best = {
                    "device": str(d),
                    "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
                    "bytes_limit": int(stats.get("bytes_limit", 0)),
                }
        return best
    except Exception:  # noqa: BLE001
        return None


# ---------------------------------------------------------------------------
# capacity planner
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CapacityEstimate:
    """Predicted ALS training footprint. All byte fields are *model*
    numbers — what the formulation requires, cross-checkable against
    ``live_array_bytes()`` (the contract test holds the factor-table term
    to within 15% of measurement on the CPU backend).

    The model (mirrors ``ops/als.py`` structures; f32 accumulators):

    - ``factor_bytes``: both factor tables incl. the +1 dummy padding row
      — ``((users+1) + (items+1)) * k * bytes_per_elem``; a bf16
      ``gather_dtype`` adds a half-size copy of each table (the gather
      operand copy the solver keeps).
    - ``workspace_bytes``: the larger half-solve's normal-equation
      accumulators ``A [E,k,k] + b [E,k] + counts [E]`` at f32, plus ~4
      CG work vectors per system.
    - ``wire_bytes``: device-resident block tables for ``nnz`` ratings
      (cols int32 + vals f32 + mask int8 ≈ 9 B/slot, both sides) — 0 when
      ``nnz`` is unknown.
    - ``per_device_bytes``: everything row-sharded over ``n_devices``,
      PLUS one fully-gathered opposite factor table when sharded — the
      ALX schedule all-gathers the fixed side each half-solve, and that
      transient is exactly what OOMs first on a pod.
    """

    users: int
    items: int
    rank: int
    dtype: str
    gather_dtype: str
    n_devices: int
    nnz: int | None
    factor_bytes: int
    workspace_bytes: int
    wire_bytes: int
    total_bytes: int
    per_device_bytes: int

    def to_json_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def fits(self, hbm_bytes_per_device: int | float) -> bool:
        return self.per_device_bytes <= hbm_bytes_per_device


def _mesh_devices(mesh: Any) -> int:
    """Device count from a mesh spec: an int, a ``"data=8,model=2"``
    string, a ``{"data": 8}`` dict, a jax Mesh, or None (=1)."""
    if mesh is None:
        return 1
    if isinstance(mesh, int):
        return max(1, mesh)
    if isinstance(mesh, str):
        s = mesh.strip()
        if s.isdigit():  # bare device count: "--mesh 8"
            return max(1, int(s))
        n = 1
        for part in mesh.split(","):
            if not part.strip():
                continue
            _, sep, size = part.partition("=")
            if not sep or not size.strip():
                raise ValueError(
                    f"mesh axis {part!r} needs an explicit size for the "
                    f"capacity planner (e.g. 'data=8,model=2')"
                )
            v = int(size)
            if v <= 0:
                raise ValueError(
                    f"mesh axis sizes must be explicit positives for the "
                    f"capacity planner, got {part!r}"
                )
            n *= v
        return max(1, n)
    if isinstance(mesh, dict):
        n = 1
        for axis, v in mesh.items():
            v = int(v)
            if v <= 0:
                raise ValueError(
                    f"mesh axis {axis!r} size must be positive, got {v}"
                )
            n *= v
        return max(1, n)
    shape = getattr(mesh, "shape", None)  # jax Mesh duck-type
    if shape is not None:
        n = 1
        for v in dict(shape).values():
            n *= int(v)
        return max(1, n)
    raise TypeError(f"cannot derive a device count from mesh {mesh!r}")


def estimate_factors(
    users: int,
    items: int,
    k: int,
    dtype: str = "f32",
    mesh: Any = None,
    *,
    nnz: int | None = None,
    gather_dtype: str = "f32",
) -> CapacityEstimate:
    """Predict the per-device HBM footprint of an ALS train (see
    :class:`CapacityEstimate` for the model). Pure arithmetic — safe to
    call from ``pio doctor`` without a device in sight."""
    if users < 0 or items < 0 or k <= 0:
        raise ValueError(f"need users/items >= 0 and k > 0, got {users}/{items}/{k}")
    bpe = 2 if dtype == "bf16" else 4
    n_dev = _mesh_devices(mesh)
    user_table = (users + 1) * k * bpe
    item_table = (items + 1) * k * bpe
    factor = user_table + item_table
    if gather_dtype == "bf16":
        factor += (user_table + item_table) // 2  # bf16 gather copies
    e = max(users, items) + 1
    workspace = e * (k * k + k + 1) * 4 + 4 * e * k * 4
    wire = 2 * int(nnz) * 9 if nnz else 0
    total = factor + workspace + wire
    per_device = -(-total // n_dev)
    if n_dev > 1:
        # the gathered opposite side is resident in full on every device
        # during a half-solve — add the larger table once
        per_device += max(user_table, item_table)
    return CapacityEstimate(
        users=users,
        items=items,
        rank=k,
        dtype=dtype,
        gather_dtype=gather_dtype,
        n_devices=n_dev,
        nnz=nnz,
        factor_bytes=factor,
        workspace_bytes=workspace,
        wire_bytes=wire,
        total_bytes=total,
        per_device_bytes=int(per_device),
    )


def estimate_ann(
    items: int,
    dim: int,
    clusters: int = 0,
    nprobe: int = 0,
    *,
    quantize_int8: bool = False,
    batch: int = 64,
) -> dict[str, Any]:
    """Price a pinned ANN index's serving HBM next to the factor tables
    (``pio doctor --capacity ... --ann "clusters,nprobe"``; docs/ann.md).

    Model (mirrors ``ann/index.py``'s layout):

    - centroids ``[C, dim]`` f32;
    - bucket ids ``[C, cap]`` int32 + bucket vectors ``[C, cap, dim]``
      (f32, or int8 + a per-item f32 scale when quantized), with ``cap``
      the build's own capacity rule (``ann.index.bucket_capacity``: pow2
      of 2x the balanced mean — overflow spills to neighbor clusters
      instead of inflating every bucket);
    - a per-batch search transient: the gathered probe slabs
      ``[batch, nprobe, cap, dim]`` plus their score matrix — the term
      that actually bounds ``batch * nprobe``.

    The index is replicated per serving device (it answers point queries,
    it is not sharded), so every byte here is a per-device byte.
    """
    if items <= 0 or dim <= 0:
        raise ValueError(f"need items > 0 and dim > 0, got {items}/{dim}")
    from predictionio_tpu.ann.index import (
        bucket_capacity,
        default_clusters,
        default_nprobe,
    )

    clusters = clusters or default_clusters(items)
    clusters = max(1, min(clusters, items))
    nprobe = min(nprobe or default_nprobe(clusters), clusters)
    # the build's own capacity rule — estimate and artifact agree exactly
    cap = bucket_capacity(items, clusters)
    vec_elem = 1 if quantize_int8 else 4
    centroid_bytes = clusters * dim * 4
    bucket_bytes = clusters * cap * (dim * vec_elem + 4)
    if quantize_int8:
        bucket_bytes += clusters * cap * 4  # per-item f32 scales
    search_transient = batch * nprobe * cap * (dim * 4 + 8)
    total = centroid_bytes + bucket_bytes
    return {
        "items": items,
        "dim": dim,
        "clusters": clusters,
        "nprobe": nprobe,
        "bucketCap": cap,
        "quantized": quantize_int8,
        "centroidBytes": centroid_bytes,
        "bucketBytes": bucket_bytes,
        "searchTransientBytes": search_transient,
        "perDeviceBytes": total,
        "candidatesPerQuery": nprobe * cap,
        "candidateFrac": round(min(1.0, nprobe * cap / max(1, items)), 4),
    }


# ---------------------------------------------------------------------------
# sharding inspector
# ---------------------------------------------------------------------------

# HLO/StableHLO spellings of the cross-device collectives worth counting
_COLLECTIVES = (
    ("all_gather", ("all-gather", "all_gather")),
    ("all_reduce", ("all-reduce", "all_reduce")),
    ("reduce_scatter", ("reduce-scatter", "reduce_scatter")),
    ("collective_permute", ("collective-permute", "collective_permute")),
    ("all_to_all", ("all-to-all", "all_to_all")),
)


def count_collectives(hlo_text: str) -> dict[str, int]:
    """Count collective ops in a lowered/compiled module's text. Applied
    to the *compiled* (post-GSPMD) HLO this is the ground truth for "did
    the partitioner insert an all-gather I didn't plan"."""
    out: dict[str, int] = {}
    for name, spellings in _COLLECTIVES:
        n = 0
        for line in hlo_text.splitlines():
            # count op sites, not attribute mentions: an op line names the
            # op right after " = " (HLO) or as a stablehlo.<op> call. TPU
            # optimized HLO emits async pairs — count the -start op (the
            # matching -done carries no second collective)
            for sp in spellings:
                if (
                    f"= {sp}" in line
                    or f" {sp}(" in line
                    or f" {sp}-start(" in line
                    or f".{sp}" in line
                ):
                    n += 1
                    break
        if n:
            out[name] = n
    return out


def describe_shardings(tree: Any, prefix: str = "") -> list[dict[str, Any]]:
    """Flatten a pytree of jax arrays into placement records:
    ``{"name", "shape", "dtype", "bytes", "sharding", "devices",
    "replicated", "per_device_bytes"}``. ``replicated`` is only flagged
    when the array actually spans multiple devices — a single-device
    array is trivially "replicated" and would drown the signal."""
    import jax

    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    out: list[dict[str, Any]] = []
    for path, leaf in leaves_with_paths:
        if not hasattr(leaf, "sharding") or not hasattr(leaf, "nbytes"):
            continue
        name = prefix + jax.tree_util.keystr(path)
        sharding = leaf.sharding
        devices = len(getattr(sharding, "device_set", ()) or ()) or 1
        replicated = bool(
            devices > 1 and getattr(sharding, "is_fully_replicated", False)
        )
        nbytes = int(leaf.nbytes)
        per_device = nbytes if replicated else -(-nbytes // devices)
        spec = getattr(sharding, "spec", None)
        out.append(
            {
                "name": name or "<root>",
                "shape": list(leaf.shape),
                "dtype": str(leaf.dtype),
                "bytes": nbytes,
                "sharding": str(spec) if spec is not None else str(sharding),
                "devices": devices,
                "replicated": replicated,
                "per_device_bytes": per_device,
            }
        )
    return out


def find_replicated(
    entries: list[dict[str, Any]], min_bytes: int = 1 << 20
) -> list[dict[str, Any]]:
    """The flag list: fully-replicated arrays at or above ``min_bytes`` —
    on a pod these are per-device HBM spent on every chip for data that
    could be sharded."""
    return [
        e
        for e in entries
        if e.get("replicated") and e.get("bytes", 0) >= min_bytes
    ]


def inspect_train_step(
    jitted_fn: Any,
    *args: Any,
    replicated_min_bytes: int = 1 << 20,
    arg_names: tuple[str, ...] | None = None,
) -> dict[str, Any]:
    """One-stop inspector for a pjit'd train step: lower+compile (without
    executing — safe before a donating call), count post-partitioning
    collectives, and describe every input's placement. The dryrun embeds
    this report in ``MULTICHIP_r*.json``.

    Cost note: the AOT ``lower().compile()`` here does NOT seed the jit
    dispatch cache, so a caller that later invokes ``jitted_fn`` directly
    compiles the program a second time. Deliberate for a preflight
    inspector (tiny dryrun shapes, and the report must exist even if the
    step is never executed) — don't call this around a production train
    step you're about to run."""
    report: dict[str, Any] = {"collectives": {}, "arrays": [], "flags": []}
    try:
        lowered = jitted_fn.lower(*args)
        try:
            text = lowered.compile().as_text()
        except Exception:  # noqa: BLE001 - backends without HLO dumping
            text = lowered.as_text()
        report["collectives"] = count_collectives(text)
    except Exception as exc:  # noqa: BLE001 - inspection must not kill a train
        report["error"] = f"{type(exc).__name__}: {exc}"
    arrays: list[dict[str, Any]] = []
    for i, a in enumerate(args):
        name = arg_names[i] if arg_names and i < len(arg_names) else f"arg{i}"
        arrays.extend(describe_shardings(a, prefix=name))
    report["arrays"] = arrays
    for e in find_replicated(arrays, replicated_min_bytes):
        report["flags"].append(
            f"fully-replicated {e['bytes']} B array {e['name']} on "
            f"{e['devices']} devices — shard it or accept the per-chip cost"
        )
    return report


__all__ = [
    "TRAIN_PHASES",
    "PHASE_HOST_ETL",
    "PHASE_SWEEP",
    "PHASE_SOLVE",
    "PHASE_EVAL",
    "CapacityEstimate",
    "TrainProfile",
    "count_collectives",
    "current_profile",
    "describe_shardings",
    "device_fetch",
    "device_memory_stats",
    "estimate_ann",
    "estimate_factors",
    "find_replicated",
    "inspect_train_step",
    "live_array_bytes",
    "live_bytes_per_device",
    "phase",
    "register_train_metrics",
    "use_profile",
]
