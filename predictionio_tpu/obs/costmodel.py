"""Device-free XLA cost-analysis roofline for the registered jit buckets.

Every serving/training kernel family in this repo is a jitted program
with static shapes — which means XLA can *price* it without running it:
``jitted.lower(...).compile().cost_analysis()`` returns the compiler's
own flops and bytes-accessed accounting for the optimized HLO. This
module lowers one representative shape per registered bucket family
(ops/topk dot/gather/fused, ann search, twotower towers, als
sweep/solve), reads that accounting into per-kernel **arithmetic
intensity** (flops/byte), and projects it onto a device roofline
(``max(flops/peak_flops, bytes/peak_bw)``) to get a per-model
"device cost per 1k queries" in USD.

This runs entirely on the CPU backend — lowering + compiling never
touches a device — so every sandbox-measured claim in docs/PERF.md gains
an analytic device anchor *before* any hardware window opens (ROADMAP
item 5: "no hardware window is wasted"). ALX (PAPERS.md) sized its TPU
ALS from exactly this per-kernel flops/bytes accounting.

Consumers: ``pio doctor --roofline`` (JSON report), ``bench.py``'s
``roofline_*`` BENCH fields (gated by ``--compare``), and the PERF doc.
Imports jax lazily — the module is importable (and listable) from
stdlib-light CLI paths.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Peak envelope of one accelerator (or host) for the roofline
    projection. Peaks are dense bf16/f32 marketing peaks — the model
    prices the *floor* of device time, not a prediction of achieved
    time; measured utilization rides on top."""

    name: str
    peak_flops: float  # FLOP/s
    peak_bytes_per_s: float  # HBM (or DRAM) bandwidth, B/s
    usd_per_hour: float  # on-demand list price per device

    def to_json_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


# the devices this repo's claims are priced against; cpu-host is the
# sandbox floor (one modern server socket, DDR bandwidth) so the CPU
# numbers the CI measures can be read against the same model
DEVICE_SPECS: dict[str, DeviceSpec] = {
    "tpu-v4": DeviceSpec("tpu-v4", 275e12, 1.2e12, 3.22),
    "tpu-v5e": DeviceSpec("tpu-v5e", 197e12, 0.82e12, 1.20),
    "tpu-v5p": DeviceSpec("tpu-v5p", 459e12, 2.77e12, 4.20),
    "cpu-host": DeviceSpec("cpu-host", 1.0e12, 0.1e12, 0.40),
}
DEFAULT_DEVICE = "tpu-v4"


def _first_cost_dict(compiled) -> dict[str, float]:
    """``cost_analysis()`` returns a dict on some jax versions and a
    one-element list of dicts on others; normalize to a dict."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def _struct_bytes(tree) -> int:
    import jax

    return sum(
        int(math.prod(leaf.shape)) * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape")
    )


def _lower_cost(
    family: str,
    kernel: str,
    fn: Callable,
    args: tuple,
    static_kwargs: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Lower+compile one jitted bucket at its representative shape and
    read the compiler's cost accounting. ``bytesAccessed`` falls back to
    arg+out buffer sizes when the backend omits it (a lower bound: every
    operand crosses memory at least once)."""
    import jax

    static_kwargs = static_kwargs or {}
    lowered = fn.lower(*args, **static_kwargs)
    compiled = lowered.compile()
    ca = _first_cost_dict(compiled)
    arg_bytes = _struct_bytes(args)
    # jitted-fn eval_shape respects static_argnames (the plain
    # jax.eval_shape would trace the static kwargs as abstract values)
    out_bytes = _struct_bytes(fn.eval_shape(*args, **static_kwargs))
    flops = float(ca.get("flops", 0.0))
    bytes_accessed = float(ca.get("bytes accessed", 0.0))
    if not math.isfinite(flops) or flops < 0:
        flops = 0.0
    if not math.isfinite(bytes_accessed) or bytes_accessed <= 0:
        bytes_accessed = float(arg_bytes + out_bytes)
    return {
        "family": family,
        "kernel": kernel,
        "flops": flops,
        "bytesAccessed": bytes_accessed,
        "argBytes": arg_bytes,
        "outBytes": out_bytes,
        "arithmeticIntensity": flops / max(bytes_accessed, 1.0),
    }


# --------------------------------------------------------------- families
# Each builder returns (kernel cost dicts, queries-per-invocation of the
# family's headline kernel — the unit the per-1k-queries price is in).
# Shapes are small but structurally faithful (the masked matmul, the
# flattened-slab ann gather, the blocked ALS normal equations): cost
# *ratios* and arithmetic intensity are shape-stable, and small shapes
# keep the CPU compile under a second per kernel.


def topk_costs(
    *, n: int = 4096, f: int = 32, b: int = 32, q: int = 8, k: int = 10
) -> tuple[list[dict[str, Any]], int]:
    """The fused score->mask->top-k serving bucket (ops/topk)."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops import topk as T

    S = jax.ShapeDtypeStruct
    table = S((n, f), jnp.float32)
    vecs = S((b, f), jnp.float32)
    mask = S((b, n), jnp.bool_)
    weights = S((n,), jnp.float32)
    qidx = S((b, q), jnp.int32)
    qweight = S((b, q), jnp.float32)
    scores = S((b, n), jnp.float32)
    recipes = [
        ("dot_top_k", T._dot_top_k, (table, vecs, mask)),
        ("dot_top_k_unmasked", T._dot_top_k_unmasked, (table, vecs)),
        ("dot_top_k_weighted", T._dot_top_k_weighted, (table, vecs, mask, weights)),
        ("gather_sum_top_k", T._gather_sum_top_k, (table, qidx, qweight, mask)),
        (
            "gather_sum_top_k_weighted",
            T._gather_sum_top_k_weighted,
            (table, qidx, qweight, mask, weights),
        ),
        ("mask_top_k", T._mask_top_k, (scores, mask)),
    ]
    return [
        _lower_cost("topk", name, fn, args, {"k": k})
        for name, fn, args in recipes
    ], b


def ann_costs(
    *,
    c: int = 64,
    cap: int = 32,
    f: int = 32,
    b: int = 32,
    nprobe: int = 4,
    k: int = 10,
) -> tuple[list[dict[str, Any]], int]:
    """The clustered ANN probe->gather->score->top-k bucket (ann/search)."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ann import search as ann_search

    search, _excl, _masked, _q8 = ann_search._kernels()
    S = jax.ShapeDtypeStruct
    args = (
        S((c, f), jnp.float32),  # centroids
        S((c, cap * f), jnp.float32),  # bucket_flat
        S((c, cap), jnp.int32),  # bucket_ids
        S((b, f), jnp.float32),  # queries
    )
    return [
        _lower_cost("ann", "search", search, args, {"nprobe": nprobe, "k": k})
    ], b


def als_costs(
    *,
    rank: int = 16,
    n_users: int = 64,
    n_items: int = 64,
    nb: int = 32,
    d: int = 8,
    block_chunk: int = 8,
) -> tuple[list[dict[str, Any]], int]:
    """The blocked ALS sweep (both half-steps) and the batched SPD solve
    it is built on (ops/als)."""
    import functools

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops import als as A

    S = jax.ShapeDtypeStruct
    step_args = (
        S((n_users + 1, rank), jnp.float32),
        S((n_items + 1, rank), jnp.float32),
        S((nb,), jnp.int32),
        S((nb, d), jnp.int32),
        S((nb, d), jnp.float32),
        S((nb, d), jnp.int8),
        S((nb,), jnp.int32),
        S((nb, d), jnp.int32),
        S((nb, d), jnp.float32),
        S((nb, d), jnp.int8),
    )
    step_kwargs = {
        "n_users": n_users,
        "n_items": n_items,
        "reg": 0.05,
        "implicit": False,
        "alpha": 40.0,
        "block_chunk": block_chunk,
        "degree_scaled_reg": True,
        "solver": "cg",
        "gather_dtype": "f32",
    }
    solve = jax.jit(functools.partial(A._batched_spd_solve, solver="cg"))
    solve_args = (
        S((n_users, rank, rank), jnp.float32),
        S((n_users, rank), jnp.float32),
    )
    costs = [
        _lower_cost("als", "als_step", A._als_step, step_args, step_kwargs),
        _lower_cost("als", "spd_solve_cg", solve, solve_args),
    ]
    return costs, n_users + n_items  # rows re-solved per sweep


def twotower_costs(
    *,
    n_users: int = 128,
    n_items: int = 256,
    embed_dim: int = 32,
    hidden: tuple[int, ...] = (64,),
    out_dim: int = 16,
    b: int = 32,
) -> tuple[list[dict[str, Any]], int]:
    """The two-tower serving encoders (models/twotower): params come from
    ``jax.eval_shape`` over init — no real initialization runs."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.twotower.model import TwoTower, TwoTowerConfig

    cfg = TwoTowerConfig(
        n_users=n_users,
        n_items=n_items,
        embed_dim=embed_dim,
        hidden=hidden,
        out_dim=out_dim,
    )
    model = TwoTower(config=cfg)
    S = jax.ShapeDtypeStruct
    ids = S((b,), jnp.int32)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids, ids)
    user_fn = jax.jit(
        lambda p, u: model.apply(p, u, method=TwoTower.embed_users)
    )
    item_fn = jax.jit(
        lambda p, i: model.apply(p, i, method=TwoTower.embed_items)
    )
    return [
        _lower_cost("twotower", "embed_users", user_fn, (params, ids)),
        _lower_cost("twotower", "embed_items", item_fn, (params, ids)),
    ], b


FAMILY_BUILDERS: dict[str, Callable[[], tuple[list[dict[str, Any]], int]]] = {
    "topk": topk_costs,
    "ann": ann_costs,
    "als": als_costs,
    "twotower": twotower_costs,
}


# ---------------------------------------------------------------- roofline
def roofline_time_s(cost: dict[str, Any], spec: DeviceSpec) -> dict[str, Any]:
    """Roofline floor for one kernel invocation on ``spec``: the larger
    of compute time and memory time, with which wall it hit."""
    t_compute = cost["flops"] / spec.peak_flops
    t_memory = cost["bytesAccessed"] / spec.peak_bytes_per_s
    return {
        "modelTimeS": max(t_compute, t_memory),
        "bound": "compute" if t_compute >= t_memory else "memory",
        "ridgeIntensity": spec.peak_flops / spec.peak_bytes_per_s,
    }


def analyze(
    families: list[str] | None = None,
    device: str | DeviceSpec = DEFAULT_DEVICE,
) -> dict[str, Any]:
    """The full report behind ``pio doctor --roofline``: per-kernel
    flops/bytes/AI + roofline projection, per-family totals, and the
    per-1k-queries device price. A family whose lowering fails records
    an ``errors`` entry instead of sinking the report."""
    spec = DEVICE_SPECS[device] if isinstance(device, str) else device
    report: dict[str, Any] = {
        "device": spec.to_json_dict(),
        "families": {},
        "errors": {},
    }
    for fam in families or list(FAMILY_BUILDERS):
        try:
            kernels, batch = FAMILY_BUILDERS[fam]()
        except Exception as exc:  # noqa: BLE001 - report the rest regardless
            report["errors"][fam] = f"{type(exc).__name__}: {exc}"
            continue
        for cost in kernels:
            cost.update(roofline_time_s(cost, spec))
        total_flops = sum(c["flops"] for c in kernels)
        total_bytes = sum(c["bytesAccessed"] for c in kernels)
        # the family's headline kernel (first recipe) is the per-query
        # serving program; its roofline floor prices a query batch
        head = kernels[0]
        per_query_s = head["modelTimeS"] / max(batch, 1)
        report["families"][fam] = {
            "kernels": kernels,
            "batch": batch,
            "totalFlops": total_flops,
            "totalBytes": total_bytes,
            "arithmeticIntensity": total_flops / max(total_bytes, 1.0),
            "perQueryModelTimeS": per_query_s,
            "costPer1kQueriesUsd": per_query_s
            * 1000.0
            * (spec.usd_per_hour / 3600.0),
        }
    return report


def bench_fields(
    families: list[str] | None = None,
    device: str | DeviceSpec = DEFAULT_DEVICE,
) -> dict[str, Any]:
    """Flatten :func:`analyze` into the ``roofline_*`` BENCH JSON fields
    (shared by ``bench.py`` and the contract tests): per family, total
    gigaflops/megabytes, arithmetic intensity, and the per-1k-queries
    price; plus the device the projection priced against."""
    report = analyze(families=families, device=device)
    fields: dict[str, Any] = {"roofline_device": report["device"]["name"]}
    for fam, entry in report["families"].items():
        fields[f"roofline_{fam}_gflops"] = round(entry["totalFlops"] / 1e9, 6)
        fields[f"roofline_{fam}_mbytes"] = round(entry["totalBytes"] / 1e6, 6)
        fields[f"roofline_{fam}_ai"] = round(entry["arithmeticIntensity"], 4)
        fields[f"roofline_{fam}_cost_per_1k_usd"] = round(
            entry["costPer1kQueriesUsd"], 10
        )
    for fam, err in report["errors"].items():
        fields[f"roofline_{fam}_error"] = err[:200]
    return fields


__all__ = [
    "DEFAULT_DEVICE",
    "DEVICE_SPECS",
    "DeviceSpec",
    "FAMILY_BUILDERS",
    "analyze",
    "als_costs",
    "ann_costs",
    "bench_fields",
    "roofline_time_s",
    "topk_costs",
    "twotower_costs",
]
