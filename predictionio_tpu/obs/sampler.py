"""Always-on host sampling profiler: folded stacks by thread role.

The device trace (``obs/profiler``) answers "where did the *device* time
go"; this module answers the other half — where the *host* threads are
when a query is slow. A daemon thread wakes every ``period_s`` and walks
``sys._current_frames()``, attributing each thread's stack to a serving
role (event loop / dispatch / fetch / shadow / stream / sniffer) through
a thread-*name* registry — the serving stack already names its workers
``pio-dispatch``, ``pio-fetch``, ``pio-shadow``, ``pio-sniffer``,
``pio-stream`` (see ``workflow/create_server.py``), so attribution costs
one prefix match, no instrumentation in the hot path.

Samples aggregate into **folded stacks** (the flamegraph interchange
format: ``role;frame;frame;leaf count`` per line, leaf last) inside a
bounded window ring: the current window rotates every ``window_s`` and
the ring keeps the newest ``ring_windows`` windows, so ``snapshot()``
always covers roughly the last ``ring_windows * window_s`` seconds with
hard memory bounds (``max_stacks`` distinct stacks per window; overflow
collapses into a ``<other>`` leaf rather than growing).

The sampler measures its own cost: every sampling pass's wall time
accumulates into a busy counter, and ``overhead_frac()`` = busy / elapsed
is exported as the ``pio_profile_sampler_overhead_frac`` gauge — the
"always-on" claim is held by measurement (<1% CPU at the default 20 Hz
period; asserted in ``tests/test_profiler.py``).

Stdlib-only — the event server, ``pio top``, and the fleet gateway use
this without dragging in an accelerator runtime.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable

# thread-name prefix -> role, first match wins (checked in order). The
# names are the contract: serving/fleet threads are created with these
# prefixes, and MainThread is by convention the asyncio event loop in
# every server process this repo starts.
DEFAULT_ROLES: tuple[tuple[str, str], ...] = (
    ("pio-dispatch", "dispatch"),
    ("pio-fetch", "fetch"),
    ("pio-shadow", "shadow"),
    ("pio-sniffer", "sniffer"),
    ("pio-stream", "stream"),
    ("pio-sampler", "sampler"),
    ("MainThread", "event-loop"),
    ("asyncio_", "executor"),  # run_in_executor default pool workers
    ("ThreadPoolExecutor", "executor"),
)

OTHER_LEAF = "<other>"


def _frame_label(frame) -> str:
    """Compact ``module.function`` frame label (file basename, no .py):
    stable across hosts — absolute paths and line numbers would make the
    folded key differ per checkout and defeat aggregation."""
    code = frame.f_code
    mod = os.path.basename(code.co_filename)
    if mod.endswith(".py"):
        mod = mod[:-3]
    return f"{mod}.{code.co_name}"


class HostSampler:
    """Interval stack sampler with role attribution and a window ring.

    Thread-safe; ``start()``/``stop()`` are idempotent. All reads
    (``snapshot``, ``folded``, ``hotspots``, ``overhead_frac``) are safe
    while sampling runs.
    """

    def __init__(
        self,
        period_s: float = 0.05,
        *,
        max_depth: int = 40,
        max_stacks: int = 512,
        window_s: float = 60.0,
        ring_windows: int = 5,
        roles: tuple[tuple[str, str], ...] = DEFAULT_ROLES,
        metrics: Any | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.period_s = max(0.001, float(period_s))
        self.max_depth = int(max_depth)
        self.max_stacks = int(max_stacks)
        self.window_s = float(window_s)
        self._roles = tuple(roles)
        self._clock = clock
        self._lock = threading.Lock()
        self._window: dict[str, int] = {}
        self._ring: deque[dict[str, int]] = deque(maxlen=max(1, ring_windows))
        self._window_started = clock()
        self._started_at: float | None = None
        self._busy_s = 0.0
        self._samples = 0
        self._truncated = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        if metrics is not None:
            self._m_samples = metrics.counter(
                "pio_profile_sampler_samples_total",
                "host sampling passes taken by the always-on stack sampler",
            )
            self._m_overhead = metrics.gauge(
                "pio_profile_sampler_overhead_frac",
                "self-measured sampler cost: sampling wall time / elapsed "
                "wall time since start (the <1% always-on budget)",
            )
            self._m_overhead.set_function(self.overhead_frac)
            self._m_stacks = metrics.gauge(
                "pio_profile_sampler_stacks",
                "distinct folded stacks currently held across the window "
                "ring (bounded by max_stacks per window)",
            )
            self._m_stacks.set_function(lambda: float(len(self._merged())))
        else:
            self._m_samples = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._started_at = self._clock()
            self._busy_s = 0.0
            self._thread = threading.Thread(
                target=self._run, name="pio-sampler", daemon=True
            )
            self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        thread = self._thread
        self._stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
        self._thread = None

    @property
    def running(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - the sampler must never die
                pass

    # ------------------------------------------------------------- sampling
    def role_of(self, thread_name: str) -> str:
        for prefix, role in self._roles:
            if thread_name.startswith(prefix):
                return role
        return "other"

    def sample_once(self) -> int:
        """One sampling pass over every live thread except the sampler
        itself; returns the number of stacks recorded. Public so tests
        (and the bench overhead probe) can drive it deterministically."""
        t0 = time.perf_counter()
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        recorded = 0
        folded_keys: list[str] = []
        for ident, frame in frames.items():
            if ident == me:
                continue
            role = self.role_of(names.get(ident, "?"))
            if role == "sampler":
                continue
            stack: list[str] = []
            depth = 0
            while frame is not None and depth < self.max_depth:
                stack.append(_frame_label(frame))
                frame = frame.f_back
                depth += 1
            stack.reverse()  # folded format: root first, leaf last
            folded_keys.append(role + ";" + ";".join(stack))
            recorded += 1
        busy = time.perf_counter() - t0
        now = self._clock()
        with self._lock:
            if now - self._window_started >= self.window_s and self._window:
                self._ring.append(self._window)
                self._window = {}
                self._window_started = now
            for key in folded_keys:
                if key in self._window or len(self._window) < self.max_stacks:
                    self._window[key] = self._window.get(key, 0) + 1
                else:
                    # bounded: collapse overflow under the role's <other>
                    role = key.split(";", 1)[0]
                    other = f"{role};{OTHER_LEAF}"
                    self._window[other] = self._window.get(other, 0) + 1
                    self._truncated += 1
            self._busy_s += busy
            self._samples += 1
        if self._m_samples is not None:
            self._m_samples.inc()
        return recorded

    # --------------------------------------------------------------- views
    def _merged(self) -> dict[str, int]:
        with self._lock:
            windows = list(self._ring) + [self._window]
        merged: dict[str, int] = {}
        for window in windows:
            for key, count in window.items():
                merged[key] = merged.get(key, 0) + count
        return merged

    def overhead_frac(self) -> float:
        with self._lock:
            started, busy = self._started_at, self._busy_s
        if started is None:
            return 0.0
        elapsed = self._clock() - started
        if elapsed <= 0:
            return 0.0
        return busy / elapsed

    def snapshot(self) -> dict[str, Any]:
        """The ``GET /profile/stacks?format=json`` payload: folded stacks
        plus role totals and the self-measured overhead."""
        merged = self._merged()
        roles: dict[str, int] = {}
        for key, count in merged.items():
            role = key.split(";", 1)[0]
            roles[role] = roles.get(role, 0) + count
        with self._lock:
            samples, truncated = self._samples, self._truncated
        return {
            "periodS": self.period_s,
            "samples": samples,
            "truncated": truncated,
            "overheadFrac": self.overhead_frac(),
            "roles": roles,
            "stacks": merged,
        }

    def folded(self) -> str:
        """Flamegraph-ready folded text: ``stack count`` lines, hottest
        first — pipe straight into ``flamegraph.pl`` or speedscope."""
        merged = self._merged()
        lines = [
            f"{key} {count}"
            for key, count in sorted(merged.items(), key=lambda kv: -kv[1])
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def hotspots(self, top_n: int = 3) -> dict[str, list[dict[str, Any]]]:
        """Per-role top leaf frames (the ``pio top --hotspots`` table):
        role -> [{"frame": leaf, "count": n, "frac": of-role}, ...]."""
        merged = self._merged()
        by_role: dict[str, dict[str, int]] = {}
        totals: dict[str, int] = {}
        for key, count in merged.items():
            role, _, rest = key.partition(";")
            leaf = rest.rsplit(";", 1)[-1] if rest else OTHER_LEAF
            by_role.setdefault(role, {})
            by_role[role][leaf] = by_role[role].get(leaf, 0) + count
            totals[role] = totals.get(role, 0) + count
        out: dict[str, list[dict[str, Any]]] = {}
        for role, leaves in by_role.items():
            total = totals[role] or 1
            ranked = sorted(leaves.items(), key=lambda kv: -kv[1])[:top_n]
            out[role] = [
                {"frame": leaf, "count": count, "frac": round(count / total, 4)}
                for leaf, count in ranked
            ]
        return out


__all__ = ["DEFAULT_ROLES", "HostSampler", "OTHER_LEAF"]
